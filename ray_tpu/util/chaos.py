"""Chaos harness: reusable fault injection for FT tests and examples.

The fault-injection plane SURVEY §5.3 calls for: process kills at the OS
level (`kill_head`, `kill_worker_host`) and network faults at the RPC
socket layer (`partition`), usable from pytest (`-m chaos`) and from
`examples/pod_cluster.py` / `examples/head_chaos.py` alike.

Process kills are real SIGKILLs — no cooperation from the victim, exactly
what a machine failure looks like to the rest of the cluster. Partitions
install a process-wide hook consulted by `core.wire` before every frame
send/recv in THIS process (`wire.set_fault_injector`): "drop" raises
OSError, which the reconnecting client treats as a lost connection;
"delay" sleeps, simulating a slow link. The hook blocks FRAMES, not TCP
connects — a reconnect dial during a drop partition succeeds but its
first roundtrip fails, so the process stays partitioned until heal.

    from ray_tpu.util import chaos

    chaos.kill_head(head_proc)                      # SIGKILL + reap

    with chaos.partition(duration_s=3.0):           # all wire traffic
        ...                                         # heals on exit

    with chaos.partition(addresses={"10.0.0.7:6399"}, mode="delay",
                         delay_s=0.5):
        ...                                         # slow one peer
"""

from __future__ import annotations

import contextlib
import os
import signal
import threading
import time
from typing import Iterator, Optional, Set

from ..core.logging import get_logger
from ..core import wire

logger = get_logger("chaos")


def _pid_of(proc) -> int:
    """Accepts a subprocess.Popen, multiprocessing.Process, or raw pid."""
    return proc if isinstance(proc, int) else proc.pid


def _kill(proc, wait_s: float) -> int:
    pid = _pid_of(proc)
    try:
        os.kill(pid, signal.SIGKILL)
    except ProcessLookupError:
        return pid  # already gone
    # reap if we own it, so the fixture does not leak a zombie
    waiter = getattr(proc, "wait", None)
    if waiter is not None:
        try:
            waiter(timeout=wait_s)
        except TypeError:
            waiter(wait_s)  # multiprocessing.Process.join-style signature
        except Exception:  # noqa: BLE001 — reaping is best-effort
            pass
    return pid


def kill_head(proc, wait_s: float = 10.0) -> int:
    """SIGKILL the head OS process (no cleanup runs — its sockets close
    via the kernel, which is what triggers client reconnects). Returns
    the pid. The caller restarts with ``init(resume_from=...)``."""
    pid = _kill(proc, wait_s)
    logger.warning("chaos: killed head pid %d", pid)
    return pid


def kill_worker_host(proc, wait_s: float = 10.0) -> int:
    """SIGKILL a joined worker-host process; the head reaps it via the
    stale-heartbeat sweep (health_check_timeout_ms). Returns the pid."""
    pid = _kill(proc, wait_s)
    logger.warning("chaos: killed worker host pid %d", pid)
    return pid


class _Fault:
    """The installed wire hook: one active fault per process (last wins)."""

    def __init__(self, mode: str, delay_s: float,
                 addresses: Optional[Set[str]], until: Optional[float]):
        self.mode = mode
        self.delay_s = delay_s
        self.addresses = addresses
        self.until = until
        self.healed = threading.Event()

    def _matches(self, sock) -> bool:
        if self.addresses is None:
            return True
        try:
            host, port = sock.getpeername()[:2]
        except OSError:
            return False
        return f"{host}:{port}" in self.addresses

    def __call__(self, sock, kind: str) -> None:
        if self.healed.is_set():
            return
        if self.until is not None and time.monotonic() >= self.until:
            self.healed.set()
            return
        if not self._matches(sock):
            return
        if self.mode == "delay":
            time.sleep(self.delay_s)
            return
        raise OSError(f"injected partition ({kind})")


def node_addresses(control_plane, node_id) -> Set[str]:
    """Resolve a node's advertised addresses (dispatch + transfer +
    channel service) from the control-plane KV, for address-scoped
    partitions. Accepts a NodeID or its hex string."""
    hexid = node_id if isinstance(node_id, str) else node_id.hex()
    addrs: Set[str] = set()
    for prefix in ("node_service/", "object_transfer/", "channel_service/"):
        val = control_plane.kv_get(prefix + hexid)
        if val:
            addrs.add(val.decode() if isinstance(val, bytes) else val)
    return addrs


@contextlib.contextmanager
def partition(node_id=None, duration_s: Optional[float] = None,
              mode: str = "drop", delay_s: float = 0.25,
              control_plane=None,
              addresses: Optional[Set[str]] = None) -> Iterator[_Fault]:
    """Partition THIS process at the RPC socket layer.

    - ``node_id`` + ``control_plane``: scope the fault to that node's
      KV-advertised addresses (see `node_addresses`).
    - ``addresses``: scope to an explicit ``{"host:port", ...}`` set.
    - neither: every wire frame in this process faults.
    - ``mode="drop"`` raises OSError per frame (connection-loss path);
      ``mode="delay"`` sleeps ``delay_s`` per frame (slow-link path).
    - ``duration_s``: auto-heal after this long; otherwise heals when the
      context exits.
    """
    if mode not in ("drop", "delay"):
        raise ValueError(f"unknown partition mode {mode!r}")
    if node_id is not None:
        if control_plane is None:
            raise ValueError("node_id-scoped partition needs control_plane")
        addresses = node_addresses(control_plane, node_id)
    until = None if duration_s is None else time.monotonic() + duration_s
    fault = _Fault(mode, delay_s, addresses, until)
    wire.set_fault_injector(fault)
    logger.warning("chaos: partition on (%s, mode=%s)",
                   "all" if addresses is None else sorted(addresses), mode)
    try:
        yield fault
        if duration_s is not None and not fault.healed.is_set():
            # the caller asked for a timed partition: hold until it expires
            remaining = until - time.monotonic()
            if remaining > 0:
                time.sleep(remaining)
    finally:
        fault.healed.set()
        wire.set_fault_injector(None)
        logger.warning("chaos: partition healed")
