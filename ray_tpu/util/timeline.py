"""Task-event timeline: chrome-trace export + JAX profiler integration.

Reference analogue: `src/ray/gcs/gcs_task_manager.cc` (task event buffer)
surfaced by `ray timeline` (`python/ray/scripts`), which dumps a
chrome://tracing JSON of task lifetimes. Here the runtime records
submit/start/finish transitions into a bounded ring buffer, application
code can add named spans (the trainer marks each train step), and
``ray_tpu.timeline("out.json")`` writes a Perfetto-loadable trace with
both planes: runtime tasks (one track per node) and app spans.

For the device plane, ``trace_jax(logdir)`` wraps ``jax.profiler.trace``:
XLA's xplane capture lands in ``logdir`` and loads in the same Perfetto UI
(tensorboard profile plugin format) — the TPU-native differentiator the
reference lacks (SURVEY §5.1).
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..core.config import config

_lock = threading.Lock()
_events: "deque[Dict[str, Any]]" = deque(maxlen=10_000)
_total = 0  # events ever recorded (monotone; the ring may have dropped some)
_t0_us = time.time() * 1e6 - time.perf_counter() * 1e6


def _now_us() -> float:
    return _t0_us + time.perf_counter() * 1e6


def configure() -> None:
    """Resize the ring to the configured bound (called lazily on record)."""
    global _events
    cap = int(config.task_events_max_buffer)
    if _events.maxlen != cap:
        with _lock:
            _events = deque(_events, maxlen=cap)


def record(
    name: str,
    ph: str,
    cat: str = "task",
    ts_us: Optional[float] = None,
    dur_us: Optional[float] = None,
    pid: str = "runtime",
    tid: str = "0",
    args: Optional[Dict[str, Any]] = None,
) -> None:
    """Append one chrome-trace event. ph: 'X' complete, 'i' instant."""
    configure()
    ev: Dict[str, Any] = {
        "name": name,
        "cat": cat,
        "ph": ph,
        "ts": ts_us if ts_us is not None else _now_us(),
        "pid": pid,
        "tid": tid,
    }
    if dur_us is not None:
        ev["dur"] = dur_us
    if args:
        ev["args"] = args
    global _total
    with _lock:
        _events.append(ev)
        _total += 1


def drain_since(cursor: int) -> Tuple[int, List[Dict[str, Any]]]:
    """Events recorded after `cursor` (a value this function previously
    returned; start at 0) plus the new cursor. Read-only: the caller owns
    the cursor, so a failed telemetry flush retries with the old one."""
    with _lock:
        dropped = _total - len(_events)
        start = max(0, cursor - dropped)
        return _total, [dict(ev) for ev in list(_events)[start:]]


def ingest(events: List[Dict[str, Any]], lane: str) -> int:
    """Merge events flushed from another process into this buffer (head
    side of telemetry federation). Each event's pid becomes
    '<lane>/<orig pid>' so the merged chrome-trace shows one process
    group per source node. Returns the number added."""
    if not events:
        return 0
    configure()
    global _total
    with _lock:
        for ev in events:
            ev = dict(ev)
            ev["pid"] = f"{lane}/{ev.get('pid', '?')}"
            _events.append(ev)
            _total += 1
    return len(events)


@contextlib.contextmanager
def span(name: str, cat: str = "app", pid: str = "app", tid: str = "0",
         args: Optional[Dict[str, Any]] = None):
    """Record a named span around a code block (e.g. one train step)."""
    t0 = _now_us()
    try:
        yield
    finally:
        record(name, "X", cat=cat, ts_us=t0, dur_us=_now_us() - t0,
               pid=pid, tid=tid, args=args)


def clear() -> None:
    with _lock:
        _events.clear()


def export(path: str) -> int:
    """Write the buffered events as chrome://tracing / Perfetto JSON.
    Returns the number of events written."""
    with _lock:
        events = list(_events)
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {"tool": "ray_tpu.timeline", "exported_at": time.time()},
    }
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(events)


@contextlib.contextmanager
def trace_jax(logdir: str):
    """Capture an XLA device trace (xplane) alongside the task timeline.
    Load the logdir in Perfetto / tensorboard's profile plugin."""
    import jax

    with jax.profiler.trace(logdir):
        yield
