"""State API (reference: `python/ray/util/state/api.py` — `ray list
actors/tasks/nodes`, `ray summary`): queryable cluster state with filters,
plus a Prometheus metrics HTTP endpoint (dashboard-lite: the reference's
observability planes without the React app, per SURVEY.md §7.5)."""

from __future__ import annotations

import threading
from dataclasses import asdict
from typing import Any, Dict, List, Optional, Tuple

from .. import api
from ..core.metrics import registry as metrics_registry

Filter = Tuple[str, str, Any]  # (key, "=" | "!=", value)


def _apply_filters(rows: List[Dict[str, Any]], filters) -> List[Dict[str, Any]]:
    for key, op, value in filters or []:
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
        else:
            raise ValueError(f"unsupported filter op {op!r}")
    return rows


def list_nodes(filters=None, limit: int = 100) -> List[Dict[str, Any]]:
    rt = api._auto_init()
    rows = []
    for n in rt.control_plane.all_nodes():
        rows.append({
            "node_id": n.node_id.hex()[:16],
            "state": n.state.name,
            "resources_total": dict(n.resources_total),
            "resources_available": dict(n.resources_available),
            "labels": dict(n.labels or {}),
        })
    return _apply_filters(rows, filters)[:limit]


def list_actors(filters=None, limit: int = 100) -> List[Dict[str, Any]]:
    rt = api._auto_init()
    rows = []
    for a in rt.control_plane.list_actors():
        rows.append({
            "actor_id": a.actor_id.hex()[:16],
            "class_name": a.class_name,
            "state": a.state.name,
            "name": a.name or "",
            "node_id": a.node_id.hex()[:16] if a.node_id else "",
            "restarts": a.num_restarts,
        })
    return _apply_filters(rows, filters)[:limit]


def list_jobs(filters=None, limit: int = 100) -> List[Dict[str, Any]]:
    rt = api._auto_init()
    rows = [
        {"job_id": j.hex()[:16], **meta}
        for j, meta in rt.control_plane.list_jobs().items()
    ]
    return _apply_filters(rows, filters)[:limit]


def list_objects(filters=None, limit: int = 100) -> List[Dict[str, Any]]:
    """Cluster-wide object rows from the federated ledger: local stores
    snapshotted now, joined hosts from their latest telemetry snapshot,
    each row carrying location set / refcount / pin reason / age."""
    rt = api._auto_init()
    from ..core import object_ledger

    rows = []
    for r in object_ledger.collect_objects(rt, limit=10_000)["objects"]:
        rows.append({
            "object_id": r.get("object_id", "")[:16],
            "node_id": r.get("node_id", "")[:16],
            "size_bytes": r.get("size_bytes", 0),
            "store": r.get("store", ""),
            "pin_reason": r.get("pin_reason", ""),
            "refcount": r.get("refcount", 0),
            "locations": ",".join(r.get("locations", [])),
            "age_s": round(float(r.get("age_s", 0.0)), 1),
            "creator_task": r.get("creator_task", ""),
        })
    return _apply_filters(rows, filters)[:limit]


def summary() -> Dict[str, Any]:
    rt = api._auto_init()
    actors = list_actors(limit=10_000)
    by_state: Dict[str, int] = {}
    for a in actors:
        by_state[a["state"]] = by_state.get(a["state"], 0) + 1
    utilization: Dict[str, Dict[str, float]] = {}
    try:
        # per-node CPU/RSS next to the memory fraction (profiling plane):
        # same source the health payload reads, so /api/v0/summary and
        # /api/v0/health agree
        from ..core.health import get_health_plane

        plane = get_health_plane(create=False)
        if plane is not None:
            utilization, _ = plane._profiling_sections(plane._cp())
        else:
            from . import profiler
            row = profiler.update_resource_gauges()
            utilization = {"head": {
                "cpu_fraction": row.get("host_cpu_used_fraction", 0.0),
                "rss_bytes": row.get("process_rss_bytes", 0.0),
            }}
    except Exception:  # noqa: BLE001 — summary must render regardless
        pass
    return {
        "nodes_alive": len(rt.control_plane.alive_nodes()),
        "nodes_total": len(rt.control_plane.all_nodes()),
        "actors_by_state": by_state,
        "cluster_resources": api.cluster_resources(),
        "available_resources": api.available_resources(),
        "utilization": utilization,
    }


# ---------------------------------------------------------------------------
# Metrics endpoint (per-node agent's /metrics in the reference)
# ---------------------------------------------------------------------------

_metrics_server = None


def start_metrics_server(host: str = "127.0.0.1", port: int = 0) -> int:
    """Serve the process metrics registry as Prometheus text. -> bound port."""
    global _metrics_server
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path not in ("/metrics", "/"):
                self.send_response(404)
                self.end_headers()
                return
            body = metrics_registry.render_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    _metrics_server = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=_metrics_server.serve_forever, daemon=True)
    t.start()
    return _metrics_server.server_address[1]


def stop_metrics_server() -> None:
    global _metrics_server
    if _metrics_server is not None:
        _metrics_server.shutdown()
        _metrics_server.server_close()
        _metrics_server = None
