"""Runtime concurrency sanitizer: instrumented locks + thread hygiene.

Layer 2 of the correctness-tooling plane (layer 1 is the static linter,
`ray_tpu.tools.raylint`). Env-gated — `RAY_TPU_SANITIZE=1` makes
`ray_tpu` swap `threading.Lock`/`threading.RLock` for tracked wrappers
at import time (`maybe_install()`), so every lock the framework creates
afterwards feeds two detectors:

* **Lock-order graph.** Each acquisition while other locks are held adds
  a held→acquired edge to a per-process directed graph. A new edge that
  closes a cycle means two code paths take the same locks in opposite
  orders — a potential deadlock, reported the first time the cycle is
  observed even if the interleaving never actually deadlocks.
* **Hold-time budget.** Releasing a lock held longer than
  `config.sanitize_hold_ms` (blocking work under a lock — the raylint R2
  class, caught dynamically) records a violation with the lock's
  creation site and the measured hold.

Reports go to the flight recorder (`kind="sanitizer"`, so they land in
crash postmortems), the `sanitizer_reports_total` counter, the logger,
and a bounded in-memory list (`reports()`) that tests assert against.

Off (the default) nothing is patched and the stock primitives are used:
zero overhead. The wrappers keep the `Condition` protocol
(`_is_owned`/`_acquire_restore`/`_release_save`) so `threading.Condition`,
`Event`, `Semaphore`, and `queue.Queue` built on patched primitives keep
working.

Thread hygiene (`thread_snapshot`/`check_thread_leaks`) backs the
conftest fixture that fails tests leaking non-daemon threads or showing
runaway daemon-thread growth.
"""

from __future__ import annotations

import _thread
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

from ..core.config import config
from ..core.logging import get_logger
from ..core.metrics import Counter
from . import flight_recorder

__all__ = [
    "install", "uninstall", "maybe_install", "installed", "reports",
    "clear_reports", "thread_snapshot", "check_thread_leaks",
]

logger = get_logger("sanitizer")

# saved at import time, before any patching
_real_allocate = _thread.allocate_lock
_real_Lock = threading.Lock
_real_RLock = threading.RLock

_reports_total = Counter(
    "sanitizer_reports_total",
    "Concurrency-sanitizer violations observed in this process, by kind",
)

# All mutable sanitizer state is guarded by a REAL lock (never a tracked
# one — the bookkeeping must not feed itself).
_state_lock = _real_allocate()
_graph: Dict[int, set] = {}            # lock id -> lock ids acquired after it
_edges_seen: set = set()               # (before_id, after_id) already recorded
_sites: Dict[int, str] = {}            # lock id -> creation site "file:line"
_cycles_reported: set = set()          # frozenset of lock ids per cycle
_reports: List[Dict[str, Any]] = []
_MAX_REPORTS = 256
_hold_budget_s = 0.1
_installed = False

_tls = threading.local()               # .held: [(lock, t_acquired)], .rdepth: {id: n}


def _caller_site() -> str:
    # the frame that called Lock()/RLock(), skipping this module's own
    for frame in reversed(traceback.extract_stack()[:-1]):
        if frame.filename != __file__:
            return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


def _held_stack() -> List[Tuple[Any, float]]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _report(kind: str, **data: Any) -> None:
    entry = {"violation": kind, "thread": threading.current_thread().name, **data}
    with _state_lock:
        if len(_reports) < _MAX_REPORTS:
            _reports.append(entry)
    flight_recorder.record("sanitizer", **entry)
    _reports_total.inc(tags={"kind": kind})
    logger.warning("sanitizer %s: %s", kind, data)


def _find_path(src: int, dst: int) -> Optional[List[int]]:
    """DFS path src -> dst in the lock-order graph (caller holds _state_lock)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _graph.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _note_acquired(lock: Any) -> None:
    held = _held_stack()
    if held:
        lid = lock._san_id
        cycles: List[Dict[str, Any]] = []
        with _state_lock:
            for h, _t in held:
                hid = h._san_id
                if hid == lid or (hid, lid) in _edges_seen:
                    continue
                _edges_seen.add((hid, lid))
                _graph.setdefault(hid, set()).add(lid)
                # the new hid->lid edge closes a cycle iff lid already
                # reaches hid through previously observed orderings
                path = _find_path(lid, hid)
                if path is not None and frozenset(path) not in _cycles_reported:
                    _cycles_reported.add(frozenset(path))
                    sites = [_sites.get(n, "?") for n in path]
                    cycles.append({
                        "cycle": sites + [sites[0]],
                        "new_edge": [_sites.get(hid, "?"), _sites.get(lid, "?")],
                    })
        for c in cycles:  # report AFTER dropping _state_lock (_report re-takes it)
            _report("lock_order_cycle", **c)
    held.append((lock, time.monotonic()))


def _note_released(lock: Any) -> None:
    held = getattr(_tls, "held", None)
    if not held:
        return
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is lock:
            _, t0 = held.pop(i)
            dur = time.monotonic() - t0
            if dur > _hold_budget_s:
                _report("lock_hold", site=lock._san_site,
                        held_ms=round(dur * 1000.0, 2),
                        budget_ms=round(_hold_budget_s * 1000.0, 2))
            return


class _TrackedLock:
    """threading.Lock stand-in feeding the lock-order/hold detectors."""

    def __init__(self) -> None:
        self._inner = _real_allocate()
        self._san_id = id(self)
        self._san_site = _caller_site()
        with _state_lock:
            _sites[self._san_id] = self._san_site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _note_acquired(self)
        return ok

    def release(self) -> None:
        _note_released(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _at_fork_reinit(self) -> None:
        # os.register_at_fork consumers (concurrent.futures.thread grabs
        # this attribute at import time) force-reset the lock in the child
        self._inner._at_fork_reinit()
        held = getattr(_tls, "held", None)
        if held:
            held[:] = [(l, t) for l, t in held if l is not self]

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<TrackedLock {self._san_site} locked={self.locked()}>"


class _TrackedRLock:
    """threading.RLock stand-in; only the outermost acquire/release of a
    reentrant series is fed to the detectors."""

    def __init__(self) -> None:
        self._inner = _real_RLock()
        self._san_id = id(self)
        self._san_site = _caller_site()
        with _state_lock:
            _sites[self._san_id] = self._san_site

    def _depths(self) -> Dict[int, int]:
        d = getattr(_tls, "rdepth", None)
        if d is None:
            d = _tls.rdepth = {}
        return d

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            d = self._depths()
            n = d.get(self._san_id, 0) + 1
            d[self._san_id] = n
            if n == 1:
                _note_acquired(self)
        return ok

    def release(self) -> None:
        d = self._depths()
        n = d.get(self._san_id, 0) - 1
        if n <= 0:
            d.pop(self._san_id, None)
            _note_released(self)
        else:
            d[self._san_id] = n
        self._inner.release()

    def _at_fork_reinit(self) -> None:
        self._inner._at_fork_reinit()
        self._depths().pop(self._san_id, None)
        held = getattr(_tls, "held", None)
        if held:
            held[:] = [(l, t) for l, t in held if l is not self]

    # Condition protocol (wait() fully releases, then restores)
    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self) -> Any:
        self._depths().pop(self._san_id, None)
        _note_released(self)
        return self._inner._release_save()

    def _acquire_restore(self, state: Any) -> None:
        self._inner._acquire_restore(state)
        self._depths()[self._san_id] = 1
        _note_acquired(self)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<TrackedRLock {self._san_site}>"


# ---------------------------------------------------------------------------
# install / inspect
# ---------------------------------------------------------------------------

def install(hold_ms: Optional[float] = None) -> None:
    """Patch threading.Lock/RLock with the tracked wrappers. Locks created
    BEFORE install (interpreter internals, already-built subsystems) stay
    stock — the sanitizer watches what the process builds from here on."""
    global _installed, _hold_budget_s
    _hold_budget_s = float(hold_ms if hold_ms is not None
                           else config.sanitize_hold_ms) / 1000.0
    if _installed:
        return
    threading.Lock = _TrackedLock
    threading.RLock = _TrackedRLock
    _installed = True
    logger.info("concurrency sanitizer installed (hold budget %.0f ms)",
                _hold_budget_s * 1000.0)


def uninstall() -> None:
    global _installed
    threading.Lock = _real_Lock
    threading.RLock = _real_RLock
    _installed = False


def maybe_install() -> bool:
    """Install iff config.sanitize (RAY_TPU_SANITIZE=1). Called from
    ray_tpu/__init__ so the env flag alone arms every process."""
    try:
        enabled = bool(config.sanitize)
    except Exception:
        return False
    if enabled:
        install()
    return _installed


def installed() -> bool:
    return _installed


def reports() -> List[Dict[str, Any]]:
    with _state_lock:
        return list(_reports)


def clear_reports() -> None:
    """Reset report/graph state (tests); installed wrappers stay active."""
    with _state_lock:
        _reports.clear()
        _graph.clear()
        _edges_seen.clear()
        _cycles_reported.clear()


# ---------------------------------------------------------------------------
# thread hygiene (conftest fixture backend)
# ---------------------------------------------------------------------------

def thread_snapshot() -> Dict[str, Any]:
    """Names of live non-daemon threads (minus main) + daemon count."""
    threads = [t for t in threading.enumerate() if t.is_alive()]
    return {
        "nondaemon": sorted(
            t.name for t in threads
            if not t.daemon and t is not threading.main_thread()),
        "daemons": sum(1 for t in threads if t.daemon),
    }


def check_thread_leaks(before: Dict[str, Any],
                       grace_s: float = 1.5,
                       daemon_growth_max: int = 64) -> List[str]:
    """Compare the current thread population against a `before` snapshot.

    New non-daemon threads get `grace_s` to finish (teardown races are
    normal); whatever survives is a leak — the process cannot exit while
    it runs. Daemon growth beyond `daemon_growth_max` flags an unbounded
    spawn pattern (daemons die with the process, but a per-test net gain
    that large means something spawns without reuse or cleanup).
    """
    problems: List[str] = []
    baseline = set(before.get("nondaemon", ()))
    deadline = time.monotonic() + grace_s
    while True:
        now = thread_snapshot()
        leaked = [n for n in now["nondaemon"] if n not in baseline]
        if not leaked or time.monotonic() > deadline:
            break
        time.sleep(0.05)
    if leaked:
        problems.append(
            f"leaked non-daemon thread(s) {leaked}: the process cannot exit "
            f"while they run — join them in teardown or mark them daemon "
            f"with a stop path")
    growth = now["daemons"] - before.get("daemons", 0)
    if growth > daemon_growth_max:
        problems.append(
            f"daemon thread population grew by {growth} (> {daemon_growth_max}) "
            f"during one test: unbounded spawn pattern")
    return problems
