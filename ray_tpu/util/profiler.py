"""Cluster profiling plane: live stack dumps, sampling CPU profiles,
device-memory accounting, and the goodput ledger.

Reference analogue: upstream Ray's dashboard reporter agent (py-spy /
memray endpoints, SURVEY §5.1) — the layer that answers "what is this
worker doing right now?". Pure stdlib by design (the zero-egress image
ships no py-spy): live dumps come from ``sys._current_frames()``,
sampling profiles from a background thread folding those frames into
collapsed-stack (flamegraph) form, and *hung* subprocess workers are
dumped via a ``faulthandler``-registered signal that writes an
all-threads dump into the session's flight directory, where the parent
(the node agent, or the flight recorder's postmortem writer) reads it —
a worker stuck in C or a deadlocked lock cannot answer a mailbox
request, but the kernel still delivers the signal.

Four planes in one module:

- **Stack dumps**: ``dump_stacks()`` / ``format_stacks()`` for the
  calling process; ``install_child_handlers()`` + ``dump_child()`` for
  subprocess gang/actor workers (SIGUSR2 → ``stack-<pid>.txt``).
- **Sampling CPU profiles**: ``SamplingProfiler`` accumulates
  ``func;func;func count`` collapsed stacks at ``profiler_sample_hz``;
  ``merge_collapsed()`` folds per-process profiles into one cluster
  flamegraph. Children toggle theirs via SIGUSR1 (start / stop+write
  ``profile-<pid>.txt``). Remote control rides the ``profile_start`` /
  ``profile_fetch`` RPCs (core/rpc.py allowlist → cross_host.HeadService
  → node_agent), served at ``/api/v0/profile/<node>/<pid>`` and
  ``ray-tpu profile``.
- **Device-memory accounting**: ``device_memory_snapshot()`` reads
  ``jax.live_arrays()`` + per-device ``memory_stats()`` into gauges that
  federate with heartbeat telemetry (never force-imports jax).
- **Goodput ledger**: ``goodput_ledger()`` / ``ledger_from_samples()``
  decompose wall time into compute / data-stall / channel-wait / bubble
  / migration from the metrics the subsystems already export, surfaced
  in ``ray_tpu.status()`` and the health payload.

The health plane closes the loop: ``install_auto_dump()`` subscribes a
handler that turns a firing ``heartbeat_gap`` / ``data_stall_rising``
alert into a stack dump in the flight recorder and the postmortem
stream.
"""

from __future__ import annotations

import faulthandler
import os
import signal
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from ..core.config import config, declare
from ..core.metrics import Gauge

__all__ = [
    "dump_stacks", "format_stacks", "SamplingProfiler", "merge_collapsed",
    "parse_collapsed", "install_child_handlers", "dump_child",
    "toggle_child_profile", "read_child_profile", "stack_path_for",
    "profile_path_for", "device_memory_snapshot", "update_resource_gauges",
    "goodput_ledger", "ledger_from_samples", "install_auto_dump",
    "start_profile", "fetch_profile", "LEDGER_COMPONENTS",
    "rl_ledger", "rl_ledger_from_samples", "RL_COMPONENTS",
]

declare(
    "profiler_sample_hz", 50.0,
    "Sampling rate (frames/s) of the in-process CPU profiler "
    "(util/profiler.py SamplingProfiler). The sampler only runs while a "
    "profile_start window is open, so idle cost is zero; the bench "
    "profile suite gates the active cost at <=2% serve req/s.",
)
declare(
    "profiler_max_seconds", 60.0,
    "Hard cap on one sampling-profile window; a profile_start with a "
    "longer (or omitted) duration is clamped here so a forgotten "
    "profiler cannot run forever.",
)
declare(
    "profiler_auto_dump", True,
    "Auto-trigger a live stack dump into the flight recorder + "
    "postmortem stream when a sustained stall or heartbeat-gap alert "
    "fires on the health plane (heartbeat_gap, data_stall_rising).",
)
declare(
    "profiler_device_memory", True,
    "Refresh device-memory gauges (jax.live_arrays / backend "
    "bytes-in-use) on each telemetry flush. Never force-imports jax: "
    "processes that have not touched jax pay nothing.",
)

# Federated with heartbeat telemetry (cross_host ships the full registry
# snapshot), so every per-process set lands tagged node_id/role at the head.
_g_cpu = Gauge("host_cpu_used_fraction",
               "Host-wide CPU utilization fraction (busy/total jiffies "
               "delta from /proc/stat between telemetry flushes)")
_g_rss = Gauge("process_rss_bytes",
               "Resident set size of this process (/proc/self/status VmRSS)")
_g_dev_bytes = Gauge("device_memory_bytes_in_use",
                     "Backend-reported bytes in use per local device "
                     "(jax memory_stats), tagged device=")
_g_live_arrays = Gauge("device_live_array_count",
                       "Number of live jax arrays held by this process")
_g_live_bytes = Gauge("device_live_array_bytes",
                      "Total nbytes of live jax arrays held by this process")
_g_profiler_on = Gauge("profiler_sampling_active",
                       "1 while this process's sampling CPU profiler is "
                       "collecting (profile_start window open)")

# Signals for subprocess workers: USR2 = one-shot all-threads stack dump
# (faulthandler: async-signal-safe, fires even when every Python thread is
# wedged), USR1 = toggle the sampling profiler (start / stop+persist).
_DUMP_SIGNAL = getattr(signal, "SIGUSR2", None)
_PROFILE_SIGNAL = getattr(signal, "SIGUSR1", None)


# ---------------------------------------------------------------------------
# Live stack dumps (in-process)
# ---------------------------------------------------------------------------

def dump_stacks() -> Dict[str, Any]:
    """Snapshot every thread's Python stack in THIS process. Callable from
    any thread (the dispatch handler dumps while task threads hang)."""
    frames = sys._current_frames()
    known = {t.ident: t for t in threading.enumerate()}
    threads: List[Dict[str, Any]] = []
    for ident, frame in frames.items():
        t = known.get(ident)
        stack = traceback.extract_stack(frame)
        threads.append({
            "thread_id": ident,
            "name": t.name if t is not None else f"thread-{ident}",
            "daemon": bool(t.daemon) if t is not None else False,
            "frames": [
                {"file": f.filename, "line": f.lineno, "func": f.name}
                for f in stack
            ],
        })
    threads.sort(key=lambda th: th["name"])
    return {"pid": os.getpid(), "at": time.time(), "threads": threads}


def format_stacks(dump: Dict[str, Any]) -> str:
    """Render a dump_stacks() record the way faulthandler does (newest
    frame last), one block per thread."""
    lines = [f"pid {dump['pid']} at {time.strftime('%H:%M:%S', time.localtime(dump['at']))} "
             f"({len(dump['threads'])} threads)"]
    for th in dump["threads"]:
        daemon = " daemon" if th["daemon"] else ""
        lines.append(f"Thread {th['thread_id']} ({th['name']}{daemon}):")
        for fr in th["frames"]:
            lines.append(f'  File "{fr["file"]}", line {fr["line"]}, '
                         f'in {fr["func"]}')
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Sampling CPU profiler (collapsed-stack / flamegraph form)
# ---------------------------------------------------------------------------

def _frame_label(frame) -> str:
    code = frame.f_code
    return f"{os.path.basename(code.co_filename)}:{code.co_name}"


class SamplingProfiler:
    """Wall-clock sampler: a daemon thread snapshots every OTHER thread's
    stack `hz` times per second and folds each into a root-first
    ``file:func;file:func;... -> count`` collapsed entry (the flamegraph
    wire format). Zero cost while stopped."""

    def __init__(self, hz: Optional[float] = None):
        self.hz = float(hz or config.profiler_sample_hz)
        self._collapsed: Dict[str, int] = {}
        self._samples = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_at = 0.0
        self._deadline = 0.0

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self, duration_s: Optional[float] = None) -> None:
        if self.running:
            return
        cap = float(config.profiler_max_seconds)
        dur = min(float(duration_s), cap) if duration_s else cap
        self._stop.clear()
        self._started_at = time.monotonic()
        self._deadline = self._started_at + dur
        self._thread = threading.Thread(
            target=self._loop, name="ray-tpu-profiler", daemon=True)
        self._thread.start()
        _g_profiler_on.set(1)

    def _loop(self) -> None:
        period = 1.0 / max(self.hz, 1.0)
        me = threading.get_ident()
        while not self._stop.is_set() and time.monotonic() < self._deadline:
            frames = sys._current_frames()
            with self._lock:
                self._samples += 1
                for ident, frame in frames.items():
                    if ident == me:
                        continue
                    parts: List[str] = []
                    f = frame
                    while f is not None:
                        parts.append(_frame_label(f))
                        f = f.f_back
                    parts.reverse()
                    key = ";".join(parts)
                    self._collapsed[key] = self._collapsed.get(key, 0) + 1
            self._stop.wait(period)
        _g_profiler_on.set(0)

    def stop(self) -> Dict[str, int]:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        _g_profiler_on.set(0)
        return self.collapsed()

    def collapsed(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._collapsed)

    @property
    def sample_count(self) -> int:
        with self._lock:
            return self._samples

    def collapsed_text(self) -> str:
        """The `flamegraph.pl` wire form: one `stack count` line each."""
        with self._lock:
            items = sorted(self._collapsed.items(),
                           key=lambda kv: (-kv[1], kv[0]))
        return "\n".join(f"{stack} {count}" for stack, count in items)


def parse_collapsed(text: str) -> Dict[str, int]:
    """Inverse of collapsed_text(): `stack count` lines -> dict."""
    out: Dict[str, int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count = line.rpartition(" ")
        if not stack:
            continue
        try:
            out[stack] = out.get(stack, 0) + int(count)
        except ValueError:
            continue
    return out


def merge_collapsed(*profiles: Dict[str, int]) -> Dict[str, int]:
    """Fold per-process collapsed profiles into one cluster flamegraph —
    identical stacks from different processes simply add, which is the
    point of the shared collapsed form."""
    out: Dict[str, int] = {}
    for p in profiles:
        for stack, count in (p or {}).items():
            out[stack] = out.get(stack, 0) + int(count)
    return out


# Per-process singleton the profile_start/profile_fetch RPCs drive. One
# window at a time: a second start while running is a no-op (idempotent
# retries must not reset the accumulation).
_proc_profiler: Optional[SamplingProfiler] = None
_proc_lock = threading.Lock()


def start_profile(duration_s: Optional[float] = None,
                  hz: Optional[float] = None) -> Dict[str, Any]:
    global _proc_profiler
    with _proc_lock:
        if _proc_profiler is None or not _proc_profiler.running:
            _proc_profiler = SamplingProfiler(hz=hz)
            _proc_profiler.start(duration_s)
        p = _proc_profiler
    return {"pid": os.getpid(), "hz": p.hz, "running": True}


def fetch_profile(stop: bool = True) -> Dict[str, Any]:
    with _proc_lock:
        p = _proc_profiler
    if p is None:
        return {"pid": os.getpid(), "samples": 0, "collapsed": "",
                "running": False}
    if stop:
        p.stop()
    return {"pid": os.getpid(), "samples": p.sample_count,
            "collapsed": p.collapsed_text(), "running": p.running}


# ---------------------------------------------------------------------------
# Subprocess workers: signal-driven dumps + profile toggle
# ---------------------------------------------------------------------------

def stack_path_for(pid: int, session: str) -> str:
    return os.path.join(session, "flight", f"stack-{pid}.txt")


def profile_path_for(pid: int, session: str) -> str:
    return os.path.join(session, "flight", f"profile-{pid}.txt")


_child_stack_file = None          # keep the fd alive: faulthandler needs it
_child_profile_path: Optional[str] = None
_child_profiler: Optional[SamplingProfiler] = None


def install_child_handlers(log_dir: str) -> Optional[str]:
    """Called at subprocess-worker startup (actor_process._child_main /
    process_pool._worker_main), right after flight_recorder.attach:

    - ``faulthandler.enable`` on ``<session>/flight/stack-<pid>.txt`` so
      fatal crashes (SIGSEGV/SIGABRT) leave an all-threads dump the
      postmortem writer can fold in,
    - ``faulthandler.register(SIGUSR2)`` on the same file so the parent
      can dump a LIVE (or hung) worker on demand,
    - a SIGUSR1 toggle for the sampling profiler (start on first signal,
      stop + persist ``profile-<pid>.txt`` on the second).

    Returns the stack-file path, or None when unsupported (no signals on
    the platform, or not the main thread)."""
    global _child_stack_file, _child_profile_path
    if _DUMP_SIGNAL is None or _PROFILE_SIGNAL is None:
        return None
    if threading.current_thread() is not threading.main_thread():
        return None
    try:
        session = os.path.dirname(os.path.abspath(log_dir))
        flight_dir = os.path.join(session, "flight")
        os.makedirs(flight_dir, exist_ok=True)
        path = stack_path_for(os.getpid(), session)
        _child_stack_file = open(path, "w", buffering=1)
        faulthandler.enable(file=_child_stack_file)
        faulthandler.register(_DUMP_SIGNAL, file=_child_stack_file,
                              all_threads=True)
        _child_profile_path = profile_path_for(os.getpid(), session)
        signal.signal(_PROFILE_SIGNAL, _on_profile_signal)
        return path
    except Exception:
        return None


def _on_profile_signal(signum, frame) -> None:
    """SIGUSR1 in a child: toggle the sampler. Runs on the main thread
    between bytecodes — it only flips a thread on/off and writes one
    small file, so it is safe even mid-task."""
    global _child_profiler
    try:
        p = _child_profiler
        if p is None or not p.running:
            _child_profiler = SamplingProfiler()
            _child_profiler.start()
        else:
            p.stop()
            if _child_profile_path:
                tmp = _child_profile_path + ".tmp"
                with open(tmp, "w") as f:
                    f.write(f"# pid={os.getpid()} samples={p.sample_count}\n")
                    f.write(p.collapsed_text() + "\n")
                os.replace(tmp, _child_profile_path)
    except Exception:
        pass  # a broken profiler must never kill the worker


def _wait_for_growth(path: str, size0: int, timeout_s: float) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            if os.path.getsize(path) > size0:
                # one more beat so the writer finishes the block
                time.sleep(0.05)
                return True
        except OSError:
            pass
        time.sleep(0.05)
    return False


def dump_child(pid: int, session: str, timeout_s: float = 5.0) -> str:
    """Stack-dump a subprocess worker from the parent: signal it, then
    read what faulthandler appended to its stack file. Works on a hung
    worker — faulthandler's handler is C code, no GIL needed."""
    if _DUMP_SIGNAL is None:
        raise RuntimeError("stack-dump signal unsupported on this platform")
    path = stack_path_for(pid, session)
    try:
        size0 = os.path.getsize(path)
    except OSError:
        size0 = 0
    os.kill(pid, _DUMP_SIGNAL)
    if not _wait_for_growth(path, size0, timeout_s):
        raise TimeoutError(
            f"pid {pid} wrote no stack dump within {timeout_s}s "
            f"(handlers not installed, or the process is gone)")
    with open(path, "rb") as f:
        f.seek(size0)
        return f.read().decode(errors="replace")


def toggle_child_profile(pid: int) -> None:
    if _PROFILE_SIGNAL is None:
        raise RuntimeError("profile signal unsupported on this platform")
    os.kill(pid, _PROFILE_SIGNAL)


def read_child_profile(pid: int, session: str,
                       timeout_s: float = 5.0) -> str:
    """Stop a child's sampler (second toggle) and read the collapsed
    profile it persists."""
    path = profile_path_for(pid, session)
    try:
        mtime0 = os.path.getmtime(path)
    except OSError:
        mtime0 = 0.0
    toggle_child_profile(pid)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            if os.path.getmtime(path) > mtime0 or (
                    mtime0 == 0.0 and os.path.exists(path)):
                with open(path) as f:
                    return f.read()
        except OSError:
            pass
        time.sleep(0.05)
    raise TimeoutError(f"pid {pid} wrote no profile within {timeout_s}s")


# ---------------------------------------------------------------------------
# Device-memory accounting + host CPU/RSS gauges
# ---------------------------------------------------------------------------

def device_memory_snapshot() -> Dict[str, Any]:
    """Per-process device-memory view, gauge-published for telemetry
    federation. Never force-imports jax: a process that has not touched
    it reports zeros at zero cost."""
    out: Dict[str, Any] = {"pid": os.getpid(), "backend": None,
                           "live_arrays": 0, "live_bytes": 0,
                           "devices": []}
    jax = sys.modules.get("jax")
    if jax is None:
        return out
    try:
        arrs = jax.live_arrays()
        out["live_arrays"] = len(arrs)
        out["live_bytes"] = int(sum(getattr(a, "nbytes", 0) for a in arrs))
    except Exception:
        pass
    try:
        out["backend"] = jax.default_backend()
        for d in jax.local_devices():
            stats = {}
            try:
                stats = d.memory_stats() or {}
            except Exception:
                stats = {}
            in_use = int(stats.get("bytes_in_use", 0))
            out["devices"].append({
                "device": str(d),
                "bytes_in_use": in_use,
                "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
                "bytes_limit": int(stats.get("bytes_limit", 0)),
            })
            _g_dev_bytes.set(in_use, {"device": str(d)})
    except Exception:
        pass
    _g_live_arrays.set(out["live_arrays"])
    _g_live_bytes.set(out["live_bytes"])
    return out


def _read_rss_bytes() -> int:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


_cpu_prev: Optional[Dict[str, int]] = None
_cpu_lock = threading.Lock()


def _read_proc_stat() -> Optional[Dict[str, int]]:
    try:
        with open("/proc/stat") as f:
            first = f.readline().split()
    except OSError:
        return None
    if not first or first[0] != "cpu":
        return None
    vals = [int(x) for x in first[1:]]
    idle = vals[3] + (vals[4] if len(vals) > 4 else 0)  # idle + iowait
    return {"total": sum(vals), "idle": idle}


def host_cpu_fraction() -> float:
    """Host-wide CPU utilization since the previous call (busy/total
    jiffies delta from /proc/stat). First call establishes the baseline
    and returns 0."""
    global _cpu_prev
    cur = _read_proc_stat()
    if cur is None:
        return 0.0
    with _cpu_lock:
        prev, _cpu_prev = _cpu_prev, cur
    if prev is None:
        return 0.0
    d_total = cur["total"] - prev["total"]
    d_idle = cur["idle"] - prev["idle"]
    if d_total <= 0:
        return 0.0
    return max(0.0, min(1.0, 1.0 - d_idle / d_total))


def update_resource_gauges() -> Dict[str, float]:
    """Refresh the CPU/RSS (and optionally device-memory) gauges. Called
    on every telemetry flush in workers and on head summary renders —
    a handful of /proc reads, cheap enough for the heartbeat path."""
    cpu = host_cpu_fraction()
    rss = _read_rss_bytes()
    _g_cpu.set(cpu)
    _g_rss.set(rss)
    if bool(config.profiler_device_memory):
        device_memory_snapshot()
    return {"host_cpu_used_fraction": cpu, "process_rss_bytes": float(rss)}


# ---------------------------------------------------------------------------
# Goodput / MFU ledger
# ---------------------------------------------------------------------------

LEDGER_COMPONENTS = ("compute", "data_stall", "channel_wait", "bubble",
                     "migration")


def goodput_ledger(wall_s: float, data_stall_s: float = 0.0,
                   channel_wait_s: float = 0.0,
                   bubble_fraction: float = 0.0,
                   migration_s: float = 0.0) -> Dict[str, float]:
    """Decompose `wall_s` of job time into the goodput components. The
    non-compute parts are measured; compute is the remainder (clamped at
    zero — overlapping stalls can over-count, and the ledger says so via
    overcommit_s). Components ALWAYS sum to wall_s exactly."""
    wall_s = max(float(wall_s), 0.0)
    bubble_s = max(0.0, min(1.0, float(bubble_fraction))) * wall_s
    parts = {
        "data_stall": max(float(data_stall_s), 0.0),
        "channel_wait": max(float(channel_wait_s), 0.0),
        "bubble": bubble_s,
        "migration": max(float(migration_s), 0.0),
    }
    overhead = sum(parts.values())
    overcommit = max(0.0, overhead - wall_s)
    if overcommit > 0.0 and overhead > 0.0:
        # stalls measured on concurrent threads can exceed wall time;
        # scale them down proportionally so the ledger stays a partition
        scale = wall_s / overhead
        parts = {k: v * scale for k, v in parts.items()}
        overhead = wall_s
    compute = wall_s - overhead
    ledger = {"wall_seconds": wall_s, "compute": compute, **parts,
              "overcommit_seconds": overcommit,
              "goodput_fraction": (compute / wall_s) if wall_s > 0 else 0.0}
    return ledger


RL_COMPONENTS = ("rollout", "reward", "train", "weight_sync")


def rl_ledger(wall_s: float, rollout_s: float = 0.0, reward_s: float = 0.0,
              train_s: float = 0.0,
              weight_sync_s: float = 0.0) -> Dict[str, float]:
    """Online-RL decomposition of one loop iteration's wall time into
    the RL_COMPONENTS (+ 'other' — coordination the four phases don't
    cover), an exact partition like goodput_ledger: the <5% sync-stall
    claim reads sync_stall_fraction straight off this, measured, not
    asserted. Phases timed on concurrent threads can over-count; they
    are scaled down proportionally (overcommit reported) so the ledger
    stays a partition."""
    wall_s = max(float(wall_s), 0.0)
    parts = {
        "rollout": max(float(rollout_s), 0.0),
        "reward": max(float(reward_s), 0.0),
        "train": max(float(train_s), 0.0),
        "weight_sync": max(float(weight_sync_s), 0.0),
    }
    spent = sum(parts.values())
    overcommit = max(0.0, spent - wall_s)
    if overcommit > 0.0 and spent > 0.0:
        scale = wall_s / spent
        parts = {k: v * scale for k, v in parts.items()}
        spent = wall_s
    return {"wall_seconds": wall_s, **parts,
            "other": wall_s - spent,
            "overcommit_seconds": overcommit,
            "sync_stall_fraction": (parts["weight_sync"] / wall_s
                                    if wall_s > 0 else 0.0)}


def _family_sums(families: List[Dict[str, Any]]) -> Dict[str, float]:
    """Fold a metrics snapshot (registry.snapshot() families, possibly
    merged across nodes) into {family_name: summed value}; histograms
    contribute their _sum series."""
    out: Dict[str, float] = {}
    for fam in families or []:
        name = fam.get("name", "")
        for sname, _tags, value in fam.get("samples", []):
            if sname == name or sname == f"{name}_sum":
                out[name] = out.get(name, 0.0) + float(value)
    return out


def rl_ledger_from_samples(families: List[Dict[str, Any]],
                           wall_s: Optional[float] = None
                           ) -> Dict[str, float]:
    """Build the rl ledger from the rl_phase_seconds{phase=...} family
    rl/online.py exports. Wall defaults to the phases' sum (the loop is
    sequential per iteration); pass the measured wall for a loop that
    overlaps rollout with training."""
    phase: Dict[str, float] = {}
    for fam in families or []:
        if fam.get("name") != "rl_phase_seconds":
            continue
        for sname, tags, value in fam.get("samples", []):
            if sname in ("rl_phase_seconds", "rl_phase_seconds_sum"):
                p = dict(tags or {}).get("phase", "")
                phase[p] = phase.get(p, 0.0) + float(value)
    if wall_s is None:
        wall_s = sum(phase.get(p, 0.0) for p in RL_COMPONENTS)
    return rl_ledger(
        wall_s,
        rollout_s=phase.get("rollout", 0.0),
        reward_s=phase.get("reward", 0.0),
        train_s=phase.get("train", 0.0),
        weight_sync_s=phase.get("weight_sync", 0.0),
    )


def _family_max(families: List[Dict[str, Any]], name: str) -> float:
    best = 0.0
    for fam in families or []:
        if fam.get("name") != name:
            continue
        for sname, _tags, value in fam.get("samples", []):
            if sname in (name, f"{name}_sum"):
                best = max(best, float(value))
    return best


def ledger_from_samples(families: List[Dict[str, Any]],
                        wall_s: Optional[float] = None) -> Dict[str, float]:
    """Build the goodput ledger from the metric families the subsystems
    already export. Wall time defaults to the busiest stage's
    accumulated step time (stages run concurrently, so max — not sum —
    approximates the job's wall clock); bubble uses the pipeline's own
    measured fraction, decomposed per kind (bubble_warmup, bubble_drain,
    bubble_channel_wait, bubble_grad_exchange) from the
    train_pipeline_bubble_seconds counter when the pipeline exported it."""
    sums = _family_sums(families)
    if wall_s is None:
        wall_s = _family_max(families, "train_stage_step_seconds")
    bubble = 0.0
    bubble_kinds: Dict[str, float] = {}
    for fam in families or []:
        if fam.get("name") == "train_pipeline_bubble_fraction":
            vals = [float(v) for _s, _t, v in fam.get("samples", [])]
            if vals:
                bubble = sum(vals) / len(vals)
        elif fam.get("name") == "train_pipeline_bubble_seconds":
            for _s, tags, value in fam.get("samples", []):
                # registry.snapshot() carries tags as [[k, v], ...] pairs;
                # remote telemetry payloads carry dicts — accept both.
                if tags and not isinstance(tags, dict):
                    tags = dict(tags)
                kind = (tags or {}).get("kind", "other")
                key = f"bubble_{kind}"
                bubble_kinds[key] = bubble_kinds.get(key, 0.0) + float(value)
    ledger = goodput_ledger(
        wall_s,
        data_stall_s=sums.get("data_stage_stall_seconds", 0.0),
        channel_wait_s=sums.get("channel_recv_wait_seconds", 0.0),
        bubble_fraction=bubble,
        migration_s=sums.get("serve_kv_migration_seconds", 0.0),
    )
    ledger.update(bubble_kinds)
    return ledger


# ---------------------------------------------------------------------------
# Health-plane loop closure: auto stack dump on stall / heartbeat alerts
# ---------------------------------------------------------------------------

AUTO_DUMP_RULES = frozenset({"heartbeat_gap", "data_stall_rising"})


def install_auto_dump(plane) -> bool:
    """Subscribe a handler on a HealthPlane: a FIRING stall/heartbeat
    alert triggers a live stack dump that lands in the flight-recorder
    ring AND the postmortem stream (flight_recorder.write_auto_dump), so
    the postmortem for a wedged node carries what it was doing. Returns
    whether the handler was installed (profiler_auto_dump gates it)."""
    if not bool(config.profiler_auto_dump):
        return False

    from . import flight_recorder

    def _on_alert(alert: Dict[str, Any]) -> None:
        try:
            if alert.get("state") != "firing":
                return
            if alert.get("rule") not in AUTO_DUMP_RULES:
                return
            dump = dump_stacks()
            text = format_stacks(dump)
            flight_recorder.record(
                "stack_dump", rule=alert.get("rule"),
                labels=dict(alert.get("labels") or {}),
                threads=len(dump["threads"]))
            flight_recorder.write_auto_dump(alert, text)
        except Exception:
            pass  # observability must never break the health loop

    plane.subscribe(_on_alert)
    return True
