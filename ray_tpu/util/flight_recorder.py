"""Per-process flight recorder → crash postmortems.

A bounded ring of this process's most recent activity — finished trace
spans (via a sink hook in util/tracing), log lines (via a bridge handler
in core/logging), and explicit events (`record()`) — mirrored to an
append-only JSONL file in the session dir. SIGKILL gives a worker no
chance to flush anything, so the mirror is written per entry (line-
buffered, no fsync): whatever the child managed to do in its last few
seconds is already on disk when the parent reaps it.

Reap paths (`process_pool._lane` worker death, `actor_process` crash
detection) call `write_postmortem(pid, cause, ...)`, which folds the
dead worker's mirror ring together with the tail of its redirected
stdout/stderr file into one artifact under `<session>/postmortems/`.
Worker runtimes ship freshly written artifacts to the head with the next
heartbeat telemetry flush (`drain_postmortems`), and the dashboard
serves both local and federated artifacts at `/api/v0/postmortems` — so
every `util/chaos.py` kill leaves an inspectable "last 5 seconds"
record, retrievable from the head.

Enablement: the in-memory ring and `record()` are always live (a deque
append). `attach()` — called in worker-process entrypoints — adds the
tracing sink and the on-disk mirror; unattached processes pay nothing on
the tracing hot path.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "record", "attach", "snapshot", "write_postmortem", "write_auto_dump",
    "drain_postmortems", "requeue_postmortems", "list_postmortems",
    "load_postmortem", "mirror_path_for",
]

_lock = threading.Lock()
_ring: deque = deque(maxlen=256)
_mirror_path: Optional[str] = None
_mirror_file = None
_mirror_bytes = 0
_mirror_cap = 262_144
_pending: List[Dict[str, Any]] = []   # artifacts not yet shipped to the head
_reaped: set = set()                  # pids already postmortem'd (dedup)


def _entry(kind: str, data: Dict[str, Any]) -> Dict[str, Any]:
    return {"ts": time.time(), "pid": os.getpid(), "kind": kind, **data}


def record(kind: str, **data: Any) -> None:
    """Append one event to the ring (and the mirror, when attached)."""
    e = _entry(kind, data)
    _ring.append(e)
    if _mirror_file is not None:
        _mirror_write(e)


def _mirror_write(e: Dict[str, Any]) -> None:
    global _mirror_bytes
    try:
        line = json.dumps(e, default=repr) + "\n"
    except Exception:
        return
    with _lock:
        f = _mirror_file
        if f is None:
            return
        try:
            if _mirror_bytes + len(line) > _mirror_cap:
                # rewrite from the ring: the file stays a bounded, current
                # window instead of growing or losing its newest entries
                f.seek(0)
                f.truncate()
                _mirror_bytes = 0
                for old in list(_ring):
                    ol = json.dumps(old, default=repr) + "\n"
                    f.write(ol)
                    _mirror_bytes += len(ol)
            else:
                f.write(line)
                _mirror_bytes += len(line)
            f.flush()
        except (OSError, ValueError):
            pass


def _span_sink(rec: Dict[str, Any]) -> None:
    record("span", name=rec.get("name"), trace_id=rec.get("trace_id"),
           span_id=rec.get("span_id"), start_us=rec.get("start_us"),
           end_us=rec.get("end_us"), attrs=rec.get("attrs"))


def on_log(line: str) -> None:
    """Bridge target for core/logging's flight handler."""
    record("log", line=line)


def mirror_path_for(pid: int, session: Optional[str] = None) -> str:
    if session is None:
        from ..core.logging import session_dir
        session = session_dir()
    return os.path.join(session, "flight", f"flight-{pid}.jsonl")


def attach(log_dir: str = "", component: str = "") -> None:
    """Enable the tracing sink and the on-disk mirror for this process.

    Called from worker-process entrypoints with the parent's log dir (the
    same one stdout/stderr redirect into), so parent and child agree on
    the session root without any extra protocol."""
    global _mirror_path, _mirror_file, _mirror_bytes, _mirror_cap, _ring
    try:
        from ..core.config import config
        _ring = deque(_ring, maxlen=int(config.get("flight_recorder_entries")))
        _mirror_cap = int(config.get("flight_recorder_bytes"))
    except Exception:
        pass
    from . import tracing
    tracing._flight_sink = _span_sink
    if log_dir:
        session = os.path.dirname(os.path.abspath(log_dir))
    else:
        from ..core.logging import session_dir
        session = session_dir()
    path = mirror_path_for(os.getpid(), session)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with _lock:
            _mirror_file = open(path, "w")
            _mirror_path = path
            _mirror_bytes = 0
    except OSError:
        return
    record("attach", component=component)


def snapshot() -> List[Dict[str, Any]]:
    return list(_ring)


# -- reaper side ------------------------------------------------------------

def _tail_lines(path: str, n: int = 50, max_bytes: int = 65_536) -> List[str]:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - max_bytes))
            data = f.read().decode("utf-8", "replace")
    except OSError:
        return []
    return data.splitlines()[-n:]


def write_postmortem(pid: int, cause: str, exitcode: Optional[int] = None,
                     session: Optional[str] = None,
                     stdout_hint: str = "") -> Optional[str]:
    """Fold a dead worker's flight mirror + stdout tail into one artifact.

    `stdout_hint` names the redirect file the worker wrote ("actor" or
    "worker" prefix); both are probed when empty. Returns the artifact
    path (None if this pid was already reaped — crash detection can fire
    from more than one thread)."""
    with _lock:
        if pid in _reaped:
            return None
        _reaped.add(pid)
    if session is None:
        from ..core.logging import session_dir
        session = session_dir()
    entries: List[Dict[str, Any]] = []
    mirror = mirror_path_for(pid, session)
    for raw in _tail_lines(mirror, n=512):
        try:
            entries.append(json.loads(raw))
        except ValueError:
            continue
    stdout_tail: List[str] = []
    prefixes = [stdout_hint] if stdout_hint else ["actor", "worker"]
    for prefix in prefixes:
        out = os.path.join(session, "logs", f"{prefix}-{pid}.out")
        if os.path.exists(out):
            stdout_tail = _tail_lines(out)
            break
    # final stack dump: util/profiler registers faulthandler in worker
    # children (fatal-signal dumps + SIGUSR2 on demand), appending to
    # <session>/flight/stack-<pid>.txt — whatever it last wrote is the
    # dead worker's final all-threads traceback
    stack_dump: List[str] = []
    try:
        from . import profiler
        stack_dump = _tail_lines(profiler.stack_path_for(pid, session), n=120)
    except Exception:  # noqa: BLE001 — reaping must not fail on the extras
        pass
    art = {
        "pid": pid,
        "cause": cause,
        "exitcode": exitcode,
        "written_at": time.time(),
        "spans": [e for e in entries if e.get("kind") == "span"],
        "logs": [e.get("line", "") for e in entries if e.get("kind") == "log"],
        "events": [e for e in entries if e.get("kind") not in ("span", "log")],
        "stdout_tail": stdout_tail,
        "stack_dump": stack_dump,
    }
    pm_dir = os.path.join(session, "postmortems")
    path = os.path.join(pm_dir, f"postmortem-{pid}-{int(art['written_at'])}.json")
    try:
        os.makedirs(pm_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(art, f, default=repr)
    except OSError:
        path = ""
    with _lock:
        _pending.append(art)
        del _pending[:-20]  # a reap storm must not bloat heartbeats
    try:
        from . import timeline
        timeline.record(f"postmortem:{cause}", ph="i", cat="postmortem",
                        args={"pid": pid, "exitcode": exitcode, "path": path})
    except Exception:
        pass
    return path or None


def write_auto_dump(alert: Dict[str, Any], stack_text: str,
                    session: Optional[str] = None) -> Optional[str]:
    """Persist a health-alert-triggered stack dump of THIS process as a
    postmortem-stream artifact (util/profiler.install_auto_dump is the
    caller). Unlike write_postmortem the process is alive — no reap dedup;
    the artifact rides the same `_pending` queue so it federates to the
    head and shows at /api/v0/postmortems like any crash record."""
    if session is None:
        from ..core.logging import session_dir
        session = session_dir()
    pid = os.getpid()
    art = {
        "pid": pid,
        "cause": f"auto_dump:{alert.get('rule', 'alert')}",
        "exitcode": None,
        "written_at": time.time(),
        "alert": {k: alert.get(k) for k in ("rule", "state", "labels",
                                            "value", "node")},
        "stack_dump": (stack_text or "").splitlines()[-200:],
    }
    pm_dir = os.path.join(session, "postmortems")
    path = os.path.join(pm_dir, f"autodump-{pid}-{int(art['written_at'])}.json")
    try:
        os.makedirs(pm_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(art, f, default=repr)
    except OSError:
        path = ""
    with _lock:
        _pending.append(art)
        del _pending[:-20]
    return path or None


def drain_postmortems() -> List[Dict[str, Any]]:
    """Artifacts written by this process since the last drain (shipped to
    the head with heartbeat telemetry; a failed flush requeues them via
    `requeue_postmortems`)."""
    with _lock:
        out, _pending[:] = list(_pending), []
    return out


def requeue_postmortems(arts: List[Dict[str, Any]]) -> None:
    """Put drained artifacts back after a failed telemetry flush."""
    if not arts:
        return
    with _lock:
        _pending[:0] = arts
        del _pending[:-20]


def list_postmortems(session: Optional[str] = None) -> List[str]:
    if session is None:
        from ..core.logging import session_dir
        session = session_dir()
    pm_dir = os.path.join(session, "postmortems")
    try:
        names = sorted(os.listdir(pm_dir))
    except OSError:
        return []
    return [os.path.join(pm_dir, n) for n in names if n.endswith(".json")]


def load_postmortem(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)
