"""Streaming latency digests for the SLO health plane.

Fixed-bucket log-spaced quantile sketches (the mergeable alternative to a
t-digest that needs no per-update allocation): every process keeps one
`Digest` per (metric, tags) pair, serve hot paths update them inline
(`serve/engine.py` TTFT / time-between-tokens / e2e, `serve/disagg.py`
KV-migration), and worker runtimes ship `snapshot()` with the existing
heartbeat telemetry piggyback (cross_host._maybe_report_telemetry →
control_plane.report_telemetry(digests=...)). The head merges per-node
snapshots bucket-wise — same fixed bounds everywhere, so a merge is an
element-wise add — and answers "p95 TTFT per replica over the last 60s"
without scraping histograms (core/health.py consumes this).

Bucket layout: 20 buckets per decade over [100µs, 100s) → relative
quantile error ≤ 10^(1/20)-1 ≈ 12%, plus one underflow and one overflow
bucket. Windowing: the window (config slo_digest_window_s) is cut into
`_SLICES` rotating sub-windows of counts; `snapshot()`/`quantile()` sum
the slices still inside the window, so a replica that degraded two
minutes ago but recovered reads healthy now.

`Digest.add` is lock-free by design: it is a handful of list-item
increments under the GIL on the decode hot path (the bench health suite
gates it at ≤2% tokens/s). A racing rotation can at worst misplace one
update into an adjacent 10s slice — harmless for telemetry.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Digest", "digest", "observe", "snapshot", "clear", "enabled",
    "merge_snapshots", "quantile_from_counts", "BUCKET_BOUNDS",
]

_PER_DECADE = 20
_LO_EXP = -4          # 1e-4 s = 100µs
_HI_EXP = 2           # 1e+2 s
_NB = (_HI_EXP - _LO_EXP) * _PER_DECADE   # 120 finite buckets
_UNDER = _NB          # index of the underflow bucket
_OVER = _NB + 1       # index of the overflow bucket
_TOTAL = _NB + 2
_SLICES = 6

#: Upper bound (seconds) of finite bucket i: 1e-4 * 10^((i+1)/20).
BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    10.0 ** (_LO_EXP + (i + 1) / _PER_DECADE) for i in range(_NB)
)

_LOG_LO = float(_LO_EXP)


def _bucket(value: float) -> int:
    if value < 1e-4:
        return _UNDER
    idx = int((math.log10(value) - _LOG_LO) * _PER_DECADE)
    return idx if idx < _NB else _OVER


def _bucket_value(idx: int) -> float:
    """Representative latency for bucket idx (geometric midpoint)."""
    if idx == _UNDER:
        return 5e-5
    if idx >= _NB:
        return 10.0 ** _HI_EXP
    lo = 10.0 ** (_LOG_LO + idx / _PER_DECADE)
    return lo * (10.0 ** (0.5 / _PER_DECADE))


class Digest:
    """One windowed quantile sketch. Thread-compatible: `add` is GIL-atomic
    enough for telemetry; snapshot/rotation take the instance lock."""

    __slots__ = ("name", "tags", "_slices", "_slice_start", "_slice_s",
                 "_cur", "count", "sum", "min", "max", "_lock")

    def __init__(self, name: str, tags: Optional[Dict[str, str]] = None,
                 window_s: Optional[float] = None):
        self.name = name
        self.tags = dict(tags or {})
        if window_s is None:
            try:
                from ..core.config import config
                window_s = float(config.get("slo_digest_window_s"))
            except Exception:
                window_s = 60.0
        self._slice_s = max(0.5, window_s / _SLICES)
        self._slices: List[List[int]] = [[0] * _TOTAL for _ in range(_SLICES)]
        self._slice_start = [0.0] * _SLICES
        self._cur = 0
        self.count = 0       # lifetime
        self.sum = 0.0       # lifetime
        self.min = math.inf
        self.max = 0.0
        self._lock = threading.Lock()

    # -- hot path -----------------------------------------------------------
    def add(self, value: float, n: int = 1, now: Optional[float] = None) -> None:
        if now is None:
            now = time.monotonic()
        cur = self._cur
        if now - self._slice_start[cur] >= self._slice_s:
            self._rotate(now)
            cur = self._cur
        self._slices[cur][_bucket(value)] += n
        self.count += n
        self.sum += value * n
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def _rotate(self, now: float) -> None:
        with self._lock:
            if now - self._slice_start[self._cur] < self._slice_s:
                return  # another thread rotated first
            nxt = (self._cur + 1) % _SLICES
            self._slices[nxt] = [0] * _TOTAL
            self._slice_start[nxt] = now
            self._cur = nxt

    # -- queries ------------------------------------------------------------
    def window_counts(self, now: Optional[float] = None) -> List[int]:
        """Summed bucket counts over the slices still inside the window."""
        if now is None:
            now = time.monotonic()
        horizon = now - self._slice_s * _SLICES
        out = [0] * _TOTAL
        with self._lock:
            for start, counts in zip(self._slice_start, self._slices):
                if start >= horizon:
                    for i, c in enumerate(counts):
                        if c:
                            out[i] += c
        return out

    def quantile(self, q: float, now: Optional[float] = None) -> Optional[float]:
        return quantile_from_counts(self.window_counts(now), q)

    def to_snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Wire form shipped in heartbeat telemetry. Bucket counts travel
        sparse ({idx: n}) — a typical serve digest occupies <15 buckets."""
        counts = self.window_counts(now)
        return {
            "name": self.name,
            "tags": sorted(self.tags.items()),
            "counts": {i: c for i, c in enumerate(counts) if c},
            "count": self.count,
            "sum": self.sum,
            "min": None if self.count == 0 else self.min,
            "max": self.max,
        }


def quantile_from_counts(counts: Iterable[int], q: float) -> Optional[float]:
    """Quantile over a dense count list or sparse {idx: n} dict; None when
    empty. q in [0, 1]."""
    if isinstance(counts, dict):
        dense = [0] * _TOTAL
        for i, c in counts.items():
            dense[int(i)] += c
        counts = dense
    else:
        counts = list(counts)
    total = sum(counts)
    if total == 0:
        return None
    rank = q * (total - 1)
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen > rank:
            return _bucket_value(i)
    return _bucket_value(len(counts) - 1)


def merge_snapshots(snaps: Iterable[Dict[str, Any]]
                    ) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Dict[str, Any]]:
    """Merge digest snapshots (from any number of nodes) by (name, tags).
    Returns {key: {"counts": dense list, "count", "sum", "min", "max"}} —
    feed "counts" to quantile_from_counts. Mergeability is the whole point
    of the fixed shared bucket bounds."""
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Dict[str, Any]] = {}
    for s in snaps:
        key = (s["name"], tuple(tuple(kv) for kv in s.get("tags", ())))
        m = out.get(key)
        if m is None:
            m = {"counts": [0] * _TOTAL, "count": 0, "sum": 0.0,
                 "min": None, "max": 0.0}
            out[key] = m
        for i, c in (s.get("counts") or {}).items():
            m["counts"][int(i)] += c
        m["count"] += int(s.get("count", 0))
        m["sum"] += float(s.get("sum", 0.0))
        smin = s.get("min")
        if smin is not None and (m["min"] is None or smin < m["min"]):
            m["min"] = smin
        m["max"] = max(m["max"], float(s.get("max", 0.0)))
    return out


def merged_to_snapshots(
    merged: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Dict[str, Any]]
) -> List[Dict[str, Any]]:
    """Inverse of merge_snapshots back to wire form: a pod aggregator
    pre-merges its members' digests, then ships the merged set onward as
    ordinary snapshots (so head-side merge/quantile code is unchanged —
    merging is associative over the shared bucket bounds)."""
    out: List[Dict[str, Any]] = []
    for (name, tags), m in merged.items():
        out.append({
            "name": name,
            "tags": [list(kv) for kv in tags],
            "counts": {i: c for i, c in enumerate(m["counts"]) if c},
            "count": m["count"],
            "sum": m["sum"],
            "min": m["min"],
            "max": m["max"],
        })
    return out


# -- per-process registry ---------------------------------------------------

_digests: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Digest] = {}
_reg_lock = threading.Lock()


def enabled() -> bool:
    """Resolve the slo_digests switch (read once per engine/coordinator at
    construction — not per observation)."""
    try:
        from ..core.config import config
        return bool(config.get("slo_digests"))
    except Exception:
        return True


def digest(name: str, tags: Optional[Dict[str, str]] = None) -> Digest:
    """Get-or-create the process-wide digest for (name, tags). Cache the
    returned handle on hot paths — the lookup builds a tuple key."""
    key = (name, tuple(sorted((tags or {}).items())))
    d = _digests.get(key)
    if d is None:
        with _reg_lock:
            d = _digests.get(key)
            if d is None:
                d = Digest(name, tags)
                _digests[key] = d
    return d


def observe(name: str, value: float, tags: Optional[Dict[str, str]] = None,
            n: int = 1) -> None:
    digest(name, tags).add(value, n)


def snapshot(now: Optional[float] = None) -> List[Dict[str, Any]]:
    """All local digests in wire form (shipped with heartbeat telemetry)."""
    with _reg_lock:
        ds = list(_digests.values())
    return [d.to_snapshot(now) for d in ds if d.count]


def clear() -> None:
    with _reg_lock:
        _digests.clear()
