"""Distributed tracing: span propagation through task submit/execute.

Reference analogue: `python/ray/util/tracing/tracing_helper.py` — the
reference wraps task submission and worker execution in OpenTelemetry
spans so one request's causality chain is visible across processes. Same
shape here without the OTel dependency (zero-egress image): W3C-style
ids, a thread-local current span, automatic context injection at
`.remote()` and extraction around user-function execution
(`node_agent._invoke`), spans buffered per process and exportable as
chrome-trace events alongside the timeline (`util/timeline.py`), so one
`ray-tpu timeline` capture shows both profiling spans AND request
causality.

Usage:

    from ray_tpu.util import tracing

    with tracing.start_span("handle_request", {"route": "/chat"}):
        ref = my_task.remote(x)       # ctx injected automatically
        ray_tpu.get(ref)
    spans = tracing.get_spans()       # incl. the task's execute span
                                      # (same trace_id, parented here)

Propagation is on only while a span is active — zero overhead otherwise
(the spec field stays None)."""

from __future__ import annotations

import os
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

_local = threading.local()
_lock = threading.Lock()
_spans: List[Dict[str, Any]] = []
_MAX_SPANS = 10_000


def _now_us() -> float:
    return time.time() * 1e6


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "attrs",
                 "start_us", "end_us")

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        self.trace_id = trace_id or uuid.uuid4().hex
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.name = name
        self.attrs = dict(attrs or {})
        self.start_us = _now_us()
        self.end_us: Optional[float] = None

    def context(self) -> Dict[str, str]:
        """The wire form (W3C traceparent shape, dict-framed)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def finish(self) -> None:
        self.end_us = _now_us()
        rec = {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "attrs": self.attrs, "start_us": self.start_us,
            "end_us": self.end_us, "pid": os.getpid(),
        }
        with _lock:
            _spans.append(rec)
            if len(_spans) > _MAX_SPANS:
                del _spans[: len(_spans) - _MAX_SPANS]


def current_span() -> Optional[Span]:
    return getattr(_local, "span", None)


def current_context() -> Optional[Dict[str, str]]:
    """ctx dict to stamp into an outgoing TaskSpec (None when tracing is
    inactive on this thread — the common, zero-overhead case)."""
    span = current_span()
    return span.context() if span is not None else None


@contextmanager
def start_span(name: str, attrs: Optional[Dict[str, Any]] = None,
               context: Optional[Dict[str, str]] = None):
    """Open a span. `context` parents it under a REMOTE span (extracted
    from an incoming TaskSpec); otherwise it nests under this thread's
    current span (or starts a fresh trace)."""
    parent = current_span()
    if context is not None:
        span = Span(name, trace_id=context["trace_id"],
                    parent_id=context["span_id"], attrs=attrs)
    elif parent is not None:
        span = Span(name, trace_id=parent.trace_id,
                    parent_id=parent.span_id, attrs=attrs)
    else:
        span = Span(name, attrs=attrs)
    prev = parent
    _local.span = span
    try:
        yield span
    finally:
        span.finish()
        _local.span = prev


def get_spans(trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    with _lock:
        out = list(_spans)
    if trace_id is not None:
        out = [s for s in out if s["trace_id"] == trace_id]
    return out


def clear() -> None:
    with _lock:
        _spans.clear()


def export_to_timeline() -> int:
    """Mirror buffered spans into the chrome-trace timeline (pid lane
    'trace', tid = trace id prefix) so `ray-tpu timeline` renders request
    causality next to task/profiling spans."""
    from . import timeline

    n = 0
    for s in get_spans():
        timeline.record(
            s["name"], "X", cat="trace", ts_us=s["start_us"],
            dur_us=(s["end_us"] or s["start_us"]) - s["start_us"],
            pid="trace", tid=s["trace_id"][:8],
            args={"span": s["span_id"], "parent": s["parent_id"],
                  **{k: v for k, v in s["attrs"].items()
                     if isinstance(v, (int, float, str))}},
        )
        n += 1
    return n
