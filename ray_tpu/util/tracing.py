"""Distributed tracing: span propagation through task submit/execute.

Reference analogue: `python/ray/util/tracing/tracing_helper.py` — the
reference wraps task submission and worker execution in OpenTelemetry
spans so one request's causality chain is visible across processes. Same
shape here without the OTel dependency (zero-egress image): W3C-style
ids, a thread-local current span, automatic context injection at
`.remote()` (api.RemoteFunction / core_worker.submit_actor_task) and
extraction around user-function execution
(`node_agent._call_user_function`, `actor_process._child_main`), around
each disaggregated-serving leg (`serve/disagg.py`: `disagg.admit` /
`disagg.queue_wait` / `disagg.route` / `disagg.prefill` /
`disagg.kv_export` / `disagg.kv_migration` / `disagg.kv_import` /
`disagg.decode` — under the stream transport `disagg.kv_migration`
overlaps `disagg.prefill` in the same trace), and through the
pipeline trainer (`train/pipeline.py`): a traced `pipeline.step` fans
out into per-worker `pipeline.stage_step` spans with nested
`channel_send`/`channel_recv` spans from `core/channels.py`, so one
trace shows the whole 1F1B timeline. Spans buffer
per process; worker processes flush them to the head with their
heartbeat telemetry (`cross_host.WorkerRuntime`, ingested by
`control_plane.report_telemetry`), so `get_trace()` at the head sees one
connected tree spanning every process a request touched. They are also
exportable as chrome-trace events alongside the timeline
(`util/timeline.py`), so one `ray-tpu timeline` capture shows both
profiling spans AND request causality.

Usage:

    from ray_tpu.util import tracing

    with tracing.start_span("handle_request", {"route": "/chat"}):
        ref = my_task.remote(x)       # ctx injected automatically
        ray_tpu.get(ref)
    tree = tracing.get_trace(...)     # incl. the task's execute span
                                      # (same trace_id, parented here)

Span lifecycle invariant (machine-enforced by `ray_tpu.tools.raylint`
rule R5): a span bound manually — `maybe_begin(...)` / `Span(...)`
instead of the `start_span` context manager — must reach `finish()` on
every path, i.e. in a `finally` or via an owner that finishes it later;
a return/raise edge that skips `finish()` leaks the span out of the
telemetry flush. `finish()` is idempotent, so the fix is mechanical:
wrap the body in try/finally.

Propagation is on only while a span is active — zero overhead otherwise
(the spec field stays None). Serve entry points additionally open root
spans for a `config.trace_sample_rate` fraction of requests (default 0:
off, the zero-overhead fast path)."""

from __future__ import annotations

import os
import random
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

_local = threading.local()
_lock = threading.Lock()
_spans: List[Dict[str, Any]] = []
_total = 0  # spans ever buffered (monotone; _spans may have been trimmed)
_MAX_SPANS = 10_000
# util/flight_recorder.attach() points this at its ring so finished spans
# land in the per-process crash record; None = zero-overhead default
_flight_sink = None


def _now_us() -> float:
    return time.time() * 1e6


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "attrs",
                 "start_us", "end_us")

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        self.trace_id = trace_id or uuid.uuid4().hex
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.name = name
        self.attrs = dict(attrs or {})
        self.start_us = _now_us()
        self.end_us: Optional[float] = None

    def context(self) -> Dict[str, str]:
        """The wire form (W3C traceparent shape, dict-framed)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def finish(self) -> None:
        if self.end_us is not None:
            return  # idempotent: stream teardown paths may race
        self.end_us = _now_us()
        rec = {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "attrs": self.attrs, "start_us": self.start_us,
            "end_us": self.end_us, "pid": os.getpid(),
        }
        global _total
        with _lock:
            _spans.append(rec)
            _total += 1
            if len(_spans) > _MAX_SPANS:
                del _spans[: len(_spans) - _MAX_SPANS]
        if _flight_sink is not None:
            try:
                _flight_sink(rec)
            except Exception:
                pass  # the flight recorder must never break tracing


class _RemoteParent:
    """A remote span context installed as this thread's parent without
    recording a span (see `activate`): just enough surface for
    `start_span` / `current_context` to chain under it."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def context(self) -> Dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}


def current_span() -> Optional[Span]:
    return getattr(_local, "span", None)


def current_context() -> Optional[Dict[str, str]]:
    """ctx dict to stamp into an outgoing TaskSpec (None when tracing is
    inactive on this thread — the common, zero-overhead case)."""
    span = current_span()
    return span.context() if span is not None else None


def should_sample() -> bool:
    """Head-based sampling decision for a NEW request root
    (config.trace_sample_rate). The rate-0 default short-circuits before
    touching the RNG — the provably-zero-overhead path."""
    from ..core.config import config

    rate = float(config.trace_sample_rate)
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    return random.random() < rate


def maybe_begin(name: str, attrs: Optional[Dict[str, Any]] = None
                ) -> Optional[Span]:
    """Request-entry hook for serve surfaces: returns an OPEN span (not
    thread-current, not auto-finished — the caller owns `finish()`, via
    `activate()` for the synchronous part and a finally for streams)
    when this thread is already traced or the sampler fires; None on the
    untraced fast path."""
    parent = current_span()
    if parent is not None:
        return Span(name, trace_id=parent.trace_id,
                    parent_id=parent.span_id, attrs=attrs)
    if should_sample():
        return Span(name, attrs=attrs)
    return None


@contextmanager
def start_span(name: str, attrs: Optional[Dict[str, Any]] = None,
               context: Optional[Dict[str, str]] = None):
    """Open a span. `context` parents it under a REMOTE span (extracted
    from an incoming TaskSpec or serve request dict); otherwise it nests
    under this thread's current span (or starts a fresh trace)."""
    parent = current_span()
    if context is not None:
        span = Span(name, trace_id=context["trace_id"],
                    parent_id=context["span_id"], attrs=attrs)
    elif parent is not None:
        span = Span(name, trace_id=parent.trace_id,
                    parent_id=parent.span_id, attrs=attrs)
    else:
        span = Span(name, attrs=attrs)
    prev = parent
    _local.span = span
    try:
        yield span
    finally:
        span.finish()
        _local.span = prev


@contextmanager
def span_if_traced(name: str, attrs: Optional[Dict[str, Any]] = None,
                   context: Optional[Dict[str, str]] = None):
    """`start_span`, but only when a trace is already active — an
    explicit remote `context` or a thread-current span. The untraced
    path yields None without touching the buffer or the RNG, so hot
    paths (object pulls, channel sends, disagg legs) can instrument
    unconditionally at zero cost."""
    if context is None and getattr(_local, "span", None) is None:
        yield None
        return
    with start_span(name, attrs, context=context) as s:
        yield s


@contextmanager
def activate(span_or_ctx):
    """Make an already-open span (or a bare remote context dict) current
    on this thread WITHOUT finishing it on exit — re-entry for request
    work that resumes on other threads (stream generators, get() pool
    workers). Accepts None as a no-op so callers can write
    `with tracing.activate(maybe_begin(...)):` unconditionally."""
    if span_or_ctx is None:
        yield None
        return
    if isinstance(span_or_ctx, dict):
        span_or_ctx = _RemoteParent(span_or_ctx["trace_id"],
                                    span_or_ctx["span_id"])
    prev = current_span()
    _local.span = span_or_ctx
    try:
        yield span_or_ctx
    finally:
        _local.span = prev


def get_spans(trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    with _lock:
        out = list(_spans)
    if trace_id is not None:
        out = [s for s in out if s["trace_id"] == trace_id]
    return out


def get_trace(trace_id: str) -> List[Dict[str, Any]]:
    """The trace as a TREE: root span records (those whose parent is
    absent from the buffer) each carrying a recursively-nested
    `children` list; every level sorted by start time. `trace_id` may be
    a unique prefix (the OpenAI `X-Request-Id` embeds the full id, but
    dashboards may hold a truncation)."""
    with _lock:
        recs = [dict(s) for s in _spans
                if s["trace_id"] == trace_id
                or s["trace_id"].startswith(trace_id)]
    by_id = {s["span_id"]: s for s in recs}
    roots: List[Dict[str, Any]] = []
    for s in recs:
        s.setdefault("children", [])
    for s in recs:
        parent = by_id.get(s["parent_id"]) if s["parent_id"] else None
        if parent is not None and parent is not s:
            parent["children"].append(s)
        else:
            roots.append(s)

    def _sort(nodes: List[Dict[str, Any]]) -> None:
        nodes.sort(key=lambda n: n["start_us"])
        for n in nodes:
            _sort(n["children"])

    _sort(roots)
    return roots


def drain_since(cursor: int) -> Tuple[int, List[Dict[str, Any]]]:
    """Span records buffered after `cursor` (a value this function
    previously returned; start at 0) plus the new cursor. Read-only —
    the caller owns the cursor, so a failed flush can simply retry with
    the old one (ingest() dedupes by span_id)."""
    with _lock:
        dropped = _total - len(_spans)
        start = max(0, cursor - dropped)
        return _total, list(_spans[start:])


def ingest(records: List[Dict[str, Any]]) -> int:
    """Merge span records flushed from another process into this
    buffer (head side of telemetry federation). Deduped by span_id so a
    retried flush is harmless. Returns the number actually added."""
    if not records:
        return 0
    global _total
    added = 0
    with _lock:
        seen = {s["span_id"] for s in _spans}
        for rec in records:
            sid = rec.get("span_id")
            if sid is None or sid in seen:
                continue
            seen.add(sid)
            _spans.append(dict(rec))
            _total += 1
            added += 1
        if len(_spans) > _MAX_SPANS:
            del _spans[: len(_spans) - _MAX_SPANS]
    return added


def clear() -> None:
    with _lock:
        _spans.clear()


def export_to_timeline() -> int:
    """Mirror buffered spans into the chrome-trace timeline (one lane
    per SOURCE process: pid 'trace/<ospid>', tid = trace id prefix) so
    `ray-tpu timeline` renders request causality next to task/profiling
    spans — federated spans land in their origin process's lane."""
    from . import timeline

    n = 0
    for s in get_spans():
        timeline.record(
            s["name"], "X", cat="trace", ts_us=s["start_us"],
            dur_us=(s["end_us"] or s["start_us"]) - s["start_us"],
            pid=f"trace/{s['pid']}", tid=s["trace_id"][:8],
            args={"span": s["span_id"], "parent": s["parent_id"],
                  **{k: v for k, v in s["attrs"].items()
                     if isinstance(v, (int, float, str))}},
        )
        n += 1
    return n
