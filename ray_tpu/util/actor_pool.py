"""ActorPool (reference: `python/ray/util/actor_pool.py`): load-balance a
stream of tasks over a fixed set of actors."""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Tuple

from .. import api


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        self._future_to_actor = {}
        self._pending: List[Tuple[Callable, Any]] = []

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
        else:
            self._pending.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending)

    def get_next_unordered(self, timeout: float = None) -> Any:
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        done, _ = api.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout
        )
        if not done:
            raise TimeoutError("get_next_unordered timed out")
        ref = done[0]
        actor = self._future_to_actor.pop(ref)
        self._idle.append(actor)
        while self._pending and self._idle:
            fn, value = self._pending.pop(0)
            a = self._idle.pop()
            self._future_to_actor[fn(a, value)] = a
        return api.get(ref)

    def map_unordered(self, fn: Callable, values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def map(self, fn: Callable, values: Iterable[Any]) -> Iterator[Any]:
        refs = []
        values = list(values)
        idx = 0
        actors = list(self._idle)
        n = len(actors)
        for i, v in enumerate(values):
            refs.append(fn(actors[i % n], v))
        for ref in refs:
            yield api.get(ref)
