"""ray_tpu.util — user-facing utilities (reference: `python/ray/util/`)."""

from .actor_pool import ActorPool  # noqa: F401
from .multiprocessing import Pool  # noqa: F401
from .queue import Queue  # noqa: F401
