"""ICI torus topology model and sub-slice packing.

The TPU-native replacement for the reference's scalar resource model
(upstream ray treats accelerators as counts — `num_gpus`, custom "TPU"
resources in `python/ray/_private/accelerators/tpu.py`): here a slice is a
3D torus of chips with known coordinates, a gang request is a *shape*
(e.g. 2x2x4), and the packer allocates axis-aligned sub-boxes so collectives
ride contiguous ICI links and the torus doesn't fragment.

Known generations follow public TPU topology tables (v4/v5p are 3D tori with
4 chips/host; v5e/v6e are 2D meshes with 1-8 chips/host).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

Coord = Tuple[int, ...]


@dataclass(frozen=True)
class TpuGeneration:
    name: str
    dims: int  # torus rank (2 or 3)
    chips_per_host: int
    hbm_gib_per_chip: float
    bf16_tflops_per_chip: float


GENERATIONS: Dict[str, TpuGeneration] = {
    "v4": TpuGeneration("v4", 3, 4, 32.0, 275.0),
    "v5e": TpuGeneration("v5e", 2, 4, 16.0, 197.0),
    "v5p": TpuGeneration("v5p", 3, 4, 95.0, 459.0),
    "v6e": TpuGeneration("v6e", 2, 4, 32.0, 918.0),
}


def _prod(shape: Sequence[int]) -> int:
    n = 1
    for d in shape:
        n *= d
    return n


@dataclass(frozen=True)
class SliceTopology:
    """A physical slice: generation + chip-grid shape (+ torus wraparound)."""

    generation: str
    shape: Tuple[int, ...]
    wraparound: bool = False  # full-size slices get wraparound links

    @property
    def num_chips(self) -> int:
        return _prod(self.shape)

    @property
    def num_hosts(self) -> int:
        gen = GENERATIONS[self.generation]
        return max(1, self.num_chips // gen.chips_per_host)

    @classmethod
    def from_name(cls, name: str) -> "SliceTopology":
        """Parse accelerator names like 'v5p-16' (16 = chip count *2 for v5p
        TensorCores — we use plain chip counts: v5p-16 → 8 chips, 2x2x2)."""
        gen_name, _, count_str = name.partition("-")
        if gen_name not in GENERATIONS:
            raise ValueError(f"unknown TPU generation in {name!r}")
        gen = GENERATIONS[gen_name]
        count = int(count_str)
        chips = count // 2 if gen_name in ("v4", "v5p") else count
        shape = _default_shape(chips, gen.dims)
        return cls(gen_name, shape, wraparound=chips >= 64)

    def all_coords(self) -> List[Coord]:
        return list(itertools.product(*[range(d) for d in self.shape]))

    def host_of(self, coord: Coord) -> int:
        """Host index owning a chip coordinate: hosts own contiguous 2x2
        blocks in the x-y plane (4-chip hosts -> 2x2x1 sub-blocks on v4/v5p;
        v5e/v6e hosts likewise connect a 2x2 chip square)."""
        gen = GENERATIONS[self.generation]
        bx, by = coord[0] // 2, coord[1] // 2
        hosts_x = max(1, -(-self.shape[0] // 2))
        if gen.dims == 3:
            hosts_y = max(1, -(-self.shape[1] // 2))
            return (coord[2] * hosts_y + by) * hosts_x + bx
        return by * hosts_x + bx

    def host_partition(self) -> Dict[int, List[Coord]]:
        """host index -> chip coords. Callers registering a slice should
        check the partition is uniform (every host owns chips_per_host
        chips) before enabling topology-aware placement on it — odd-dim
        shapes produce ragged partitions that no real slice has."""
        out: Dict[int, List[Coord]] = {}
        for c in self.all_coords():
            out.setdefault(self.host_of(c), []).append(c)
        return out


def _default_shape(chips: int, dims: int) -> Tuple[int, ...]:
    """Near-cubic factorization, powers of two preferred (matches how real
    slices are provisioned: 2x2x1, 2x2x2, 2x2x4, 4x4x4, ...)."""
    if dims == 2:
        best = (1, chips)
        for a in range(1, int(chips**0.5) + 1):
            if chips % a == 0:
                best = (a, chips // a)
        return best
    best: Tuple[int, ...] = (1, 1, chips)
    for a in range(1, int(round(chips ** (1 / 3))) + 2):
        if chips % a:
            continue
        rest = chips // a
        for b in range(a, int(rest**0.5) + 1):
            if rest % b == 0:
                c = rest // b
                if c >= b >= a:
                    best = (a, b, c)
    return tuple(sorted(best))


def _normalize_rank(want: Tuple[int, ...], dims: int) -> Optional[Tuple[int, ...]]:
    """Pad a short request with 1s; squeeze 1-sized axes from a long one
    (a (2,2,1) request is a (2,2) box on a 2D mesh). None if impossible."""
    while len(want) > dims and 1 in want:
        i = want.index(1)
        want = want[:i] + want[i + 1:]
    if len(want) > dims:
        return None
    if len(want) < dims:
        want = want + (1,) * (dims - len(want))
    return want


@dataclass
class Allocation:
    """An axis-aligned sub-box of a slice granted to a gang."""

    origin: Coord
    shape: Tuple[int, ...]

    def coords(self) -> List[Coord]:
        return [
            tuple(o + d for o, d in zip(self.origin, delta))
            for delta in itertools.product(*[range(s) for s in self.shape])
        ]

    @property
    def num_chips(self) -> int:
        return _prod(self.shape)


class SubSlicePacker:
    """Allocates axis-aligned sub-boxes from a torus, minimizing fragmentation.

    Strategy: for each requested shape (tried in every axis permutation),
    scan candidate origins in lexicographic order and pick the placement
    with the tightest fit against already-allocated boxes (corner-first
    packing). This is the ICI-aware heart of gang placement — the thing the
    reference's bundle packer (`gcs_placement_group_scheduler.cc`) never had
    to do because NCCL doesn't care about torus coordinates.
    """

    def __init__(self, topology: SliceTopology):
        self.topology = topology
        self._lock = threading.RLock()
        self._free: Set[Coord] = set(topology.all_coords())
        self._allocations: Dict[int, Allocation] = {}
        self._next_id = 0

    def try_allocate(self, shape: Sequence[int]) -> Optional[Tuple[int, Allocation]]:
        want = _normalize_rank(tuple(shape), len(self.topology.shape))
        if want is None:
            raise ValueError(
                f"request shape {tuple(shape)} does not fit topology rank "
                f"{len(self.topology.shape)}"
            )
        dims = len(self.topology.shape)
        with self._lock:
            best: Optional[Allocation] = None
            best_score: Optional[Tuple] = None
            for perm in sorted(set(itertools.permutations(want))):
                if any(p > s for p, s in zip(perm, self.topology.shape)):
                    continue
                # corner-first: take the lexicographically first fit per
                # permutation, then prefer the permutation touching the
                # fewest hosts (gang stays host-local when possible)
                for origin in itertools.product(
                    *[range(s - p + 1) for p, s in zip(perm, self.topology.shape)]
                ):
                    alloc = Allocation(origin, perm)
                    coords = alloc.coords()
                    if all(c in self._free for c in coords):
                        n_hosts = len({self.topology.host_of(c) for c in coords})
                        score = (n_hosts, sum(origin), origin)
                        if best_score is None or score < best_score:
                            best, best_score = alloc, score
                        break
            if best is None:
                return None
            for c in best.coords():
                self._free.discard(c)
            alloc_id = self._next_id
            self._next_id += 1
            self._allocations[alloc_id] = best
            return alloc_id, best

    def release(self, alloc_id: int) -> None:
        with self._lock:
            alloc = self._allocations.pop(alloc_id, None)
            if alloc is not None:
                self._free.update(alloc.coords())

    def free_chips(self) -> int:
        with self._lock:
            return len(self._free)

    def hosts_for(self, alloc: Allocation) -> List[int]:
        return sorted({self.topology.host_of(c) for c in alloc.coords()})

    def fragmentation(self) -> float:
        """1 - (largest allocatable cube / free chips). 0 = perfectly packed."""
        with self._lock:
            free = len(self._free)
        if free == 0:
            return 0.0
        # probe the largest power-of-two cube that still fits
        dims = len(self.topology.shape)
        size = 1
        while True:
            probe = tuple([size * 2] * dims)
            if _prod(probe) > free:
                break
            if self._fits(probe):
                size *= 2
            else:
                break
        return 1.0 - (size**dims) / free

    def _fits(self, shape: Tuple[int, ...]) -> bool:
        with self._lock:
            for origin in itertools.product(
                *[range(s - p + 1) for p, s in zip(shape, self.topology.shape)]
            ):
                alloc = Allocation(origin, shape)
                if all(c in self._free for c in alloc.coords()):
                    return True
        return False

    def could_ever_fit(self, shape: Sequence[int]) -> bool:
        """True if some axis permutation of `shape` fits an EMPTY torus —
        the feasibility test for queueing vs rejecting a gang request."""
        want = _normalize_rank(tuple(shape), len(self.topology.shape))
        if want is None:
            return False
        return any(
            all(p <= s for p, s in zip(perm, self.topology.shape))
            for perm in itertools.permutations(want)
        )


@dataclass
class SliceInfo:
    """A registered physical slice: topology + packer + host->node map.

    The control plane keeps one of these per TPU slice so gang placement
    (sched/placement_group.py) can allocate contiguous sub-boxes and pin
    bundles to the hosts that own the allocated chips.
    """

    slice_id: object  # SliceID (kept untyped here: core imports this module)
    topology: SliceTopology
    packer: SubSlicePacker = None  # type: ignore[assignment]
    hosts: Dict[int, object] = field(default_factory=dict)  # host idx -> NodeID

    def __post_init__(self):
        if self.packer is None:
            self.packer = SubSlicePacker(self.topology)
