"""Placement groups: gang resource reservation across nodes.

Equivalent of the reference's placement groups (upstream ray
`python/ray/util/placement_group.py :: placement_group()`, GCS-side
`gcs_placement_group_manager.cc` / `gcs_placement_group_scheduler.cc` with
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD bundle policies): bundles of resources
are reserved atomically on chosen nodes; tasks/actors scheduled with a
``PlacementGroupSchedulingStrategy`` consume from the bundle, not the node.

TPU-native addition: a bundle may be a ``TopologyRequest`` — the group then
reserves a contiguous ICI sub-slice via ``SubSlicePacker`` so the gang's
collectives stay on torus-adjacent links.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

from ..core import core_worker as _cw
from ..core.control_plane import NodeState
from ..core.ids import NodeID, PlacementGroupID
from ..core.logging import get_logger
from ..core.node_agent import ResourceTracker
from ..core.task_spec import TopologyRequest

logger = get_logger("placement_group")

Bundle = Union[Dict[str, float], TopologyRequest]


class PlacementGroupError(RuntimeError):
    pass


@dataclass
class PlacementGroup:
    id: PlacementGroupID
    bundles: List[Dict[str, float]]
    strategy: str
    bundle_nodes: List[NodeID] = field(default_factory=list)
    created: bool = False
    # per-bundle usage trackers (tasks consume bundle capacity, not node)
    _bundle_trackers: List[ResourceTracker] = field(default_factory=list)

    def ready(self, timeout: Optional[float] = 30.0) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self.created:
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.005)
        return True

    def bundle_node(self, index: int) -> NodeID:
        return self.bundle_nodes[index]

    def try_acquire(self, bundle_index: int, demand: Dict[str, float]) -> bool:
        if not self.created:
            return False
        return self._bundle_trackers[bundle_index].try_acquire(demand)

    def release(self, bundle_index: int, demand: Dict[str, float]) -> None:
        if 0 <= bundle_index < len(self._bundle_trackers):
            self._bundle_trackers[bundle_index].release(demand)


def _normalize_bundle(b: Bundle) -> Dict[str, float]:
    if isinstance(b, TopologyRequest):
        return {"TPU": float(b.num_chips)}
    return dict(b)


class PlacementGroupManager:
    """Reserves bundles on nodes and keeps the (pg, bundle) -> node table the
    cluster scheduler consults. Lives beside the Runtime (GCS role)."""

    def __init__(self, runtime) -> None:
        self._rt = runtime
        self._lock = threading.Lock()
        self._groups: Dict[PlacementGroupID, PlacementGroup] = {}

    def create(self, bundles: Sequence[Bundle], strategy: str = "PACK") -> PlacementGroup:
        if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
            raise ValueError(f"unknown placement strategy: {strategy}")
        if not bundles:
            raise ValueError("placement group needs at least one bundle")
        norm = [_normalize_bundle(b) for b in bundles]
        pg = PlacementGroup(PlacementGroupID.generate(), norm, strategy)
        placement = self._place_bundles(norm, strategy)
        if placement is None:
            raise PlacementGroupError(
                f"cannot place {len(norm)} bundles with strategy {strategy}: "
                "insufficient cluster resources"
            )
        # acquire atomically: roll back on partial failure
        acquired: List[Tuple[NodeID, Dict[str, float]]] = []
        ok = True
        for bundle, node_id in zip(norm, placement):
            agent = self._rt.agents.get(node_id)
            if agent is None or not agent.resources.try_acquire(bundle):
                ok = False
                break
            acquired.append((node_id, bundle))
        if not ok:
            for node_id, bundle in acquired:
                agent = self._rt.agents.get(node_id)
                if agent is not None:
                    agent.resources.release(bundle)
            raise PlacementGroupError("bundle reservation raced; retry")
        pg.bundle_nodes = list(placement)
        pg._bundle_trackers = [ResourceTracker(b) for b in norm]
        pg.created = True
        with self._lock:
            self._groups[pg.id] = pg
        for i, node_id in enumerate(placement):
            self._rt.pg_table[(pg.id, i)] = node_id
        self._rt._kick_scheduler()
        logger.info("placement group %s created: %s bundles via %s",
                    pg.id.hex()[:8], len(norm), strategy)
        return pg

    def remove(self, pg: PlacementGroup) -> None:
        with self._lock:
            stored = self._groups.pop(pg.id, None)
        if stored is None:
            return
        for bundle, node_id in zip(stored.bundles, stored.bundle_nodes):
            agent = self._rt.agents.get(node_id)
            if agent is not None:
                agent.resources.release(bundle)
        for i in range(len(stored.bundles)):
            self._rt.pg_table.pop((pg.id, i), None)
        stored.created = False

    def get(self, pg_id: PlacementGroupID) -> Optional[PlacementGroup]:
        with self._lock:
            return self._groups.get(pg_id)

    # -- placement ----------------------------------------------------------
    def _place_bundles(
        self, bundles: List[Dict[str, float]], strategy: str
    ) -> Optional[List[NodeID]]:
        nodes = [n for n in self._rt.control_plane.alive_nodes()]
        if not nodes:
            return None
        # work over a copy of each node's available view for what-if packing
        avail: Dict[NodeID, Dict[str, float]] = {}
        for n in nodes:
            agent = self._rt.agents.get(n.node_id)
            avail[n.node_id] = agent.resources.available() if agent else dict(n.resources_available)

        def fits(node_id: NodeID, bundle: Dict[str, float]) -> bool:
            a = avail[node_id]
            return all(a.get(k, 0.0) >= v - 1e-9 for k, v in bundle.items())

        def take(node_id: NodeID, bundle: Dict[str, float]) -> None:
            a = avail[node_id]
            for k, v in bundle.items():
                a[k] = a.get(k, 0.0) - v

        order = [n.node_id for n in nodes]
        placement: List[NodeID] = []

        if strategy in ("PACK", "STRICT_PACK"):
            if strategy == "STRICT_PACK":
                for node_id in order:
                    trial = dict(avail[node_id])
                    ok = True
                    for b in bundles:
                        if not all(trial.get(k, 0.0) >= v - 1e-9 for k, v in b.items()):
                            ok = False
                            break
                        for k, v in b.items():
                            trial[k] = trial.get(k, 0.0) - v
                    if ok:
                        return [node_id] * len(bundles)
                return None
            for b in bundles:
                chosen = None
                # prefer nodes already used by this group (packing)
                for node_id in list(dict.fromkeys(placement)) + order:
                    if fits(node_id, b):
                        chosen = node_id
                        break
                if chosen is None:
                    return None
                take(chosen, b)
                placement.append(chosen)
            return placement

        # SPREAD / STRICT_SPREAD
        for b in bundles:
            chosen = None
            unused = [n for n in order if n not in placement]
            for node_id in unused + ([] if strategy == "STRICT_SPREAD" else order):
                if fits(node_id, b):
                    chosen = node_id
                    break
            if chosen is None:
                return None
            take(chosen, b)
            placement.append(chosen)
        return placement


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def placement_group(
    bundles: Sequence[Bundle], strategy: str = "PACK"
) -> PlacementGroup:
    rt = _cw.get_runtime()
    return rt.pg_manager.create(bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    rt = _cw.get_runtime()
    rt.pg_manager.remove(pg)
