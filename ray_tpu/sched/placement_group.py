"""Placement groups: gang resource reservation across nodes.

Equivalent of the reference's placement groups (upstream ray
`python/ray/util/placement_group.py :: placement_group()`, GCS-side
`gcs_placement_group_manager.cc` / `gcs_placement_group_scheduler.cc` with
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD bundle policies): bundles of resources
are reserved atomically on chosen nodes; tasks/actors scheduled with a
``PlacementGroupSchedulingStrategy`` consume from the bundle, not the node.

TPU-native addition: a bundle may be a ``TopologyRequest`` — the group then
reserves a contiguous ICI sub-box via ``SubSlicePacker`` on a registered
slice, expands into one bundle per TPU host owning the box's chips (each
pinned to that host), and exposes the allocation's torus coordinates so the
gang can lay its mesh axes along physical ICI links. A topology request that
is feasible on some registered slice but currently blocked by other groups
QUEUES (``created=False``) and materializes when capacity frees.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

from ..core import core_worker as _cw
from ..core.ids import NodeID, PlacementGroupID
from ..core.logging import get_logger
from ..core.node_agent import ResourceTracker
from ..core.task_spec import TopologyRequest
from .topology import SliceInfo

logger = get_logger("placement_group")

Bundle = Union[Dict[str, float], TopologyRequest]

# CPU attached to each expanded per-host topology bundle so the gang's
# worker actor (one per host) can be scheduled into it.
_TOPOLOGY_BUNDLE_CPU = 1.0


class PlacementGroupError(RuntimeError):
    pass


@dataclass
class TopologyAllocation:
    """A granted sub-box: which slice, where in the torus, and which of the
    group's bundles map to which hosts/chip-coordinates."""

    slice_id: object
    origin: Tuple[int, ...]
    shape: Tuple[int, ...]
    bundle_indices: List[int] = field(default_factory=list)
    # parallel to bundle_indices: chip coords owned by that bundle's host
    coords_per_bundle: List[List[Tuple[int, ...]]] = field(default_factory=list)
    _alloc_id: int = -1


@dataclass
class PlacementGroup:
    id: PlacementGroupID
    bundles: List[Dict[str, float]]
    strategy: str
    bundle_nodes: List[NodeID] = field(default_factory=list)
    created: bool = False
    # the original request (kept for queued materialization)
    request: List[Bundle] = field(default_factory=list)
    # ICI sub-box allocations backing TopologyRequest bundles
    topology_allocations: List[TopologyAllocation] = field(default_factory=list)
    # per-bundle usage trackers (tasks consume bundle capacity, not node)
    _bundle_trackers: List[ResourceTracker] = field(default_factory=list)

    def ready(self, timeout: Optional[float] = 30.0) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self.created:
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.005)
        return True

    def bundle_node(self, index: int) -> NodeID:
        return self.bundle_nodes[index]

    def try_acquire(self, bundle_index: int, demand: Dict[str, float]) -> bool:
        if not self.created:
            return False
        return self._bundle_trackers[bundle_index].try_acquire(demand)

    def release(self, bundle_index: int, demand: Dict[str, float]) -> None:
        if 0 <= bundle_index < len(self._bundle_trackers):
            self._bundle_trackers[bundle_index].release(demand)


class PlacementGroupManager:
    """Reserves bundles on nodes and keeps the (pg, bundle) -> node table the
    cluster scheduler consults. Lives beside the Runtime (GCS role)."""

    def __init__(self, runtime) -> None:
        self._rt = runtime
        # One reentrant lock serializes create/materialize/remove/retry:
        # materialization touches node ledgers + packers + tables, and a
        # remove() racing a queued-group retry could otherwise resurrect a
        # just-removed group with permanently-leaked reservations.
        self._lock = threading.RLock()
        self._groups: Dict[PlacementGroupID, PlacementGroup] = {}
        # topology groups waiting for packer capacity, FIFO
        self._queued: List[PlacementGroup] = []

    def create(self, bundles: Sequence[Bundle], strategy: str = "PACK") -> PlacementGroup:
        if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
            raise ValueError(f"unknown placement strategy: {strategy}")
        if not bundles:
            raise ValueError("placement group needs at least one bundle")
        pg = PlacementGroup(
            PlacementGroupID.generate(), [], strategy, request=list(bundles)
        )
        with self._lock:
            if self._materialize(pg):
                return pg
            has_topology = any(isinstance(b, TopologyRequest) for b in bundles)
            if has_topology and self._topology_feasible(bundles):
                # blocked by current occupancy, not by cluster shape: queue
                # until another group releases chips.
                self._queued.append(pg)
                self._groups[pg.id] = pg
                logger.info(
                    "placement group %s queued (topology busy)", pg.id.hex()[:8]
                )
                return pg
        raise PlacementGroupError(
            f"cannot place {len(bundles)} bundles with strategy {strategy}: "
            + ("no registered slice fits the topology request"
               if has_topology else "insufficient cluster resources")
        )

    def remove(self, pg: PlacementGroup) -> None:
        with self._lock:
            stored = self._groups.pop(pg.id, None)
            if stored in self._queued:
                self._queued.remove(stored)
                stored.created = False
                return
            if stored is None:
                return
            for bundle, node_id in zip(stored.bundles, stored.bundle_nodes):
                agent = self._rt.agents.get(node_id)
                if agent is not None:
                    agent.resources.release(bundle)
            for alloc in stored.topology_allocations:
                info = self._rt.slices.get(alloc.slice_id)
                if info is not None:
                    info.packer.release(alloc._alloc_id)
            for i in range(len(stored.bundles)):
                self._rt.pg_table.pop((pg.id, i), None)
            stored.created = False
            self._retry_queued()

    def get(self, pg_id: PlacementGroupID) -> Optional[PlacementGroup]:
        with self._lock:
            return self._groups.get(pg_id)

    # -- materialization ----------------------------------------------------

    def _materialize(self, pg: PlacementGroup) -> bool:
        """Expand the request (allocating ICI sub-boxes), place, and acquire
        atomically. On any failure everything is rolled back and the pg is
        left un-created."""
        expanded: List[Dict[str, float]] = []
        pins: List[Optional[NodeID]] = []
        allocations: List[TopologyAllocation] = []

        def rollback_allocs() -> None:
            for alloc in allocations:
                info = self._rt.slices.get(alloc.slice_id)
                if info is not None:
                    info.packer.release(alloc._alloc_id)

        for b in pg.request:
            if isinstance(b, TopologyRequest):
                got = self._allocate_topology(b)
                if got is None:
                    rollback_allocs()
                    return False
                info, alloc_id, alloc = got
                topo_alloc = TopologyAllocation(
                    slice_id=info.slice_id,
                    origin=alloc.origin,
                    shape=alloc.shape,
                    _alloc_id=alloc_id,
                )
                host_coords: Dict[int, List[Tuple[int, ...]]] = {}
                for c in alloc.coords():
                    host_coords.setdefault(info.topology.host_of(c), []).append(c)
                for h in sorted(host_coords):
                    node_id = info.hosts.get(h)
                    if node_id is None:
                        rollback_allocs()
                        info.packer.release(alloc_id)
                        return False
                    topo_alloc.bundle_indices.append(len(expanded))
                    topo_alloc.coords_per_bundle.append(sorted(host_coords[h]))
                    expanded.append({
                        "TPU": float(len(host_coords[h])),
                        "CPU": _TOPOLOGY_BUNDLE_CPU,
                    })
                    pins.append(node_id)
                allocations.append(topo_alloc)
            else:
                expanded.append(dict(b))
                pins.append(None)

        placement = self._place_bundles(expanded, pins, pg.strategy)
        if placement is None:
            rollback_allocs()
            return False
        # acquire atomically: roll back on partial failure
        acquired: List[Tuple[NodeID, Dict[str, float]]] = []
        ok = True
        for bundle, node_id in zip(expanded, placement):
            agent = self._rt.agents.get(node_id)
            if agent is None or not agent.resources.try_acquire(bundle):
                ok = False
                break
            acquired.append((node_id, bundle))
        if not ok:
            for node_id, bundle in acquired:
                agent = self._rt.agents.get(node_id)
                if agent is not None:
                    agent.resources.release(bundle)
            rollback_allocs()
            return False
        pg.bundles = expanded
        pg.bundle_nodes = list(placement)
        pg.topology_allocations = allocations
        pg._bundle_trackers = [ResourceTracker(b) for b in expanded]
        pg.created = True
        with self._lock:
            self._groups[pg.id] = pg
        for i, node_id in enumerate(placement):
            self._rt.pg_table[(pg.id, i)] = node_id
        self._rt._kick_scheduler()
        logger.info(
            "placement group %s created: %s bundles via %s%s",
            pg.id.hex()[:8], len(expanded), pg.strategy,
            f" ({len(allocations)} ICI sub-box)" if allocations else "",
        )
        return True

    def _retry_queued(self) -> None:
        with self._lock:
            for pg in list(self._queued):
                if self._materialize(pg):
                    self._queued.remove(pg)
                    logger.info(
                        "queued placement group %s materialized", pg.id.hex()[:8]
                    )

    # -- topology allocation ------------------------------------------------

    def _allocate_topology(self, req: TopologyRequest):
        """Try every registered slice (fullest-first so small gangs don't
        fragment empty slices) for a contiguous sub-box."""
        slices: List[SliceInfo] = list(self._rt.slices.values())
        slices.sort(key=lambda s: s.packer.free_chips())
        for info in slices:
            try:
                got = info.packer.try_allocate(req.shape)
            except ValueError:  # rank impossible for this slice's torus
                continue
            if got is not None:
                alloc_id, alloc = got
                return info, alloc_id, alloc
        return None

    def _topology_feasible(self, bundles: Sequence[Bundle]) -> bool:
        return all(
            any(
                info.packer.could_ever_fit(b.shape)
                for info in self._rt.slices.values()
            )
            for b in bundles
            if isinstance(b, TopologyRequest)
        )

    # -- placement ----------------------------------------------------------
    def _place_bundles(
        self,
        bundles: List[Dict[str, float]],
        pins: List[Optional[NodeID]],
        strategy: str,
    ) -> Optional[List[NodeID]]:
        nodes = [n for n in self._rt.control_plane.alive_nodes()]
        if not nodes:
            return None
        # work over a copy of each node's available view for what-if packing
        avail: Dict[NodeID, Dict[str, float]] = {}
        for n in nodes:
            agent = self._rt.agents.get(n.node_id)
            avail[n.node_id] = agent.resources.available() if agent else dict(n.resources_available)

        def fits(node_id: NodeID, bundle: Dict[str, float]) -> bool:
            a = avail.get(node_id)
            if a is None:
                return False
            return all(a.get(k, 0.0) >= v - 1e-9 for k, v in bundle.items())

        def take(node_id: NodeID, bundle: Dict[str, float]) -> None:
            a = avail[node_id]
            for k, v in bundle.items():
                a[k] = a.get(k, 0.0) - v

        # pinned bundles (topology hosts) are authoritative for any strategy
        placement: List[Optional[NodeID]] = [None] * len(bundles)
        for i, (b, pin) in enumerate(zip(bundles, pins)):
            if pin is None:
                continue
            if not fits(pin, b):
                return None
            take(pin, b)
            placement[i] = pin

        free_idx = [i for i, p in enumerate(placement) if p is None]
        if not free_idx:
            return placement  # type: ignore[return-value]
        order = [n.node_id for n in nodes]

        if strategy in ("PACK", "STRICT_PACK"):
            if strategy == "STRICT_PACK":
                for node_id in order:
                    trial = dict(avail[node_id])
                    ok = True
                    for i in free_idx:
                        b = bundles[i]
                        if not all(trial.get(k, 0.0) >= v - 1e-9 for k, v in b.items()):
                            ok = False
                            break
                        for k, v in b.items():
                            trial[k] = trial.get(k, 0.0) - v
                    if ok:
                        for i in free_idx:
                            placement[i] = node_id
                        return placement  # type: ignore[return-value]
                return None
            chosen_so_far: List[NodeID] = []
            for i in free_idx:
                b = bundles[i]
                chosen = None
                # prefer nodes already used by this group (packing)
                for node_id in list(dict.fromkeys(chosen_so_far)) + order:
                    if fits(node_id, b):
                        chosen = node_id
                        break
                if chosen is None:
                    return None
                take(chosen, b)
                placement[i] = chosen
                chosen_so_far.append(chosen)
            return placement  # type: ignore[return-value]

        # SPREAD / STRICT_SPREAD
        used: List[NodeID] = []
        for i in free_idx:
            b = bundles[i]
            chosen = None
            unused = [n for n in order if n not in used]
            for node_id in unused + ([] if strategy == "STRICT_SPREAD" else order):
                if fits(node_id, b):
                    chosen = node_id
                    break
            if chosen is None:
                return None
            take(chosen, b)
            placement[i] = chosen
            used.append(chosen)
        return placement  # type: ignore[return-value]


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def placement_group(
    bundles: Sequence[Bundle], strategy: str = "PACK"
) -> PlacementGroup:
    rt = _cw.get_runtime()
    return rt.pg_manager.create(bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    rt = _cw.get_runtime()
    rt.pg_manager.remove(pg)
