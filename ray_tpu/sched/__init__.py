"""Topology-aware scheduling: ICI torus model, sub-slice packing, gangs."""

from .placement_group import (  # noqa: F401
    PlacementGroup,
    PlacementGroupError,
    placement_group,
    remove_placement_group,
)
from .topology import (  # noqa: F401
    GENERATIONS,
    Allocation,
    SliceTopology,
    SubSlicePacker,
    TpuGeneration,
)
