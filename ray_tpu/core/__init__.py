"""Core runtime: ids, config, control plane, scheduler, object store, workers."""

from .config import config  # noqa: F401
from .ids import (  # noqa: F401
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    SliceID,
    TaskID,
    WorkerID,
)
