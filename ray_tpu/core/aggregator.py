"""Per-pod telemetry aggregator: head ingest O(pods), not O(nodes).

The shared-service decomposition argument (arXiv:2210.14826) applied to
this runtime's federated planes: every node in a pod reports heartbeats,
metric snapshots, SLO digests, profiler samples and object-ledger rows to
its pod's ``PodAggregator``, which pre-merges and forwards ONE summarized
report per flush period to the head —

- heartbeats    → one ``heartbeat_bulk`` RPC carrying the whole pod
                  (alive verdicts fan back out to the members),
- SLO digests   → ``slo.merge_snapshots`` then back to wire form
                  (merging is associative over the shared bucket bounds,
                  so head-side quantile code is unchanged),
- metrics       → counters sum by (name, sample, tags), gauges last-wins,
- profiles      → ``profiler.merge_collapsed`` (identical stacks add),
- ledger rows / channel cursors → concatenated / keyed by node.

The aggregator is transport-agnostic: ``control_plane`` may be the head's
in-process ControlPlane, a ``RemoteControlPlane``, or the federated
``ShardedControlPlane`` — it only needs ``heartbeat_bulk`` and
``report_telemetry``. It can also be served over RPC as a standalone
per-pod service (``serve()``), with its own raylint-R3-checked registry.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ..util import profiler, slo
from .logging import get_logger
from .metrics import Counter

logger = get_logger("aggregator")

_agg_flushes = Counter(
    "aggregator_flushes_total",
    "Pod-aggregator flushes forwarded to the head",
)
_agg_reports_absorbed = Counter(
    "aggregator_reports_absorbed_total",
    "Per-node reports absorbed into pod-level summaries (head RPCs saved)",
)

# the aggregator's served surface when run as a standalone pod service;
# everything on it is absorbing (bulk-ingest with replace/merge semantics),
# so the whole registry is idempotent
_AGG_ALLOWED_METHODS: Set[str] = {
    "ingest_heartbeat", "ingest_telemetry", "ingest_profile",
    "flush", "pod_info", "subscribe",
}
_AGG_IDEMPOTENT_METHODS: Set[str] = {
    "ingest_heartbeat", "ingest_telemetry", "ingest_profile",
    "flush", "pod_info", "subscribe",
}


def merge_metric_snapshots(
    per_node: List[List[Dict[str, Any]]]
) -> List[Dict[str, Any]]:
    """Merge registry.snapshot() lists from many nodes into one: counter
    samples sum by (metric, sample name, tags); gauges and everything else
    last-writer-wins (they are point-in-time readings — summing a gauge
    across nodes would invent capacity)."""
    merged: Dict[str, Dict[str, Any]] = {}
    samples: Dict[str, Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]] = {}
    for snap in per_node:
        for metric in snap or []:
            name = metric["name"]
            m = merged.get(name)
            if m is None:
                m = {"name": name, "kind": metric.get("kind", "gauge"),
                     "description": metric.get("description", "")}
                merged[name] = m
                samples[name] = {}
            summing = m["kind"] == "counter"
            for sname, tags, value in metric.get("samples", []):
                key = (sname, tuple(tuple(kv) for kv in tags))
                if summing:
                    samples[name][key] = samples[name].get(key, 0.0) + float(value)
                else:
                    samples[name][key] = float(value)
    out = []
    for name, m in merged.items():
        m["samples"] = [(sname, [list(kv) for kv in tags], value)
                        for (sname, tags), value in samples[name].items()]
        out.append(m)
    return out


class PodAggregator:
    """Pre-merges one pod's reports; ``flush()`` forwards the summary.

    Thread-safe: members ingest concurrently, flush swaps the buffers out
    under the lock and merges outside it."""

    def __init__(self, pod_id: str, control_plane,
                 flush_period_s: Optional[float] = None):
        from .config import config

        self.pod_id = str(pod_id)
        self._cp = control_plane
        self._period = (float(flush_period_s) if flush_period_s is not None
                        else float(config.telemetry_report_period_s))
        self._lock = threading.Lock()
        self._beats: Dict[Any, Optional[Dict[str, float]]] = {}
        self._verdicts: Dict[str, bool] = {}
        self._telemetry: Dict[str, Dict[str, Any]] = {}
        self._profile: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- member-facing ingest ----------------------------------------------
    def ingest_heartbeat(self, node_id,
                         resources_available: Optional[Dict[str, float]] = None
                         ) -> bool:
        """Same contract as ControlPlane.heartbeat, answered from the pod:
        the verdict is the head's reply to the LAST bulk flush (optimistic
        True for a node the head hasn't judged yet). A reaped node learns
        it is dead at most one flush period late — within the head's
        health timeout for any sane configuration."""
        with self._lock:
            self._beats[node_id] = (dict(resources_available)
                                    if resources_available is not None else None)
            _agg_reports_absorbed.inc()
            return self._verdicts.get(node_id.hex(), True)

    def ingest_telemetry(self, node_id_hex: str, role: str = "worker",
                         metrics: Optional[List[Dict[str, Any]]] = None,
                         digests: Optional[List[Dict[str, Any]]] = None,
                         objects: Optional[List[Dict[str, Any]]] = None,
                         channels: Optional[Dict[str, float]] = None) -> bool:
        """Replace-not-append per node, mirroring report_telemetry: None
        keeps the node's previous field (delta-encoded senders)."""
        with self._lock:
            prev = self._telemetry.get(node_id_hex) or {}
            self._telemetry[node_id_hex] = {
                "role": role,
                "metrics": metrics if metrics is not None
                else prev.get("metrics", []),
                "digests": digests if digests is not None
                else prev.get("digests", []),
                "objects": objects if objects is not None
                else prev.get("objects", []),
                "channels": channels if channels is not None
                else prev.get("channels", {}),
            }
            _agg_reports_absorbed.inc()
            return True

    def ingest_profile(self, collapsed: Dict[str, int]) -> bool:
        with self._lock:
            self._profile = profiler.merge_collapsed(self._profile, collapsed)
            _agg_reports_absorbed.inc()
            return True

    def pod_info(self) -> Dict[str, Any]:
        with self._lock:
            return {"pod_id": self.pod_id, "members": len(self._beats),
                    "reporting": len(self._telemetry)}

    # -- head-facing flush --------------------------------------------------
    def flush(self) -> bool:
        """One heartbeat_bulk + one report_telemetry for the whole pod."""
        with self._lock:
            beats = list(self._beats.items())
            self._beats.clear()
            # telemetry cache is kept (replace semantics per node); the
            # merged profile stays pod-local, served via merged_profile()
            tel_view = {k: dict(v) for k, v in self._telemetry.items()}
        if beats:
            try:
                verdicts = self._cp.heartbeat_bulk(beats)
            except Exception:
                logger.warning("pod %s heartbeat_bulk failed", self.pod_id,
                               exc_info=True)
                # leave verdicts as-is: members keep their last answer
                # rather than all flapping to dead on a head blip
                verdicts = {}
            with self._lock:
                self._verdicts.update(verdicts)
        merged_digests = slo.merged_to_snapshots(slo.merge_snapshots(
            [d for t in tel_view.values() for d in t.get("digests", [])]))
        merged_metrics = merge_metric_snapshots(
            [t.get("metrics", []) for t in tel_view.values()])
        objects = [row for t in tel_view.values()
                   for row in t.get("objects", [])]
        channels: Dict[str, float] = {}
        for t in tel_view.values():
            channels.update(t.get("channels", {}))
        try:
            self._cp.report_telemetry(
                f"pod:{self.pod_id}", role="pod",
                metrics=merged_metrics, digests=merged_digests,
                objects=objects, channels=channels)
            _agg_flushes.inc()
        except Exception:
            logger.warning("pod %s telemetry flush failed", self.pod_id,
                           exc_info=True)
            return False
        return True

    def merged_profile(self) -> Dict[str, int]:
        """The pod's merged flamegraph (profiler.merge_collapsed of every
        member ingest) — the profile plane fetches this per pod instead of
        per node."""
        with self._lock:
            return dict(self._profile)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "PodAggregator":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"pod-agg-{self.pod_id}")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._period):
            self.flush()

    def stop(self, final_flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if final_flush:
            self.flush()

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """Expose the aggregator as a standalone pod service (members dial
        it instead of the head)."""
        from .control_plane import Pubsub
        from .rpc import ControlPlaneServer

        if not hasattr(self, "pubsub"):
            self.pubsub = Pubsub()  # handler contract for served objects
        return ControlPlaneServer(self, host=host, port=port,
                                  allowed_methods=_AGG_ALLOWED_METHODS)
