"""Object-plane observability: cluster-wide object ledger, per-edge
transfer-flow accounting, and leak/staleness detection.

Reference analogue: upstream ray's `ray memory` / object-store dashboard
(per-object reference tables over Plasma, `src/ray/core_worker/
reference_count.cc` joined with the object directory) and the Pathways
argument that a centralized view of resource state is what lets the
orchestration layer make globally good transfer decisions. Three planes,
one module:

* **Ledger** — every store entry carries creator/pin/last-access metadata
  (`object_store._Entry`, `shm_store._ShmMeta`); each store renders a
  bounded largest-first snapshot (`snapshot_store`) that worker runtimes
  ship on heartbeat telemetry (`cross_host._maybe_report_telemetry` →
  `control_plane.report_telemetry(objects=...)`). The head joins those
  snapshots with its `ReferenceCounter` counts and `ObjectDirectory`
  locations (`collect_objects`) to answer "every live object, where it
  lives, who holds it, why" cluster-wide.
* **Flow accounting** — `record_flow` tags byte/transfer counters with
  `(src, dst, path)` at exactly the sites that increment
  `object_pull_bytes` (native / chunked / stripe in object_transfer.py)
  plus remote channel sends (channels.py), so the per-edge sums are
  conservative against the pull totals. Window bandwidth gauges
  (`object_flow_window_bps`) ride the same tags; everything federates
  through the ordinary metrics snapshot, and `collect_flows` folds the
  cluster's families into one matrix.
* **Leak sweep** — `sweep` (driven from the head monitor loop) flags
  pinned/escaped objects with zero live refs past `object_leak_age_s`,
  directory entries pointing at non-ALIVE nodes, and pull-through cache
  bytes never re-hit, re-asserting `object_leak` alerts through
  `core/health.py::HealthPlane.inject` each pass (injected alerts expire
  unless re-asserted) and publishing `object_leaks{kind}` gauges.

Everything here is gated on `config.object_ledger` (cached ~1s —
`reload_enabled()` after toggling mid-process, as the bench overhead
suite does).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .config import config
from .logging import get_logger
from .metrics import Counter, Gauge

logger = get_logger("object_ledger")

# -- pin-reason taxonomy ----------------------------------------------------
# Why is this object held alive? (README "Object plane introspection")
PIN_USER_PUT = "user_put"            # driver ray_tpu.put(); freed by ref GC
PIN_CACHE = "cache"                  # pull-through replica on a puller node
PIN_CHANNEL = "channel"              # staged/held for a DistChannel edge
PIN_ESCAPED = "serialized_escape"    # ref pickled out; exempt from auto-free
PIN_INGEST = "ingest_cache"          # ingest-service preprocessed-block cache
PIN_REASONS = (PIN_USER_PUT, PIN_CACHE, PIN_CHANNEL, PIN_ESCAPED, PIN_INGEST)

LEAK_KINDS = ("pinned_no_refs", "dead_node_location", "cold_cache")

_flow_bytes = Counter(
    "object_flow_bytes",
    "Bytes moved per transfer edge, tagged (src, dst, path): path is "
    "native/chunked/stripe for object pulls (recorded puller-side at the "
    "same sites as object_pull_bytes, so the sums reconcile) and channel "
    "for remote DistChannel sends (recorded sender-side).")
_flow_transfers = Counter(
    "object_flow_transfers",
    "Completed transfers per (src, dst, path) edge (one per pulled "
    "object / stripe / channel frame, not per chunk).")
_flow_window_bps = Gauge(
    "object_flow_window_bps",
    "Per-edge bandwidth over the last config.object_flow_window_s "
    "seconds, tagged (src, dst, path) like object_flow_bytes.")
_store_live_gauge = Gauge(
    "object_store_live_bytes",
    "Live bytes per store, tagged (node, store=memory|shm); refreshed "
    "at every ledger snapshot (telemetry flush / objects API hit).")
_leaks_gauge = Gauge(
    "object_leaks",
    "Objects flagged by the head-side leak sweep, by kind "
    "(pinned_no_refs / dead_node_location / cold_cache).")
_leaked_bytes_gauge = Gauge(
    "object_leaked_bytes",
    "Bytes held by objects the leak sweep flagged, by kind.")

# -- process-level node identity -------------------------------------------

_local_node = ""


def set_local_node(node_hex: str) -> None:
    """Record this process's node identity (dst side of pull edges, src
    side of channel edges). Head runtimes set their driver node; worker
    runtimes set theirs on join."""
    global _local_node
    _local_node = node_hex or ""


def local_node() -> str:
    return _local_node


# -- enabled flag (cached: record_flow sits on per-chunk hot paths) ---------

_enabled_cache: List[Any] = [True, 0.0]


def enabled() -> bool:
    now = time.monotonic()
    if now - _enabled_cache[1] > 1.0:
        try:
            _enabled_cache[0] = bool(config.object_ledger)
        except Exception:  # noqa: BLE001 — observability never breaks a pull
            _enabled_cache[0] = True
        _enabled_cache[1] = now
    return _enabled_cache[0]


def reload_enabled() -> None:
    """Invalidate the cached config.object_ledger value (call after
    toggling the flag mid-process, e.g. the bench overhead suite)."""
    _enabled_cache[1] = 0.0


# -- transfer-peer map (address -> node hex) --------------------------------

_peer_lock = threading.Lock()
_peer_nodes: Dict[str, str] = {}


def note_peer(addr: str, node_hex: str) -> None:
    """Learn an advertised transfer/channel address's node identity, so
    flow edges recorded by address resolve to node hexes."""
    if not addr or not node_hex:
        return
    with _peer_lock:
        if len(_peer_nodes) > 4096:
            _peer_nodes.clear()
        _peer_nodes[addr] = node_hex


def peer_node(addr: str) -> str:
    with _peer_lock:
        return _peer_nodes.get(addr, "")


# -- flow accounting --------------------------------------------------------

_flow_lock = threading.Lock()
# (src, dst, path) -> deque[(monotonic_ts, nbytes)] for the window gauges
_flow_windows: Dict[Tuple[str, str, str], deque] = {}


def _edge(src: str, dst: str, path: str) -> Tuple[str, str, str]:
    return ((src or "?")[:12], (dst or "?")[:12], path)


def record_flow(src: str, dst: str, path: str, nbytes: int,
                transfers: int = 0) -> None:
    """Account `nbytes` moved src->dst over `path`. Call at the same
    sites that count the authoritative byte totals (object_pull_bytes /
    channel_send_bytes) so the per-edge sums stay conservative."""
    if not enabled():
        return
    src, dst, path = _edge(src, dst, path)
    tags = {"src": src, "dst": dst, "path": path}
    if nbytes:
        _flow_bytes.inc(nbytes, tags=tags)
    if transfers:
        _flow_transfers.inc(transfers, tags=tags)
    if nbytes:
        with _flow_lock:
            _flow_windows.setdefault((src, dst, path), deque()).append(
                (time.monotonic(), nbytes))


def refresh_flow_gauges() -> None:
    """Prune per-edge windows and publish object_flow_window_bps. Called
    from the telemetry flush (workers) and the flows API/bench (head) —
    off the transfer hot path."""
    window = max(float(config.object_flow_window_s), 1e-3)
    now = time.monotonic()
    with _flow_lock:
        for (src, dst, path), dq in list(_flow_windows.items()):
            while dq and now - dq[0][0] > window:
                dq.popleft()
            if not dq:
                del _flow_windows[(src, dst, path)]
            _flow_window_bps.set(
                sum(n for _t, n in dq) / window,
                tags={"src": src, "dst": dst, "path": path})


# -- per-store snapshots (ships on heartbeat telemetry) ---------------------


def snapshot_store(store: Any, node_hex: str = "",
                   max_objects: Optional[int] = None) -> Dict[str, Any]:
    """Bounded wire snapshot of one store's ledger: largest records
    first, truncation made visible through total counts. Ages are
    computed locally (monotonic deltas) so cross-host clock skew never
    corrupts them."""
    if max_objects is None:
        max_objects = int(config.object_ledger_max_objects)
    node_hex = node_hex or local_node()
    try:
        records = store.ledger_records()
    except AttributeError:
        records = [{"object_id": oid.hex(), "size_bytes": size,
                    "age_s": 0.0, "idle_s": 0.0, "pin_count": 0,
                    "pin_reason": "", "creator_node": "", "creator_pid": 0,
                    "creator_task": ""}
                   for oid, size in store.list_objects()]
    kind = getattr(store, "kind", "memory")
    for r in records:
        r.setdefault("node_id", node_hex[:12])
        r.setdefault("store", kind)
    records.sort(key=lambda r: r.get("size_bytes", 0), reverse=True)
    total_bytes = sum(r.get("size_bytes", 0) for r in records)
    _store_live_gauge.set(total_bytes,
                          tags={"node": node_hex[:12], "store": kind})
    try:
        stats = dict(store.stats())
    except AttributeError:
        stats = {}
    return {
        "node_id": node_hex[:12],
        "store": kind,
        "total_objects": len(records),
        "total_bytes": total_bytes,
        "truncated": max(0, len(records) - max_objects),
        "records": records[:max_objects],
        "stats": stats,
    }


def local_snapshots(agents: Dict[Any, Any]) -> List[Dict[str, Any]]:
    """One bounded snapshot per non-remote agent store (worker runtimes
    have one agent; the head may host several virtual nodes)."""
    out = []
    for nid, agent in agents.items():
        if getattr(agent, "is_remote", False):
            continue
        store = getattr(agent, "store", None)
        if store is None:
            continue
        try:
            out.append(snapshot_store(store, nid.hex()))
        except Exception:  # noqa: BLE001 — telemetry never kills a beat
            logger.debug("ledger snapshot failed for %s", nid, exc_info=True)
    return out


# -- head-side federation ---------------------------------------------------


def _collect_rows(runtime) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Federated object rows + per-node store summaries: local agent
    stores snapshotted now, remote nodes from their latest telemetry
    ledger snapshots, each row joined with the head's refcount and the
    directory's location set."""
    from .ids import ObjectID

    snaps: List[Dict[str, Any]] = []
    with runtime._lock:
        agents = dict(runtime.agents)
    snaps.extend(local_snapshots(agents))
    try:
        telem = runtime.control_plane.telemetry_snapshots()
    except Exception:  # noqa: BLE001
        telem = {}
    for _node_hex, rec in sorted(telem.items()):
        snaps.extend(rec.get("objects") or [])

    rows: List[Dict[str, Any]] = []
    node_stats: Dict[str, Any] = {}
    for snap in snaps:
        key = f"{snap.get('node_id', '?')}/{snap.get('store', 'memory')}"
        node_stats[key] = {
            "objects": snap.get("total_objects", 0),
            "bytes": snap.get("total_bytes", 0),
            "truncated": snap.get("truncated", 0),
            **{k: v for k, v in (snap.get("stats") or {}).items()
               if k in ("num_spilled", "num_evictions", "capacity_bytes")},
        }
        rows.extend(dict(r) for r in snap.get("records", []))

    rc = getattr(runtime, "reference_counter", None)
    directory = getattr(runtime, "directory", None)
    loc_cache: Dict[str, List[str]] = {}
    for row in rows:
        oid_hex = row.get("object_id", "")
        try:
            oid = ObjectID.from_hex(oid_hex)
        except Exception:  # noqa: BLE001 — foreign id formats stay unjoined
            row.setdefault("refcount", 0)
            row.setdefault("locations", [])
            continue
        if rc is not None:
            row["refcount"] = rc.count(oid)
            row["escaped"] = rc.is_escaped(oid)
        if directory is not None:
            locs = loc_cache.get(oid_hex)
            if locs is None:
                locs = loc_cache[oid_hex] = [
                    n.hex()[:12] for n in directory.locations(oid)]
            row["locations"] = locs
    return rows, node_stats


def collect_objects(runtime, limit: int = 1000) -> Dict[str, Any]:
    """The federated /api/v0/objects body (also `ray-tpu memory`)."""
    rows, node_stats = _collect_rows(runtime)
    rows.sort(key=lambda r: r.get("size_bytes", 0), reverse=True)
    report = last_leak_report()
    return {
        "generated_at": time.time(),
        "total_objects": len(rows),
        "total_bytes": sum(r.get("size_bytes", 0) for r in rows),
        "objects": rows[:limit],
        "nodes": node_stats,
        "leaks": report.get("leaks", []),
        "leak_counts": report.get("counts", {}),
    }


_FLOW_FIELDS = {
    "object_flow_bytes": "bytes",
    "object_flow_transfers": "transfers",
    "object_flow_window_bps": "window_bps",
}


def collect_flows(runtime=None, control_plane=None) -> Dict[str, Any]:
    """The /api/v0/flows body: fold the local registry plus every node's
    federated metric snapshot into one per-edge matrix. Each edge is
    recorded by exactly one process (puller-side for pulls, sender-side
    for channels), so summing across sources never double-counts."""
    from .metrics import registry

    refresh_flow_gauges()
    cp = control_plane
    if cp is None and runtime is not None:
        cp = runtime.control_plane
    sources: List[Tuple[str, List[Dict[str, Any]]]] = [
        ("head", registry.snapshot())]
    if cp is not None:
        try:
            for node_hex, rec in sorted(cp.telemetry_snapshots().items()):
                sources.append((node_hex[:12], rec.get("metrics") or []))
        except Exception:  # noqa: BLE001
            pass
    edges: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
    for reporter, fams in sources:
        for fam in fams:
            field = _FLOW_FIELDS.get(fam.get("name", ""))
            if field is None:
                continue
            for _sname, tag_list, value in fam.get("samples", []):
                tags = dict(tag_list)
                key = (tags.get("src", "?"), tags.get("dst", "?"),
                       tags.get("path", "?"))
                edge = edges.get(key)
                if edge is None:
                    edge = edges[key] = {
                        "src": key[0], "dst": key[1], "path": key[2],
                        "bytes": 0.0, "transfers": 0.0, "window_bps": 0.0,
                        "reporters": []}
                edge[field] += float(value)
                if reporter not in edge["reporters"]:
                    edge["reporters"].append(reporter)
    rows = sorted(edges.values(), key=lambda e: e["bytes"], reverse=True)
    return {
        "generated_at": time.time(),
        "edges": rows,
        "total_bytes": sum(e["bytes"] for e in rows),
    }


# -- leak & staleness sweep (head-side) -------------------------------------

_sweep_lock = threading.Lock()
_sweep_last = 0.0
_last_leaks: Dict[str, Any] = {"generated_at": 0.0, "leaks": [], "counts": {}}


def last_leak_report() -> Dict[str, Any]:
    with _sweep_lock:
        return dict(_last_leaks)


def sweep(runtime, force: bool = False) -> Dict[str, Any]:
    """Flag held-but-unreachable objects, dead-node directory entries,
    and cold cache bytes; re-assert `object_leak` health alerts (injected
    alerts expire after ~3 periods unless re-asserted, so a sweep that
    stops seeing a leak lets its alert age out naturally)."""
    global _sweep_last
    now = time.monotonic()
    with _sweep_lock:
        if not force and now - _sweep_last < float(config.object_sweep_period_s):
            return dict(_last_leaks)
        _sweep_last = now
    if not enabled():
        return last_leak_report()
    age_thr = float(config.object_leak_age_s)
    leaks: List[Dict[str, Any]] = []
    try:
        rows, _stats = _collect_rows(runtime)
    except Exception:  # noqa: BLE001 — sweep never breaks the monitor loop
        logger.debug("leak sweep collect failed", exc_info=True)
        return last_leak_report()

    for row in rows:
        age = float(row.get("age_s", 0.0))
        idle = float(row.get("idle_s", 0.0))
        pinned = (row.get("pin_count", 0) or 0) > 0
        escaped = bool(row.get("escaped")) or row.get("pin_reason") == PIN_ESCAPED
        refs = int(row.get("refcount", 0) or 0)
        if (pinned or escaped) and refs == 0 and age > age_thr:
            leaks.append(_leak("pinned_no_refs", row,
                               f"pin_count={row.get('pin_count', 0)} "
                               f"reason={row.get('pin_reason', '') or 'pin'} "
                               f"refs=0 age={age:.0f}s"))
        elif (row.get("pin_reason") in (PIN_CACHE, PIN_INGEST)
                and age > age_thr and age - idle < 1.0):
            leaks.append(_leak("cold_cache", row,
                               f"cached {age:.0f}s ago, never re-hit"))

    # directory entries pointing at non-ALIVE nodes (the DEAD-mark ->
    # KV-purge window, or a purge that raced an add)
    directory = getattr(runtime, "directory", None)
    cp = getattr(runtime, "control_plane", None)
    if directory is not None and cp is not None:
        try:
            alive = {n.node_id.hex() for n in cp.alive_nodes()}
            for oid, node_ids in directory.items().items():
                for nid in node_ids:
                    if nid.hex() not in alive:
                        leaks.append({
                            "kind": "dead_node_location",
                            "object_id": oid.hex(),
                            "node_id": nid.hex()[:12],
                            "size_bytes": 0,
                            "age_s": 0.0,
                            "pin_reason": "",
                            "detail": f"directory lists {nid.hex()[:12]} "
                                      "but the node is not ALIVE",
                        })
        except Exception:  # noqa: BLE001
            logger.debug("dead-node directory scan failed", exc_info=True)

    counts: Dict[str, int] = {k: 0 for k in LEAK_KINDS}
    leaked_bytes: Dict[str, int] = {k: 0 for k in LEAK_KINDS}
    for l in leaks:
        counts[l["kind"]] = counts.get(l["kind"], 0) + 1
        leaked_bytes[l["kind"]] = (leaked_bytes.get(l["kind"], 0)
                                   + int(l.get("size_bytes", 0) or 0))
    for kind in counts:
        _leaks_gauge.set(counts[kind], tags={"kind": kind})
        _leaked_bytes_gauge.set(leaked_bytes[kind], tags={"kind": kind})

    _assert_alerts(leaks, counts, leaked_bytes)
    report = {"generated_at": time.time(), "leaks": leaks, "counts": counts,
              "leaked_bytes": leaked_bytes}
    with _sweep_lock:
        _last_leaks.clear()
        _last_leaks.update(report)
    return dict(report)


def _leak(kind: str, row: Dict[str, Any], detail: str) -> Dict[str, Any]:
    return {
        "kind": kind,
        "object_id": row.get("object_id", ""),
        "node_id": row.get("node_id", ""),
        "size_bytes": row.get("size_bytes", 0),
        "age_s": round(float(row.get("age_s", 0.0)), 1),
        "pin_reason": row.get("pin_reason", ""),
        "detail": detail,
    }


def _assert_alerts(leaks: List[Dict[str, Any]], counts: Dict[str, int],
                   leaked_bytes: Dict[str, int]) -> None:
    if not leaks:
        return
    try:
        from .health import get_health_plane

        plane = get_health_plane(create=False)
        if plane is None:
            return
        by_group: Dict[Tuple[str, str], int] = {}
        for l in leaks:
            key = (l["kind"], l.get("node_id", "") or "?")
            by_group[key] = by_group.get(key, 0) + 1
        for (kind, node), n in by_group.items():
            plane.inject(
                "object_leak", {"kind": kind, "node_id": node},
                value=float(n), severity="warning",
                expr=f"object ledger sweep: {n} {kind} object(s) on {node}")
    except Exception:  # noqa: BLE001 — alerting never breaks the sweep
        logger.debug("leak alert injection failed", exc_info=True)


# -- status()/health-payload sections ---------------------------------------


def objects_section(runtime) -> Dict[str, Any]:
    """Compact object-plane summary for ray_tpu.status() / the health
    payload: per-node live objects/bytes plus current leak counts."""
    if runtime is None or not enabled():
        return {}
    try:
        _rows, node_stats = _collect_rows(runtime)
        report = last_leak_report()
        return {
            "nodes": node_stats,
            "total_bytes": sum(s.get("bytes", 0) for s in node_stats.values()),
            "total_objects": sum(s.get("objects", 0)
                                 for s in node_stats.values()),
            "leak_counts": report.get("counts", {}),
        }
    except Exception:  # noqa: BLE001 — status must render regardless
        return {}


def channels_section(runtime) -> Dict[str, Dict[str, float]]:
    """Federated channel stats: the head's process-local totals plus each
    node's `channels` telemetry snapshot (satellite: channel_stats() was
    process-local only)."""
    out: Dict[str, Dict[str, float]] = {}
    try:
        from . import channels

        local = channels.channel_stats()
        if any(local.values()):
            out["head"] = local
        if runtime is not None:
            for node_hex, rec in sorted(
                    runtime.control_plane.telemetry_snapshots().items()):
                snap = rec.get("channels")
                if snap and any(snap.values()):
                    out[node_hex[:12]] = dict(snap)
    except Exception:  # noqa: BLE001
        pass
    return out
