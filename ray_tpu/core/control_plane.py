"""Cluster control plane (GCS equivalent).

Equivalent of the reference's Global Control Service (upstream ray
`src/ray/gcs/gcs_server/gcs_server.cc :: GcsServer` with its node / actor /
job / placement-group managers, `InternalKVInterface`, pubsub and health
checks): the single authority for cluster membership, the actor directory,
cluster-wide KV, and resource views. In-process for a single host; the same
object is served over gRPC-style RPC for multi-host (see
``ray_tpu.core.rpc``). State mutations publish to channels so node agents and
drivers react to membership/actor changes without polling.
"""

from __future__ import annotations

import enum
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from .ids import ActorID, JobID, NodeID, PlacementGroupID, SliceID
from .logging import get_logger
from .metrics import Counter, Gauge

logger = get_logger("control_plane")

_nodes_gauge = Gauge("ray_tpu_nodes", "Cluster nodes by state")
_actors_gauge = Gauge("ray_tpu_actors", "Actors by state")
_gossip_swept = Counter(
    "control_plane_gossip_swept_total",
    "Stale gossip KV entries removed by the TTL sweep")
_heartbeat_lag = Gauge(
    "control_plane_heartbeat_lag_seconds",
    "Worst heartbeat staleness across ALIVE nodes, sampled each health sweep")

# Gossip namespaces: per-node advertisements other nodes rank/dial by.
# Keys are `<prefix><node_hex>` (relay claims differ — see sweep). A node
# that dies WITHOUT mark_node_dead (SIGKILLed host, partitioned forever,
# crashed before deregistering) leaves these behind; at fleet scale the
# tombstones accumulate, so the TTL sweep reaps any entry whose owner is
# not ALIVE and whose last write is older than the TTL. The write-stamp
# grace matters: worker hosts advertise KV BEFORE register_node, so a
# fresh key with no ALIVE owner yet is a joiner, not a corpse.
GOSSIP_NODE_PREFIXES: Tuple[str, ...] = (
    "object_transfer/",       # transfer-plane address (object_transfer.KV_PREFIX)
    "object_transfer_load/",  # pull-load ranking gossip (LOAD_PREFIX)
    "object_transfer_host/",  # same-host shm tokens (HOST_PREFIX)
    "node_service/",          # dispatch address (cross_host.NODE_SERVICE_PREFIX)
    "channel_service/",       # DistChannel service (channels.KV_CHANNEL_PREFIX)
)
# value-suffix-owned namespaces: key does not embed the node, the value
# records "...|<node_hex>" (broadcast relay CAS claims)
GOSSIP_RELAY_PREFIX = "object_transfer_relay/"


def _is_gossip_key(key: str) -> bool:
    return key.startswith(GOSSIP_NODE_PREFIXES) or key.startswith(GOSSIP_RELAY_PREFIX)


class NodeState(enum.Enum):
    ALIVE = "ALIVE"
    DEAD = "DEAD"


class ActorState(enum.Enum):
    PENDING = "PENDING"
    STARTING = "STARTING"
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"


@dataclass
class NodeInfo:
    node_id: NodeID
    address: str
    resources_total: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    slice_id: Optional[SliceID] = None
    topology_coords: Optional[Tuple[int, ...]] = None  # host position in slice torus
    state: NodeState = NodeState.ALIVE
    last_heartbeat: float = field(default_factory=time.monotonic)
    # eventually-consistent load view, updated by the resource syncer
    resources_available: Dict[str, float] = field(default_factory=dict)

    def __post_init__(self):
        if not self.resources_available:
            self.resources_available = dict(self.resources_total)


@dataclass
class ActorInfo:
    actor_id: ActorID
    name: str
    class_name: str = ""
    state: ActorState = ActorState.PENDING
    node_id: Optional[NodeID] = None
    num_restarts: int = 0
    max_restarts: int = 0
    death_cause: str = ""


class Pubsub:
    """In-process pub/sub (reference: `src/ray/pubsub/ :: Publisher/Subscriber`)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._subs: Dict[str, List[Callable[[Any], None]]] = {}

    def subscribe(self, channel: str, callback: Callable[[Any], None]) -> Callable[[], None]:
        with self._lock:
            self._subs.setdefault(channel, []).append(callback)

        def unsubscribe() -> None:
            with self._lock:
                try:
                    self._subs.get(channel, []).remove(callback)
                except ValueError:
                    pass

        return unsubscribe

    def publish(self, channel: str, message: Any) -> None:
        with self._lock:
            callbacks = list(self._subs.get(channel, []))
        for cb in callbacks:
            try:
                cb(message)
            except Exception:  # subscriber errors must not poison the bus
                logger.exception("pubsub subscriber error on channel %s", channel)


class ControlPlane:
    """Single-authority cluster state. All methods are thread-safe."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.pubsub = Pubsub()
        self._nodes: Dict[NodeID, NodeInfo] = {}
        self._actors: Dict[ActorID, ActorInfo] = {}
        self._named_actors: Dict[str, ActorID] = {}
        self._jobs: Dict[JobID, Dict[str, Any]] = {}
        self._kv: Dict[str, bytes] = {}
        # last-write stamps for gossip-namespace keys only (sweep_gossip);
        # durable KV (function table, checkpoints, serve config) is never
        # stamped and never swept
        self._kv_stamp: Dict[str, float] = {}
        self._last_sweep = 0.0
        self._placement_groups: Dict[PlacementGroupID, Any] = {}
        # node_id hex -> latest telemetry report (metrics snapshot + role
        # + flush cursors) from that worker process; spans/timeline events
        # are ingested straight into the head's own buffers on arrival.
        self._telemetry: Dict[str, Dict[str, Any]] = {}
        # federated crash postmortems (bounded; see util/flight_recorder)
        self._postmortems: deque = deque(maxlen=50)
        self._dead = False

    # -- node table ---------------------------------------------------------
    def register_node(self, info: NodeInfo) -> None:
        with self._lock:
            # the info may have crossed a process boundary: its monotonic
            # heartbeat stamp is another clock's — restamp locally. A
            # rejoining host (falsely reaped, or head restarted) registers
            # with the SAME node id: revive it rather than zombie it.
            info.state = NodeState.ALIVE
            info.last_heartbeat = time.monotonic()
            prev = self._nodes.get(info.node_id)
            self._nodes[info.node_id] = info
        if prev is None:
            _nodes_gauge.add(1, {"state": "ALIVE"})
        elif prev.state is NodeState.DEAD:
            _nodes_gauge.add(-1, {"state": "DEAD"})
            _nodes_gauge.add(1, {"state": "ALIVE"})
        self.pubsub.publish("node", ("ALIVE", info))

    def mark_node_dead(self, node_id: NodeID, reason: str = "") -> None:
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None or info.state is NodeState.DEAD:
                return
            info.state = NodeState.DEAD
            # purge the node's advertised addresses and transfer-load
            # gossip: stale object_transfer_load/* keys would keep
            # pull_from_any's least-loaded ranking preferring a corpse
            # (prefix literals: object_transfer.KV_PREFIX/LOAD_PREFIX,
            # cross_host.NODE_SERVICE_PREFIX, channels.KV_CHANNEL_PREFIX —
            # spelled out here to avoid import cycles)
            hexid = node_id.hex()
            for prefix in GOSSIP_NODE_PREFIXES:
                self._kv.pop(prefix + hexid, None)
                self._kv_stamp.pop(prefix + hexid, None)
            # relay claims record "address|flow_label|node_hex"; a dead
            # relay must not stay in any broadcast tree — children time
            # out on its partial and fall back, but new pulls ranking by
            # claim slot would keep dialing the corpse
            for key in [k for k in self._kv
                        if k.startswith("object_transfer_relay/")]:
                val = self._kv.get(key)
                if isinstance(val, str) and val.rsplit("|", 1)[-1] == hexid:
                    self._kv.pop(key, None)
                    self._kv_stamp.pop(key, None)
            # and its last telemetry snapshot: a dead node's metrics and
            # digests must not haunt the merged dashboard/health view
            self._telemetry.pop(hexid, None)
        _nodes_gauge.add(-1, {"state": "ALIVE"})
        _nodes_gauge.add(1, {"state": "DEAD"})
        logger.warning("node %s marked DEAD: %s", node_id, reason)
        self.pubsub.publish("node", ("DEAD", info))

    def heartbeat(self, node_id: NodeID, resources_available: Optional[Dict[str, float]] = None) -> bool:
        """-> True if the node is ALIVE in the table. False tells the
        sender it has been reaped (or was never known): a worker whose
        partition outlived the health timeout must learn it is DEAD and
        shut down instead of zombie-heartbeating forever (reference: a
        raylet killed on GCS death declaration)."""
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None or info.state is not NodeState.ALIVE:
                return False
            info.last_heartbeat = time.monotonic()
            if resources_available is not None:
                info.resources_available = dict(resources_available)
            return True

    def heartbeat_bulk(
        self,
        beats: List[Tuple[Any, Optional[Dict[str, float]]]],
    ) -> Dict[str, bool]:
        """Pod-aggregator heartbeat: one RPC carries a whole pod's beats.
        ``beats`` is [(node_id, resources_available_or_None)]; the reply
        maps node hex -> alive verdict, same semantics as `heartbeat` per
        entry. Keeps head ingest O(pods), not O(nodes)."""
        out: Dict[str, bool] = {}
        for node_id, avail in beats:
            out[node_id.hex()] = self.heartbeat(node_id, avail)
        return out

    # -- federated telemetry ------------------------------------------------
    def report_telemetry(
        self,
        node_id_hex: str,
        role: str = "worker",
        metrics: Optional[List[Dict[str, Any]]] = None,
        spans: Optional[List[Dict[str, Any]]] = None,
        events: Optional[List[Dict[str, Any]]] = None,
        event_cursor: int = 0,
        digests: Optional[List[Dict[str, Any]]] = None,
        postmortems: Optional[List[Dict[str, Any]]] = None,
        objects: Optional[List[Dict[str, Any]]] = None,
        channels: Optional[Dict[str, float]] = None,
    ) -> bool:
        """Worker-process telemetry flush (piggybacked on the heartbeat
        loop, see cross_host.WorkerRuntime). Metrics and SLO digests
        replace the node's previous snapshot; spans merge into the head
        trace buffer (deduped by span_id, so transparent RPC retries are
        safe); timeline events append into the head ring under a
        per-node lane, guarded by `event_cursor` so a retried flush
        can't double-append; crash postmortem artifacts append to the
        head's bounded postmortem store (/api/v0/postmortems)."""
        from ..util import timeline, tracing

        with self._lock:
            prev = self._telemetry.get(node_id_hex) or {}
            seen_events = int(prev.get("event_cursor", 0))
            rec = {
                "role": role,
                "metrics": metrics if metrics is not None
                else prev.get("metrics", []),
                "digests": digests if digests is not None
                else prev.get("digests", []),
                "objects": objects if objects is not None
                else prev.get("objects", []),
                "channels": channels if channels is not None
                else prev.get("channels", {}),
                "event_cursor": max(seen_events, int(event_cursor)),
                "reported_at": time.time(),
            }
            self._telemetry[node_id_hex] = rec
            if postmortems:
                # dedup on (pid, written_at): a flush retried after a
                # requeue may carry artifacts the head already has
                seen = {(p.get("pid"), p.get("written_at"))
                        for p in self._postmortems}
                for p in postmortems:
                    if (p.get("pid"), p.get("written_at")) not in seen:
                        self._postmortems.append(
                            dict(p, node_id=node_id_hex[:12]))
        if spans:
            tracing.ingest(spans)
        if events and event_cursor > seen_events:
            timeline.ingest(events, lane=node_id_hex[:8])
        return True

    def telemetry_snapshots(self) -> Dict[str, Dict[str, Any]]:
        """node_id hex -> latest {role, metrics, digests, reported_at}
        (for the dashboard's merged /metrics and the health plane).
        Snapshots older than telemetry_stale_factor report periods are
        dropped — a node that stopped flushing (killed, partitioned)
        must not haunt the merged view with its last readings."""
        from .config import config

        try:
            horizon = time.time() - (
                float(config.telemetry_stale_factor)
                * float(config.telemetry_report_period_s))
        except Exception:
            horizon = 0.0
        with self._lock:
            stale = [k for k, v in self._telemetry.items()
                     if v.get("reported_at", 0.0) < horizon]
            for k in stale:
                del self._telemetry[k]
            return {k: dict(v) for k, v in self._telemetry.items()}

    def postmortems(self) -> List[Dict[str, Any]]:
        """Federated crash postmortems (newest last, bounded)."""
        with self._lock:
            return [dict(p) for p in self._postmortems]

    def alive_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self._nodes.values() if n.state is NodeState.ALIVE]

    def get_node(self, node_id: NodeID) -> Optional[NodeInfo]:
        with self._lock:
            return self._nodes.get(node_id)

    def all_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return list(self._nodes.values())

    # -- actor directory ----------------------------------------------------
    def register_actor(self, info: ActorInfo) -> None:
        with self._lock:
            self._actors[info.actor_id] = info
            if info.name:
                if info.name in self._named_actors:
                    raise ValueError(f"actor name already taken: {info.name}")
                self._named_actors[info.name] = info.actor_id
        self.pubsub.publish("actor", (info.state, info))

    def update_actor(self, actor_id: ActorID, state: ActorState, node_id: Optional[NodeID] = None,
                     death_cause: str = "") -> None:
        with self._lock:
            info = self._actors.get(actor_id)
            if info is None:
                return
            info.state = state
            if node_id is not None:
                info.node_id = node_id
            if death_cause:
                info.death_cause = death_cause
            if state is ActorState.RESTARTING:
                info.num_restarts += 1
            if state is ActorState.DEAD and info.name:
                self._named_actors.pop(info.name, None)
        self.pubsub.publish("actor", (state, info))

    def get_actor(self, actor_id: ActorID) -> Optional[ActorInfo]:
        with self._lock:
            return self._actors.get(actor_id)

    def get_named_actor(self, name: str) -> Optional[ActorInfo]:
        with self._lock:
            actor_id = self._named_actors.get(name)
            return self._actors.get(actor_id) if actor_id else None

    def list_actors(self) -> List[ActorInfo]:
        with self._lock:
            return list(self._actors.values())

    # -- job table ----------------------------------------------------------
    def register_job(self, job_id: JobID, metadata: Optional[Dict[str, Any]] = None) -> None:
        with self._lock:
            self._jobs[job_id] = {"state": "RUNNING", "start_time": time.time(),
                                  **(metadata or {})}

    def finish_job(self, job_id: JobID, state: str = "SUCCEEDED") -> None:
        with self._lock:
            if job_id in self._jobs:
                self._jobs[job_id]["state"] = state
                self._jobs[job_id]["end_time"] = time.time()

    def list_jobs(self) -> Dict[JobID, Dict[str, Any]]:
        with self._lock:
            return dict(self._jobs)

    # -- internal KV (function table, serve config, checkpoints metadata) ---
    def kv_put(self, key: str, value: bytes, overwrite: bool = True) -> bool:
        with self._lock:
            if not overwrite and key in self._kv:
                return False
            self._kv[key] = value
            if _is_gossip_key(key):
                self._kv_stamp[key] = time.monotonic()
            return True

    def kv_get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._kv.get(key)

    def kv_del(self, key: str) -> bool:
        with self._lock:
            self._kv_stamp.pop(key, None)
            return self._kv.pop(key, None) is not None

    def kv_keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return [k for k in self._kv if k.startswith(prefix)]

    def sweep_gossip(self, ttl_s: Optional[float] = None) -> int:
        """Reap gossip KV entries whose owner node is not ALIVE and whose
        last write is older than ``ttl_s`` (default
        config.control_plane_gossip_ttl_s). mark_node_dead already purges
        on clean deregistration; this catches nodes that died without it.
        Returns the number of keys removed."""
        if ttl_s is None:
            from .config import config

            ttl_s = float(config.control_plane_gossip_ttl_s)
        horizon = time.monotonic() - ttl_s
        swept = 0
        with self._lock:
            alive = {n.node_id.hex() for n in self._nodes.values()
                     if n.state is NodeState.ALIVE}
            doomed: List[str] = []
            for key in self._kv:
                if key.startswith(GOSSIP_NODE_PREFIXES):
                    owner = key.rsplit("/", 1)[-1]
                elif key.startswith(GOSSIP_RELAY_PREFIX):
                    val = self._kv.get(key)
                    owner = (val.rsplit("|", 1)[-1]
                             if isinstance(val, str) else "")
                else:
                    continue
                if owner in alive:
                    continue
                # stamp grace: keys written before the sweep machinery (or
                # restored from a snapshot) have no stamp — treat as old
                if self._kv_stamp.get(key, horizon - 1.0) <= horizon:
                    doomed.append(key)
            for key in doomed:
                self._kv.pop(key, None)
                self._kv_stamp.pop(key, None)
                swept += 1
        if swept:
            _gossip_swept.inc(swept)
            logger.info("gossip sweep reaped %d stale KV entries", swept)
        return swept

    # -- health checking ----------------------------------------------------
    def check_health(self, timeout_s: float) -> List[NodeID]:
        """Mark nodes dead whose heartbeat is older than timeout. Returns them."""
        now = time.monotonic()
        stale: List[NodeID] = []
        worst_lag = 0.0
        with self._lock:
            for node_id, info in self._nodes.items():
                if info.state is not NodeState.ALIVE:
                    continue
                lag = now - info.last_heartbeat
                worst_lag = max(worst_lag, lag)
                if lag > timeout_s:
                    stale.append(node_id)
        _heartbeat_lag.set(worst_lag)
        for node_id in stale:
            self.mark_node_dead(node_id, reason=f"no heartbeat for {timeout_s}s")
        return stale

    def snapshot(self) -> Dict[str, Any]:
        """State-API view of the whole cluster (reference: `ray list ...`)."""
        with self._lock:
            return {
                "nodes": [
                    {
                        "node_id": n.node_id.hex(),
                        "state": n.state.value,
                        "address": n.address,
                        "resources_total": dict(n.resources_total),
                        "resources_available": dict(n.resources_available),
                        "labels": dict(n.labels),
                    }
                    for n in self._nodes.values()
                ],
                "actors": [
                    {
                        "actor_id": a.actor_id.hex(),
                        "name": a.name,
                        "state": a.state.value,
                        "node_id": a.node_id.hex() if a.node_id else None,
                        "num_restarts": a.num_restarts,
                    }
                    for a in self._actors.values()
                ],
                "jobs": {j.hex(): dict(v) for j, v in self._jobs.items()},
            }
