"""Per-node object store: immutable create/seal/get semantics.

TPU-native equivalent of the reference's Plasma store + LocalObjectManager
(upstream ray `src/ray/object_manager/plasma/store.cc :: ObjectStore`,
`object_lifecycle_manager.cc`, spilling in `raylet/local_object_manager.cc`):
objects are sealed-once-then-immutable, pinned while referenced, LRU-evicted
to a disk spill directory under memory pressure, and restored on demand.

Two backends share one interface:
  * ``MemoryObjectStore`` — python-heap store used by in-process nodes (the
    common case for thread-pool workers; JAX arrays stay as device buffers
    and are NOT copied through the store — see ``ray_tpu.core.serialization``).
  * The C++ shared-memory store (``ray_tpu/core/_shm``) — mmap'd host shm for
    cross-process zero-copy, bound via ctypes (see shm_store.py).
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set

from .config import config
from .ids import ObjectID
from .logging import get_logger

logger = get_logger("object_store")


class ObjectStoreFullError(RuntimeError):
    pass


class SealedBytes:
    """A pickled payload sealed into the store. Every ``get`` deserializes a
    fresh object, so no consumer can alias the producer's live object or
    another consumer's copy — the serialization boundary the reference
    enforces by construction with worker processes + plasma. Large array
    buffers ride out-of-band (pickle protocol 5): the store keeps ONE
    immutable bytes copy and each ``get`` reconstructs arrays as zero-copy
    read-only views over it — plasma's shared-read semantics."""

    __slots__ = ("payload", "buffers")

    def __init__(self, payload: bytes, buffers=()):
        self.payload = payload
        self.buffers = tuple(buffers)

    @property
    def nbytes(self) -> int:
        return len(self.payload) + sum(len(b) for b in self.buffers)

    def load(self) -> Any:
        if self.buffers:
            return pickle.loads(self.payload, buffers=self.buffers)
        return pickle.loads(self.payload)

    def __reduce_ex__(self, protocol):
        # payload/buffers may be memoryviews after a zero-copy wire decode
        # (object_transfer._decode_blob); PickleBuffer keeps them picklable
        # either way — inline when no buffer_callback is active, out-of-band
        # (no copy) when the dumper collects buffers (protocol 5).
        if protocol >= 5:
            return (
                SealedBytes,
                (pickle.PickleBuffer(self.payload),
                 tuple(pickle.PickleBuffer(b) for b in self.buffers)),
            )
        return (SealedBytes, (bytes(self.payload),
                              tuple(bytes(b) for b in self.buffers)))


def _has_device_leaves(value: Any) -> bool:
    """True if the value's pytree contains jax.Arrays (checked lazily — if
    jax was never imported, there can be none)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        return any(isinstance(l, jax.Array) for l in jax.tree.leaves(value))
    except Exception:
        return True  # exotic tree: don't risk serializing


def seal_value(value: Any, name: str = "<put>") -> Any:
    """Wrap a value for aliasing-safe storage (see SealedBytes).

    Already-sealed payloads and immutable scalars pass through; jax.Array
    trees pass through (immutable, and pickling would drag device buffers
    through the host — plasma-style zero-copy sharing is exactly right for
    them); unpicklable values are stored live as a documented fallback."""
    if value is None or isinstance(
        value, (bool, int, float, str, bytes, SealedBytes)
    ):
        return value
    if _has_device_leaves(value):
        return value
    import cloudpickle

    buffers: list = []
    try:
        payload = cloudpickle.dumps(
            value, protocol=5, buffer_callback=buffers.append
        )
        return SealedBytes(payload, [bytes(b.raw()) for b in buffers])
    except Exception:
        logger.debug("value from %s not picklable; stored live", name)
        return value


class ObjectLostError(RuntimeError):
    def __init__(self, object_id: ObjectID, reason: str = "object lost"):
        super().__init__(f"{reason}: {object_id}")
        self.object_id = object_id


@dataclass
class _Entry:
    value: Any
    nbytes: int
    sealed: bool = True
    pin_count: int = 0
    spilling: bool = False  # disk write in flight (value still readable)
    spilled_path: Optional[str] = None
    created_at: float = field(default_factory=time.monotonic)
    # object-plane ledger metadata (core/object_ledger.py): who made this
    # object, why it is held, and when it was last read
    last_access: float = field(default_factory=time.monotonic)
    pin_reason: str = ""
    creator_node: str = ""
    creator_pid: int = 0
    creator_task: str = ""


class MemoryObjectStore:
    """Single-node store with pinning, LRU eviction and disk spill."""

    kind = "memory"

    def __init__(self, capacity_bytes: Optional[int] = None, spill_dir: Optional[str] = None):
        if capacity_bytes is None:
            capacity_bytes = config.object_store_memory_bytes or 2 * 1024**3
        self.capacity = capacity_bytes
        self.spill_dir = spill_dir or config.object_store_fallback_dir
        self._lock = threading.Condition()
        self._entries: "OrderedDict[ObjectID, _Entry]" = OrderedDict()
        self._used = 0
        self._evictions = 0
        # ledger identity: the node this store serves (NodeAgent sets it);
        # stamped as creator_node on entries sealed here
        self.ledger_node = ""
        self._waiters: Dict[ObjectID, List[Callable[[], None]]] = {}
        # fires (outside the lock) when an object leaves the store for good
        # — delete, not spill (spilled objects are still gettable). The node
        # agent hooks this to deregister the directory location, so a
        # pull-through replica's advertisement dies with the replica.
        self.on_evict: Optional[Callable[[ObjectID], None]] = None

    # -- size accounting ----------------------------------------------------
    @staticmethod
    def sizeof(value: Any) -> int:
        try:
            import numpy as np

            if isinstance(value, np.ndarray):
                return int(value.nbytes)
        except ImportError:
            pass
        nbytes = getattr(value, "nbytes", None)
        if isinstance(nbytes, int):
            return nbytes
        try:
            return len(pickle.dumps(value, protocol=5))
        except Exception:
            return 1024  # unpicklable (actor handles etc.) — nominal size

    def list_objects(self):
        """[(object_id, nbytes)] snapshot — the `ray memory` introspection."""
        with self._lock:
            return [(oid, e.nbytes) for oid, e in self._entries.items()]

    # -- primary API --------------------------------------------------------
    def put(self, object_id: ObjectID, value: Any, nbytes: Optional[int] = None) -> None:
        size = nbytes if nbytes is not None else self.sizeof(value)
        if size > self.capacity:
            raise ObjectStoreFullError(
                f"object of {size} bytes exceeds store capacity {self.capacity}"
            )
        while True:
            victim_id = None
            with self._lock:
                if object_id in self._entries:
                    return  # idempotent seal (retries)
                if self._used + size <= self.capacity:
                    self._entries[object_id] = _Entry(
                        value=value, nbytes=size,
                        creator_node=self.ledger_node, creator_pid=os.getpid())
                    self._used += size
                    callbacks = self._waiters.pop(object_id, [])
                    self._lock.notify_all()
                    break
                for oid, entry in self._entries.items():  # oldest first
                    if (entry.pin_count == 0 and not entry.spilling
                            and entry.spilled_path is None):
                        victim_id = oid
                        entry.spilling = True
                        victim_value = entry.value
                        break
                if victim_id is None:
                    raise ObjectStoreFullError(
                        f"store full ({self._used}B used, {size}B requested) and "
                        "all objects are pinned or spilling"
                    )
            # disk write happens OUTSIDE the lock: gets/puts proceed meanwhile
            path = self._write_spill_file(victim_id, victim_value)
            with self._lock:
                entry = self._entries.get(victim_id)
                if entry is not None and entry.spilling:
                    entry.spilling = False
                    entry.spilled_path = path
                    entry.value = None
                    self._used -= entry.nbytes
                    logger.debug("spilled %s (%d bytes) to %s", victim_id, entry.nbytes, path)
                else:  # deleted concurrently — discard the file
                    try:
                        os.remove(path)
                    except OSError:
                        pass
        for cb in callbacks:
            cb()

    def nbytes_of(self, object_id: ObjectID):
        """Size of a resident object, or None (backpressure accounting)."""
        with self._lock:
            entry = self._entries.get(object_id)
            return entry.nbytes if entry is not None else None

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._entries

    def get(self, object_id: ObjectID, timeout: Optional[float] = None) -> Any:
        value = self.get_raw(object_id, timeout)
        if isinstance(value, SealedBytes):
            return value.load()  # fresh object per consumer
        return value

    def get_raw(self, object_id: ObjectID, timeout: Optional[float] = None) -> Any:
        """get() without unwrapping SealedBytes — for store-to-store
        transfer, which must preserve the sealed form so the guarantee
        survives node hops."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while object_id not in self._entries:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(f"timed out waiting for {object_id}")
                self._lock.wait(timeout=remaining if remaining is None else min(remaining, 0.1))
            entry = self._entries[object_id]
            self._entries.move_to_end(object_id)  # LRU touch
            entry.last_access = time.monotonic()
            value = entry.value
            path = entry.spilled_path
        if value is None and path is not None:
            # restore from disk OUTSIDE the lock
            with open(path, "rb") as f:
                value = pickle.load(f)
        return value

    def on_available(self, object_id: ObjectID, callback: Callable[[], None]) -> None:
        """Invoke callback once the object is sealed (immediately if already)."""
        with self._lock:
            if object_id in self._entries:
                ready = True
            else:
                ready = False
                self._waiters.setdefault(object_id, []).append(callback)
        if ready:
            callback()

    def pin(self, object_id: ObjectID, reason: str = "") -> None:
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is not None:
                entry.pin_count += 1
                if reason:
                    entry.pin_reason = reason

    def annotate(self, object_id: ObjectID, pin_reason: Optional[str] = None,
                 creator_task: Optional[str] = None,
                 creator_node: Optional[str] = None) -> None:
        """Attach ledger metadata to a sealed entry. `serialized_escape`
        is sticky — once a ref escaped the process, a later cache/channel
        annotation must not hide why the object cannot be auto-freed."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                return
            if pin_reason is not None and entry.pin_reason != "serialized_escape":
                entry.pin_reason = pin_reason
            if creator_task is not None:
                entry.creator_task = creator_task
            if creator_node is not None:
                entry.creator_node = creator_node

    def unpin(self, object_id: ObjectID) -> None:
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is not None and entry.pin_count > 0:
                entry.pin_count -= 1

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            entry = self._entries.pop(object_id, None)
            path = None
            if entry is not None:
                # spilled entries already gave their bytes back at spill time
                if entry.spilled_path is None:
                    self._used -= entry.nbytes
                entry.spilling = False  # in-flight spill finalizer will no-op
                path = entry.spilled_path
                self._evictions += 1
        if path:
            try:
                os.remove(path)
            except OSError:
                pass
        on_evict = self.on_evict
        if entry is not None and on_evict is not None:
            try:
                on_evict(object_id)
            except Exception:  # noqa: BLE001 — eviction hooks never fail a delete
                logger.debug("on_evict hook failed for %s", object_id,
                             exc_info=True)

    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    def object_ids(self) -> Set[ObjectID]:
        with self._lock:
            return set(self._entries.keys())

    def stats(self) -> Dict[str, int]:
        with self._lock:
            spilled = sum(1 for e in self._entries.values() if e.spilled_path)
            return {
                "num_objects": len(self._entries),
                "used_bytes": self._used,
                "capacity_bytes": self.capacity,
                "num_spilled": spilled,
                "num_evictions": self._evictions,
            }

    def ledger_records(self) -> List[Dict[str, Any]]:
        """Wire-friendly ledger rows for every resident object (ages as
        local monotonic deltas — see object_ledger.snapshot_store)."""
        now = time.monotonic()
        with self._lock:
            return [{
                "object_id": oid.hex(),
                "size_bytes": e.nbytes,
                "age_s": round(now - e.created_at, 3),
                "idle_s": round(now - e.last_access, 3),
                "pin_count": e.pin_count,
                "pin_reason": e.pin_reason,
                "creator_node": e.creator_node[:12],
                "creator_pid": e.creator_pid,
                "creator_task": e.creator_task,
                "spilled": e.spilled_path is not None,
            } for oid, e in self._entries.items()]

    # -- eviction / spill ---------------------------------------------------
    def _write_spill_file(self, object_id: ObjectID, value: Any) -> str:
        os.makedirs(self.spill_dir, exist_ok=True)
        path = os.path.join(self.spill_dir, object_id.hex())
        with open(path, "wb") as f:
            pickle.dump(value, f, protocol=5)
        return path

    def notify_all(self) -> None:
        with self._lock:
            self._lock.notify_all()
