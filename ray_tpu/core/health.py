"""SLO health plane: alert rules, health scores, health-aware routing.

The active half of the observability plane (PR 6 built the transport):
a head-side `HealthPlane` periodically evaluates declarative alert rules
against three federated sources — latency digests (util/slo.py, shipped
with heartbeat telemetry), merged metric samples (head registry + per-
node snapshots), and control-plane heartbeat ages — and drives a
firing/resolved alert lifecycle that is published on pubsub channel
``"alerts"``, recorded into the timeline (ph="i", cat="alert"), exposed
at ``/api/v0/alerts`` + ``/api/v0/health``, and fed back into routing
(`ReplicaHealth`) and provisioning (`Autoscaler(health_plane=...)`).

Rule syntax
===========
A rule is one comparison with an optional sustain window::

    p95(serve_ttft_seconds{role=decode}) > 0.5 for 2
    serve_disagg_queue_depth{role=prefill} > 64 for 2
    delta(control_plane_reconnects_total) > 2
    node_heartbeat_age_seconds > 3 for 1

Grammar::

    expr   := source OP number ['for' N ['periods']]
    source := FN '(' name [tags] ')'  |  name [tags]
    tags   := '{' key=value (',' key=value)* '}'
    FN     := p50 | p90 | p95 | p99   -- digest quantile (util/slo.py)
            | value                   -- metric sample sum (the default)
            | delta                   -- increase since the previous
                                         evaluation pass ("rising")
    OP     := > | >= | < | <=

Tags FILTER the matched samples; ``Rule(group_by=("node_id",))`` expands
the rule into one independent alert per distinct value of those tags
(e.g. one heartbeat alert per node, one p95 alert per replica). A firing
group whose samples disappear (node purged on mark_node_dead, replica
gone) resolves with reason ``no_data``.

Sustain: the comparison must hold for `for N` CONSECUTIVE evaluation
passes (config health_eval_period_s apart) before the alert fires; one
clear pass resolves it. ``Rule(demand={"CPU": 1})`` additionally
advertises resources to the autoscaler while the alert is firing
(`pending_demand`).
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..util import slo
from .logging import get_logger
from .metrics import Gauge

logger = get_logger("health")

_m_alerts = Gauge("health_alerts_firing",
                  "Health-plane alerts currently firing, by severity.")
_m_quantile = Gauge(
    "slo_quantile_seconds",
    "Digest quantiles refreshed by the health plane, tagged "
    "{metric, q, role} (Grafana's window into util/slo.py sketches).")

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}

_RULE_RE = re.compile(
    r"^\s*(?:(?P<fn>p50|p90|p95|p99|value|delta)\s*\(\s*)?"
    r"(?P<name>[A-Za-z_][\w.]*)"
    r"(?:\{(?P<tags>[^}]*)\})?"
    r"(?(fn)\s*\))\s*"
    r"(?P<op>>=|<=|>|<)\s*"
    r"(?P<thr>-?\d+(?:\.\d+)?(?:e-?\d+)?)"
    r"(?:\s+for\s+(?P<n>\d+)(?:\s+periods?)?)?\s*$"
)


def parse_rule(expr: str) -> Dict[str, Any]:
    """Parse the rule grammar above into its components (see module
    docstring). Raises ValueError on a malformed expression."""
    m = _RULE_RE.match(expr)
    if m is None:
        raise ValueError(f"unparseable health rule: {expr!r}")
    tags: Dict[str, str] = {}
    if m.group("tags"):
        for part in m.group("tags").split(","):
            if not part.strip():
                continue
            k, _, v = part.partition("=")
            tags[k.strip()] = v.strip()
    return {
        "fn": m.group("fn") or "value",
        "name": m.group("name"),
        "tags": tags,
        "op": m.group("op"),
        "threshold": float(m.group("thr")),
        "for_periods": int(m.group("n") or 1),
    }


@dataclass
class Rule:
    """One declarative alert rule (grammar in the module docstring)."""

    name: str
    expr: str
    severity: str = "warning"
    group_by: Tuple[str, ...] = ()
    demand: Optional[Dict[str, float]] = None  # autoscaler input while firing
    _p: Dict[str, Any] = field(default_factory=dict, repr=False)

    def __post_init__(self):
        self._p = parse_rule(self.expr)
        self.group_by = tuple(self.group_by)


def default_rules() -> List[Rule]:
    """The stock rule set (ISSUE 7): armed from config at plane creation."""
    from .config import config

    rules = [
        Rule("queue_depth",
             f"serve_disagg_queue_depth > {int(config.get('health_queue_depth_max'))} for 2",
             group_by=("role",),
             # sustained backlog asks the autoscaler for another serving
             # node before the scheduler's pending queue ever backs up;
             # serve/fleet.py reads the same firing alert for replica
             # targets, so both actuation paths see one signal
             demand={"CPU": 1.0}),
        Rule("memory_pressure",
             f"host_memory_used_fraction > {float(config.get('health_memory_fraction_max'))} for 2",
             severity="critical", group_by=("node_id",)),
        Rule("heartbeat_gap",
             f"node_heartbeat_age_seconds > "
             f"{3.0 * float(config.get('health_check_period_ms')) / 1000.0}",
             severity="critical", group_by=("node_id",)),
        Rule("reconnect_spike",
             "delta(control_plane_reconnects_total) > 2", group_by=("role",)),
        Rule("data_stall_rising",
             "delta(data_stage_stall_seconds) > 1.0 for 2",
             # tenant-scoped: one tenant's input stall names that tenant
             # (stage + tenant labels on the firing alert) and advertises
             # CPU demand, so the ingest pool controller / autoscaler see
             # per-tenant pressure instead of a fleet-wide alarm
             group_by=("stage", "tenant"),
             demand={"CPU": 1.0}),
    ]
    stall_pct = float(config.get("rl_sync_stall_max_pct"))
    if stall_pct > 0:
        # the <5% sync-stall claim as an alert: rl/online.py publishes
        # the measured weight_sync share of each loop iteration
        rules.append(Rule(
            "rl_sync_stall",
            f"rl_sync_stall_fraction > {stall_pct / 100.0} for 2"))
    slo_ttft_ms = float(config.get("slo_ttft_ms"))
    if slo_ttft_ms > 0:
        rules.insert(0, Rule(
            "ttft_slo",
            f"p95(serve_ttft_seconds) > {slo_ttft_ms / 1000.0} for 2",
            severity="critical", group_by=("role",)))
        rules.insert(1, Rule(
            "replica_latency_slo",
            f"p95(serve_replica_latency_seconds) > {3 * slo_ttft_ms / 1000.0} for 2",
            group_by=("role", "replica")))
    return rules


def _match(sample_tags: Dict[str, str], want: Dict[str, str]) -> bool:
    return all(sample_tags.get(k) == v for k, v in want.items())


class HealthPlane:
    """Head-side rule engine (see module docstring for the data flow).

    Sources are injectable for tests: `metrics_fn` yields
    (name, tags_dict, value) samples, `digests_fn` yields digest
    snapshots in slo wire form. The defaults federate the local metrics
    registry + control-plane telemetry snapshots + heartbeat ages."""

    def __init__(self, rules: Optional[List[Rule]] = None,
                 control_plane: Any = None,
                 period_s: Optional[float] = None,
                 metrics_fn: Optional[Callable[[], List[Tuple]]] = None,
                 digests_fn: Optional[Callable[[], List[Dict]]] = None):
        from .config import config

        self.rules: List[Rule] = (list(rules) if rules is not None
                                  else default_rules())
        self._control_plane = control_plane
        self.period_s = (float(period_s) if period_s is not None
                         else float(config.get("health_eval_period_s")))
        self._metrics_fn = metrics_fn or self._federated_metrics
        self._digests_fn = digests_fn or self._federated_digests
        self._lock = threading.Lock()
        self._states: Dict[Tuple, Dict[str, Any]] = {}
        self._prev: Dict[Tuple, float] = {}       # for delta()
        self._active: Dict[Tuple, Dict[str, Any]] = {}
        self._history: deque = deque(maxlen=200)
        self._subs: List[Callable[[Dict[str, Any]], None]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._last_digests: Dict = {}

    # ---------------------------------------------------------- sources

    def _cp(self):
        if self._control_plane is not None:
            return self._control_plane
        try:
            from . import core_worker
            rt = core_worker._global_runtime
            return rt.control_plane if rt is not None else None
        except Exception:
            return None

    def _federated_metrics(self) -> List[Tuple[str, Dict[str, str], float]]:
        from .metrics import registry

        out: List[Tuple[str, Dict[str, str], float]] = []

        def flatten(snapshot, extra: Dict[str, str]):
            for fam in snapshot:
                for sname, tag_list, value in fam.get("samples", []):
                    tags = dict(tag_list)
                    tags.update(extra)
                    out.append((sname, tags, float(value)))

        flatten(registry.snapshot(), {})
        cp = self._cp()
        if cp is not None:
            now_mono = time.monotonic()
            try:
                snaps = cp.telemetry_snapshots()
            except Exception:
                snaps = {}
            for node_hex, rec in snaps.items():
                flatten(rec.get("metrics", []),
                        {"node_id": node_hex[:12],
                         "role": rec.get("role", "worker")})
            # heartbeat ages only for nodes that federate telemetry (i.e.
            # real worker runtimes): the head's own node row never
            # heartbeats itself and must not trip heartbeat_gap
            try:
                for n in cp.all_nodes():
                    nid = (n.node_id.hex() if hasattr(n.node_id, "hex")
                           else str(n.node_id))
                    if nid in snaps and getattr(n.state, "name", "") == "ALIVE":
                        out.append(("node_heartbeat_age_seconds",
                                    {"node_id": nid[:12]},
                                    max(0.0, now_mono - n.last_heartbeat)))
            except Exception:
                pass
        return out

    def _federated_digests(self) -> List[Dict[str, Any]]:
        snaps = list(slo.snapshot())
        cp = self._cp()
        if cp is not None:
            try:
                for rec in cp.telemetry_snapshots().values():
                    snaps.extend(rec.get("digests") or [])
            except Exception:
                pass
        return snaps

    # --------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="health-plane")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.evaluate()
            except Exception:
                logger.exception("health evaluation failed")

    # -------------------------------------------------------- evaluation

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One rule-evaluation pass. Returns the active alert list."""
        if now is None:
            now = time.time()
        samples = list(self._metrics_fn())
        merged = slo.merge_snapshots(self._digests_fn())
        with self._lock:
            self._last_digests = merged
            for rule in self.rules:
                self._eval_rule(rule, samples, merged, now)
            # inject()ed alerts live outside the rule engine: they expire
            # unless the injector keeps re-asserting them (the memory
            # monitor re-injects on every over-threshold sample)
            for skey, a in list(self._active.items()):
                if a.get("injected") and now - a["at"] > 3 * self.period_s:
                    self._resolve(skey, a.get("value"), now, reason="expired")
            self._set_gauges()
            return list(self._active.values())

    def _eval_rule(self, rule: Rule, samples, merged, now: float) -> None:
        p = rule._p
        groups: Dict[Tuple, float] = {}
        counts: Dict[Tuple, int] = {}
        if p["fn"] in ("p50", "p90", "p95", "p99"):
            q = int(p["fn"][1:]) / 100.0
            for (name, tag_t), m in merged.items():
                tags = dict(tag_t)
                if name != p["name"] or not _match(tags, p["tags"]):
                    continue
                gkey = tuple((k, tags.get(k, "")) for k in rule.group_by)
                # group quantiles merge bucket-wise, not by averaging
                acc = groups.get(gkey)
                if acc is None:
                    groups[gkey] = list(m["counts"])
                else:
                    for i, c in enumerate(m["counts"]):
                        acc[i] += c
            groups = {g: v for g, v in (
                (g, slo.quantile_from_counts(c, q)) for g, c in groups.items())
                if v is not None}
        else:
            for name, tags, value in samples:
                if name != p["name"] or not _match(tags, p["tags"]):
                    continue
                gkey = tuple((k, tags.get(k, "")) for k in rule.group_by)
                groups[gkey] = groups.get(gkey, 0.0) + value
                counts[gkey] = counts.get(gkey, 0) + 1
            if p["fn"] == "delta":
                deltas = {}
                for gkey, value in groups.items():
                    pkey = (rule.name, gkey)
                    prev = self._prev.get(pkey)
                    self._prev[pkey] = value
                    if prev is not None:
                        deltas[gkey] = value - prev
                groups = deltas

        cmp = _OPS[p["op"]]
        seen = set()
        for gkey, value in groups.items():
            seen.add(gkey)
            skey = (rule.name, gkey)
            st = self._states.setdefault(skey, {"consec": 0})
            if cmp(value, p["threshold"]):
                st["consec"] += 1
                if st["consec"] >= p["for_periods"] and skey not in self._active:
                    self._fire(rule, gkey, value, now)
                elif skey in self._active:
                    self._active[skey]["value"] = value
                    self._active[skey]["at"] = now
            else:
                st["consec"] = 0
                if skey in self._active:
                    self._resolve(skey, value, now, reason="cleared")
        # groups that vanished (node purged, replica gone) resolve firing
        # alerts instead of freezing them. Only groups THIS rule could
        # have created (label keys == group_by) are swept: an inject()ed
        # alert sharing the rule name carries foreign labels and must
        # outlive the pass.
        for skey in [k for k in list(self._active) if k[0] == rule.name
                     and k[1] not in seen
                     and tuple(kk for kk, _ in k[1]) == rule.group_by]:
            self._states.get(skey, {}).update(consec=0)
            self._resolve(skey, None, now, reason="no_data")

    # ------------------------------------------------------- transitions

    def _fire(self, rule: Rule, gkey: Tuple, value: float, now: float) -> None:
        alert = {
            "rule": rule.name,
            "expr": rule.expr,
            "state": "firing",
            "severity": rule.severity,
            "labels": dict(gkey),
            "value": value,
            "threshold": rule._p["threshold"],
            "since": now,
            "at": now,
            "demand": rule.demand,
        }
        self._active[(rule.name, gkey)] = alert
        self._announce(alert)

    def _resolve(self, skey: Tuple, value, now: float, reason: str) -> None:
        alert = self._active.pop(skey, None)
        if alert is None:
            return
        alert = dict(alert, state="resolved", value=value, at=now,
                     resolve_reason=reason)
        self._announce(alert)

    def inject(self, rule_name: str, labels: Optional[Dict[str, str]] = None,
               value: float = 0.0, severity: str = "critical",
               expr: str = "injected") -> Dict[str, Any]:
        """Force-fire an alert from outside the rule engine (e.g. the
        memory monitor raising memory_pressure just before it kills a
        worker — visible before the kill, not only after)."""
        gkey = tuple(sorted((labels or {}).items()))
        with self._lock:
            skey = (rule_name, gkey)
            if skey in self._active:
                self._active[skey].update(value=value, at=time.time())
                return self._active[skey]
            rule = Rule(rule_name, "value > 0", severity=severity)
            rule.expr = expr
            self._fire(rule, gkey, value, time.time())
            self._active[skey]["injected"] = True
            self._set_gauges()
            return self._active[skey]

    def _announce(self, alert: Dict[str, Any]) -> None:
        self._history.append(dict(alert))
        state, rule = alert["state"], alert["rule"]
        logger.log(30 if state == "firing" else 20,
                   "alert %s: %s %s value=%s labels=%s",
                   state, rule, alert["expr"], alert["value"],
                   alert["labels"])
        try:
            from ..util import timeline
            timeline.record(f"alert:{rule}", ph="i", cat="alert",
                            args={k: alert[k] for k in
                                  ("state", "severity", "labels", "value")})
        except Exception:
            pass
        cp = self._cp()
        if cp is not None:
            try:
                cp.pubsub.publish("alerts", dict(alert))
            except Exception:
                pass
        for fn in list(self._subs):
            try:
                fn(dict(alert))
            except Exception:
                pass

    def subscribe(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        """Local (in-process) alert subscription — routers use this to
        quarantine replicas named in firing alerts."""
        self._subs.append(fn)

    def _set_gauges(self) -> None:
        by_sev: Dict[str, int] = {}
        for a in self._active.values():
            by_sev[a["severity"]] = by_sev.get(a["severity"], 0) + 1
        for sev in ("warning", "critical"):
            _m_alerts.set(float(by_sev.get(sev, 0)), tags={"severity": sev})
        for (name, tag_t), m in self._last_digests.items():
            tags = dict(tag_t)
            if "replica" in tags:
                continue  # per-replica series would blow up the gauge set
            role = tags.get("role", "")
            for q in (0.5, 0.95):
                v = slo.quantile_from_counts(m["counts"], q)
                if v is not None:
                    _m_quantile.set(v, tags={"metric": name,
                                             "q": f"p{int(q * 100)}",
                                             "role": role})

    # ----------------------------------------------------------- queries

    def active(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(a) for a in self._active.values()]

    def history(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(a) for a in self._history]

    def pending_demand(self) -> List[Dict[str, float]]:
        """Resource bundles advertised to the autoscaler while demand-
        carrying rules fire (`Autoscaler(health_plane=...)`)."""
        with self._lock:
            return [dict(a["demand"]) for a in self._active.values()
                    if a.get("demand")]

    def scores(self) -> Dict[str, float]:
        """Coarse health scores in [0,1]: 1 = healthy. Nodes lose score
        with heartbeat age and firing alerts; replica/role series lose
        score when a matching alert fires."""
        out: Dict[str, float] = {}
        with self._lock:
            digests = dict(self._last_digests)
            active = [dict(a) for a in self._active.values()]
        for (name, tag_t) in digests:
            tags = dict(tag_t)
            rep = tags.get("replica")
            if rep:
                out.setdefault(f"replica:{rep}", 1.0)
        cp = self._cp()
        if cp is not None:
            try:
                for node_hex in cp.telemetry_snapshots():
                    out.setdefault(f"node:{node_hex[:12]}", 1.0)
            except Exception:
                pass
        for a in active:
            labels = a.get("labels", {})
            penalty = 0.0 if a["severity"] == "critical" else 0.5
            for key in (f"replica:{labels.get('replica')}",
                        f"node:{labels.get('node_id')}"):
                if key in out:
                    out[key] = min(out[key], penalty)
        return out

    def payload(self) -> Dict[str, Any]:
        """The /api/v0/health body (also what ray_tpu.status() renders)."""
        with self._lock:
            digests = {}
            for (name, tag_t), m in self._last_digests.items():
                label = name + "".join(
                    f",{k}={v}" for k, v in tag_t)
                digests[label] = {
                    "p50": slo.quantile_from_counts(m["counts"], 0.5),
                    "p95": slo.quantile_from_counts(m["counts"], 0.95),
                    "count": m["count"],
                    "max": m["max"],
                }
        nodes = []
        cp = self._cp()
        if cp is not None:
            try:
                now_mono = time.monotonic()
                snaps = cp.telemetry_snapshots()
                for n in cp.all_nodes():
                    nid = n.node_id.hex() if hasattr(n.node_id, "hex") else str(n.node_id)
                    nodes.append({
                        "node_id": nid[:12],
                        "state": getattr(n.state, "name", str(n.state)),
                        "heartbeat_age_s": round(now_mono - n.last_heartbeat, 3),
                        "role": (snaps.get(nid) or {}).get("role", ""),
                    })
            except Exception:
                pass
        utilization, goodput = self._profiling_sections(cp)
        objects: Dict[str, Any] = {}
        channels: Dict[str, Any] = {}
        try:
            from . import core_worker, object_ledger

            rt = getattr(core_worker, "_global_runtime", None)
            objects = object_ledger.objects_section(rt)
            channels = object_ledger.channels_section(rt)
        except Exception:  # noqa: BLE001 — payload must render regardless
            pass
        return {
            "generated_at": time.time(),
            "nodes": nodes,
            "alerts": self.active(),
            "digests": digests,
            "scores": self.scores(),
            "utilization": utilization,
            "goodput": goodput,
            "objects": objects,
            "channels": channels,
        }

    _UTIL_GAUGES = {"host_cpu_used_fraction": "cpu_fraction",
                    "process_rss_bytes": "rss_bytes",
                    "host_memory_used_fraction": "memory_fraction"}

    def _profiling_sections(self, cp) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Per-node CPU/RSS/memory gauges + the goodput ledger, both from
        the same federated family snapshots the rule engine reads
        (util/profiler sets the gauges; telemetry flushes federate them)."""
        utilization: Dict[str, Dict[str, float]] = {}
        goodput: Dict[str, Any] = {}
        try:
            from ..util import profiler
            from .metrics import registry

            try:
                # worker runtimes refresh on telemetry flushes; the head
                # has no flush loop, so its own row refreshes here
                profiler.update_resource_gauges()
            except Exception:
                pass
            sources: List[Tuple[str, List]] = [("head", registry.snapshot())]
            if cp is not None:
                try:
                    for node_hex, rec in cp.telemetry_snapshots().items():
                        sources.append((node_hex[:12],
                                        rec.get("metrics") or []))
                except Exception:
                    pass
            for key, fams in sources:
                row: Dict[str, float] = {}
                for fam in fams:
                    out_key = self._UTIL_GAUGES.get(fam.get("name", ""))
                    if not out_key:
                        continue
                    vals = [float(v) for _s, _t, v in fam.get("samples", [])]
                    if vals:
                        # fractions are host-wide (any sample is the
                        # host's value); byte gauges sum across processes
                        row[out_key] = (max(vals) if "fraction" in out_key
                                        else sum(vals))
                if row:
                    utilization[key] = row
            goodput = profiler.ledger_from_samples(
                [f for _k, fams in sources for f in fams])
        except Exception:  # noqa: BLE001 — payload must render regardless
            pass
        return utilization, goodput


# -- client-side routing health --------------------------------------------

class ReplicaHealth:
    """Per-replica health scorer for routers (Pow2Router, the disagg
    coordinator): tracks observed latency/outcomes per replica key,
    down-weights degraded replicas, and quarantines broken ones BEFORE
    the control plane's heartbeat timeout marks the node DEAD.

    Lifecycle: errors collapse the score multiplicatively (one transport
    crash quarantines outright); after `quarantine_s` the replica gets
    ONE probe request — success restores it, failure re-quarantines with
    doubled backoff. `eligible()` fails open when every replica is
    quarantined (degraded service beats no service)."""

    def __init__(self, quarantine_s: Optional[float] = None,
                 now_fn: Callable[[], float] = time.monotonic):
        if quarantine_s is None:
            try:
                from .config import config
                quarantine_s = float(config.get("health_quarantine_s"))
            except Exception:
                quarantine_s = 5.0
        self.quarantine_s = quarantine_s
        self._now = now_fn
        self._lock = threading.Lock()
        self._s: Dict[Any, Dict[str, Any]] = {}

    def _st(self, key) -> Dict[str, Any]:
        st = self._s.get(key)
        if st is None:
            st = self._s[key] = {"score": 1.0, "quar_until": 0.0,
                                 "backoff": self.quarantine_s,
                                 "probing": False, "errors": 0, "ok": 0,
                                 "reason": ""}
        return st

    def observe(self, key, latency_s: Optional[float] = None,
                ok: bool = True, role: str = "") -> None:
        if not ok:
            return self.record_error(key)
        with self._lock:
            st = self._st(key)
            st["ok"] += 1
            st["score"] = min(1.0, st["score"] * 0.7 + 0.3)
            if st["probing"] or st["quar_until"]:
                st["probing"] = False
                st["quar_until"] = 0.0
                st["backoff"] = self.quarantine_s
                st["reason"] = ""
        if latency_s is not None:
            tags = {"replica": str(key)}
            if role:
                tags["role"] = role
            slo.observe("serve_replica_latency_seconds", latency_s, tags=tags)

    def record_error(self, key, reason: str = "error") -> None:
        with self._lock:
            st = self._st(key)
            st["errors"] += 1
            st["score"] *= 0.25
            if st["probing"]:
                st["backoff"] = min(60.0, st["backoff"] * 2)
                st["probing"] = False
            if st["score"] < 0.3:
                st["quar_until"] = self._now() + st["backoff"]
                st["reason"] = reason

    def quarantine(self, key, reason: str = "external",
                   duration: Optional[float] = None) -> None:
        """Direct quarantine (alert subscriptions, heartbeat signals)."""
        with self._lock:
            st = self._st(key)
            st["score"] = 0.0
            st["quar_until"] = self._now() + (duration if duration is not None
                                              else st["backoff"])
            st["reason"] = reason

    def score(self, key) -> float:
        with self._lock:
            st = self._s.get(key)
            if st is None:
                return 1.0
            if st["quar_until"] and self._now() < st["quar_until"]:
                return 0.0
            return st["score"]

    def quarantined(self, key) -> bool:
        with self._lock:
            st = self._s.get(key)
            return bool(st and st["quar_until"]
                        and self._now() < st["quar_until"])

    def eligible(self, keys: List[Any]) -> List[Any]:
        """Routing candidates: quarantined replicas are excluded until
        their probe window opens (then exactly one probe passes). Fails
        open to the full list when nothing is eligible."""
        now = self._now()
        out = []
        with self._lock:
            for k in keys:
                st = self._s.get(k)
                if st is None or not st["quar_until"]:
                    out.append(k)
                    continue
                if now >= st["quar_until"] and not st["probing"]:
                    st["probing"] = True
                    st["quar_until"] = now + st["backoff"]  # next window
                    out.append(k)
        return out if out else list(keys)

    def penalty(self, key) -> int:
        """Load-units penalty for pow2 comparisons: a degraded replica
        competes as if it already had a queue."""
        s = self.score(key)
        return 0 if s >= 0.99 else int((1.0 - s) * 8)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {str(k): {"score": st["score"],
                             "quarantined": bool(
                                 st["quar_until"]
                                 and self._now() < st["quar_until"]),
                             "errors": st["errors"], "ok": st["ok"],
                             "reason": st["reason"]}
                    for k, st in self._s.items()}


# -- module singleton -------------------------------------------------------

_plane: Optional[HealthPlane] = None
_plane_lock = threading.Lock()


def get_health_plane(create: bool = True) -> Optional[HealthPlane]:
    """The process-wide plane (head-side). Created lazily by the
    dashboard, cross-host enablement, or status(); started on creation."""
    global _plane
    if _plane is None and create:
        with _plane_lock:
            if _plane is None:
                _plane = HealthPlane()
                _plane.start()
                try:
                    # loop closure (profiling plane): sustained stall /
                    # heartbeat-gap alerts auto-capture a stack dump into
                    # the flight recorder + postmortem stream
                    from ..util import profiler
                    profiler.install_auto_dump(_plane)
                except Exception:  # noqa: BLE001 — optional plane
                    pass
    return _plane


def shutdown_health_plane() -> None:
    global _plane
    with _plane_lock:
        p, _plane = _plane, None
    if p is not None:
        p.stop()
