"""Log aggregation: tail per-process session logs to the driver + pubsub.

Reference: `python/ray/_private/log_monitor.py` — a per-node process tails
every worker's log files and publishes lines to the driver, prefixed
`(pid=…, ip=…)`. Same shape here: one LogMonitor thread per session tails
`<session>/logs/*` (runtime components and pool workers), emits each line
to a sink (driver stderr by default) with a `(file pid=…)` prefix, and
optionally publishes to the control plane's "logs" pubsub channel so a
remote CLI (`ray-tpu logs --follow --address …`) can stream them over RPC.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .logging import get_logger, log_dir

logger = get_logger("log_monitor")

LOG_CHANNEL = "logs"

# files the monitor tails; everything a session writes lands in one of these
_SUFFIXES = (".log", ".out", ".err")


def _pid_of(filename: str) -> Optional[str]:
    # convention: <component>-<pid>.log / worker-<pid>.out
    stem = filename.rsplit(".", 1)[0]
    tail = stem.rsplit("-", 1)[-1]
    return tail if tail.isdigit() else None


class LogMonitor:
    """Tails the session log dir; fans lines out to sinks.

    Each record is a dict {"file", "pid", "line"}; the default sink prints
    `(file pid=…) line` to stderr, matching the reference's driver echo."""

    def __init__(
        self,
        directory: Optional[str] = None,
        sink: Optional[Callable[[Dict[str, str]], None]] = None,
        pubsub=None,
        poll_interval: float = 0.25,
        from_start: bool = False,
    ):
        self.directory = directory or log_dir()
        self.sink = sink if sink is not None else self._default_sink
        self.pubsub = pubsub
        self.poll_interval = poll_interval
        self.from_start = from_start
        self._offsets: Dict[str, int] = {}
        self._partial: Dict[str, bytes] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _default_sink(record: Dict[str, str]) -> None:
        import sys

        pid = f" pid={record['pid']}" if record.get("pid") else ""
        print(f"({record['file']}{pid}) {record['line']}",
              file=sys.stderr, flush=True)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "LogMonitor":
        if self._thread is not None:
            return self
        if not self.from_start:
            # start tailing at current EOF: a monitor attached mid-session
            # reports new lines, not history (reference behavior)
            for name, path in self._files():
                try:
                    self._offsets[name] = os.path.getsize(path)
                except OSError:
                    pass
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="log-monitor"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- tailing -------------------------------------------------------------

    def _files(self) -> List[Tuple[str, str]]:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        return [
            (n, os.path.join(self.directory, n))
            for n in sorted(names)
            if n.endswith(_SUFFIXES)
        ]

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.poll_interval)

    def poll_once(self) -> int:
        """One scan pass; returns the number of lines emitted."""
        emitted = 0
        for name, path in self._files():
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            offset = self._offsets.get(name, 0)
            if size < offset:  # rotated/truncated: restart
                offset = 0
                self._partial.pop(name, None)
            if size == offset:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    data = f.read(size - offset)
            except OSError:
                continue
            self._offsets[name] = size
            data = self._partial.pop(name, b"") + data
            lines = data.split(b"\n")
            if lines and lines[-1]:  # trailing partial line: hold it back
                self._partial[name] = lines[-1]
            for raw in lines[:-1]:
                line = raw.decode("utf-8", errors="replace").rstrip("\r")
                if not line:
                    continue
                record = {"file": name, "pid": _pid_of(name) or "", "line": line}
                try:
                    self.sink(record)
                except Exception:  # noqa: BLE001 — a bad sink must not stop tailing
                    logger.warning("log sink raised", exc_info=True)
                if self.pubsub is not None:
                    try:
                        self.pubsub.publish(LOG_CHANNEL, record)
                    except Exception:  # noqa: BLE001
                        pass
                emitted += 1
        return emitted


def list_log_files(directory: Optional[str] = None) -> List[Dict[str, object]]:
    """Session log inventory for `ray-tpu logs` (name, bytes, mtime)."""
    directory = directory or _latest_log_dir()
    out: List[Dict[str, object]] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for n in names:
        p = os.path.join(directory, n)
        try:
            st = os.stat(p)
        except OSError:
            continue
        out.append({"file": n, "bytes": st.st_size,
                    "mtime": time.strftime("%H:%M:%S", time.localtime(st.st_mtime))})
    return out


def tail_log_file(name: str, n: int = 100,
                  directory: Optional[str] = None) -> List[str]:
    directory = directory or _latest_log_dir()
    path = os.path.join(directory, name)
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(max(0, size - (n + 1) * 512))
        lines = f.read().decode("utf-8", errors="replace").splitlines()
    return lines[-n:]


def _latest_log_dir() -> str:
    base = os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")
    return os.path.join(base, "session_latest", "logs")
