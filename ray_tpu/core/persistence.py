"""Control-plane snapshot/restore: crash-survivable cluster state.

Reference analogue: GCS persistence via Redis
(`src/ray/gcs/store_client/redis_store_client.cc` +
`gcs_table_storage.cc`) — the reference journals every table mutation to an
external store so a restarted GCS rebuilds its tables. TPU-native design
choice: a single-host runtime has no external store to lean on, so the
control plane snapshots its tables to a local file on an interval
(atomic tmp+rename), and ``ray_tpu.init(resume_from=...)`` rebuilds from
the latest snapshot.

What restores, and why:
- **KV**: fully restored — it is the cluster's durable metadata plane
  (checkpoint paths, serve configs, function table).
- **Jobs**: table restored; jobs that were RUNNING are marked FAILED with
  a runtime-death cause (their processes are gone).
- **Named actors**: re-created from their pickled creation specs
  (class, args, options). Named = reachable by ``get_actor``, the proxy
  for the reference's detached actors; anonymous actors' handles died
  with the driver, so re-creating them would leak unreachable actors.
  Placement-group scheduling strategies are stripped on restore (PGs are
  ephemeral to their creating driver, as upstream non-detached PGs are).
- **Nodes / placement groups / object directory**: snapshotted for
  forensics (`snapshot["nodes"]`, ...), not restored — nodes are
  process-local constructs that re-register on init, PGs die with their
  driver, and objects live in process memory (lineage reconstruction is
  the recovery path for those, not persistence).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

import cloudpickle

from .config import config
from .logging import get_logger
from .metrics import Counter, Gauge

logger = get_logger("persistence")

SNAPSHOT_VERSION = 1

# A silently-failing snapshot loop is a durability hole that only shows up
# when the head dies: make it alertable instead of a log line.
_snapshot_age = Gauge(
    "control_plane_snapshot_age_seconds",
    "Seconds since the last successful control-plane snapshot write",
)
_snapshot_failures = Counter(
    "control_plane_snapshot_failures_total",
    "Control-plane snapshot write attempts that raised",
)


def take_snapshot(runtime) -> Dict[str, Any]:
    """Capture the control plane's tables. Each table read is atomic;
    cross-table consistency is best-effort (matching the reference's
    per-table Redis writes, which are not transactional across tables)."""
    cp = runtime.control_plane
    named = {}
    with runtime._lock:
        specs = dict(runtime._actor_specs)
    for name, actor_id in list(cp._named_actors.items()):
        info = cp.get_actor(actor_id)
        spec = specs.get(actor_id)
        if info is None or spec is None:
            continue
        try:
            payload = cloudpickle.dumps(
                (spec.func, spec.args, spec.kwargs, spec.options)
            )
        except Exception:
            logger.debug("actor %r not snapshottable (unpicklable spec)", name)
            continue
        named[name] = {
            "payload": payload,
            "class_name": info.class_name,
            "max_restarts": info.max_restarts,
        }
    return {
        "version": SNAPSHOT_VERSION,
        "time": time.time(),
        "kv": dict(cp._kv),
        "jobs": {jid.hex(): dict(meta) for jid, meta in cp.list_jobs().items()},
        "named_actors": named,
        "nodes": [
            {
                "node_id": n.node_id.hex(),
                "resources": dict(n.resources_total),
                "state": n.state.value,
                "labels": dict(n.labels),
            }
            for n in cp.all_nodes()
        ],
        "placement_groups": [
            {"id": pid.hex(), "repr": repr(pg)}
            for pid, pg in list(cp._placement_groups.items())
        ],
        "objects": [oid.hex() for oid in list(runtime.directory._locations)],
    }


def write_snapshot(runtime, path: str) -> None:
    """Atomic snapshot write: tmp + rename, so a crash mid-write leaves the
    previous snapshot intact."""
    snap = take_snapshot(runtime)
    tmp = f"{path}.tmp.{os.getpid()}"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "wb") as f:
        f.write(cloudpickle.dumps(snap))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_snapshot(path: str) -> Dict[str, Any]:
    with open(path, "rb") as f:
        snap = cloudpickle.loads(f.read())
    if snap.get("version") != SNAPSHOT_VERSION:
        raise ValueError(
            f"snapshot version {snap.get('version')} != {SNAPSHOT_VERSION}"
        )
    return snap


def restore_into(runtime, snap: Dict[str, Any]) -> Dict[str, int]:
    """Rebuild restorable state into a fresh runtime (see module docstring
    for the restore policy). Returns counts per restored table."""
    from .ids import JobID

    cp = runtime.control_plane
    for key, value in snap.get("kv", {}).items():
        cp.kv_put(key, value, overwrite=False)
    n_jobs = 0
    for jid_hex, meta in snap.get("jobs", {}).items():
        meta = dict(meta)
        if meta.get("state") == "RUNNING":
            meta["state"] = "FAILED"
            meta["death_cause"] = "runtime died (restored from snapshot)"
        try:
            cp._jobs[JobID(bytes.fromhex(jid_hex))] = meta
            n_jobs += 1
        except Exception:
            logger.debug("job %s not restorable", jid_hex)
    n_actors = 0
    for name, entry in snap.get("named_actors", {}).items():
        try:
            cls, args, kwargs, options = cloudpickle.loads(entry["payload"])
            from .task_spec import (
                PlacementGroupSchedulingStrategy,
                SchedulingStrategy,
            )

            if isinstance(
                getattr(options, "scheduling_strategy", None),
                PlacementGroupSchedulingStrategy,
            ):
                # PGs are ephemeral to their creating driver (upstream
                # non-detached semantics): strip only the PG constraint —
                # Spread/NodeAffinity strategies restore as-is
                import dataclasses as _dc

                options = _dc.replace(
                    options, scheduling_strategy=SchedulingStrategy()
                )
            runtime.create_actor(cls, args, kwargs, options)
            n_actors += 1
        except Exception:
            logger.warning("named actor %r failed to restore", name, exc_info=True)
    counts = {
        "kv": len(snap.get("kv", {})),
        "jobs": n_jobs,
        "named_actors": n_actors,
    }
    logger.info(
        "restored control plane from snapshot (t=%s): %s",
        time.strftime("%H:%M:%S", time.localtime(snap.get("time", 0))),
        counts,
    )
    return counts


class SnapshotWriter:
    """Background snapshotter: writes every interval and once at stop()."""

    def __init__(self, runtime, path: str, interval_s: Optional[float] = None):
        self._rt = runtime
        self._path = path
        self._interval = (
            interval_s
            if interval_s is not None
            else config.control_plane_snapshot_interval_s
        )
        self._stop = threading.Event()
        self._write_lock = threading.Lock()
        self._last_ok = time.monotonic()  # age counts from writer birth
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="cp-snapshot"
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self._write()

    def _write(self) -> None:
        with self._write_lock:  # interval vs final write share a tmp path
            try:
                write_snapshot(self._rt, self._path)
                self._last_ok = time.monotonic()
                _snapshot_age.set(0.0)
            except Exception:
                logger.warning("control-plane snapshot failed", exc_info=True)
                _snapshot_failures.inc()
                _snapshot_age.set(time.monotonic() - self._last_ok)

    def stop(self, final_write: bool = True) -> None:
        """Stop the interval loop (joining any in-flight write) and take one
        last snapshot so shutdown state is durable."""
        self._stop.set()
        self._thread.join(timeout=10.0)
        if final_write:
            self._write()
