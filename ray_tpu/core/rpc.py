"""Control-plane RPC: the single-authority tables served over TCP.

Reference analogue: `src/ray/rpc/gcs_server/` (GcsRpcServer) and
`gcs_client/` — every daemon talks to the GCS over gRPC. Here the same
shape: `serve_control_plane` exposes a ControlPlane's public methods on a
socket, `RemoteControlPlane` is a drop-in client with the same duck-typed
surface, so a Runtime on another host (or another OS process on the same
host) can share one authority. Pubsub crosses the wire as pushed EVENT
frames feeding the client's local Pubsub — subscribers are oblivious.

Fault tolerance (reference: GCS-FT — Redis-backed tables plus client-side
accessor resubscribe): the client survives head death. Connection loss
triggers bounded exponential-backoff reconnect (config
`control_plane_reconnect_max_s`); every call runs under a deadline
(`control_plane_call_deadline_s`); idempotent methods retry transparently
across reconnects, non-idempotent ones surface the retryable
`ControlPlaneUnavailable`. On reconnect every channel in `_subscribed`
re-registers server-side, so pubsub survives a head restart invisibly.
Request/reply state is PER CONNECTION (`_Conn`): a straggler response from
connection N can never satisfy a request issued on connection N+1, even
though request ids restart at 1 on each connection.

Threading model: one handler thread per connection (control-plane call
rates are low; no need for an event loop), one push thread per subscribed
client. The client proxy serializes request/response pairs over one
socket with a lock; pushed events are queued by the per-connection reader
thread and delivered to the local Pubsub from a dedicated dispatcher
thread — subscriber callbacks may therefore issue RPCs on this same
client (a callback running ON the reader would deadlock: the reply it
waits for can only be decoded by the reader it is blocking). A
short-lived reconnect thread re-dials after a loss and exits once a
connection is installed.

Registry invariant (machine-enforced by `ray_tpu.tools.raylint` rule R3):
`_IDEMPOTENT_METHODS` must be a subset of `_ALLOWED_METHODS` — a
transparently retried method that isn't served would loop into
'method not served' rejections. New control-plane methods must be added
to `_ALLOWED_METHODS` and, deliberately, to `_IDEMPOTENT_METHODS` only
when a blind resend after an ambiguous connection loss is safe.
"""

from __future__ import annotations

import queue
import random
import socket
import socketserver
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Set

from .config import config
from .logging import get_logger
from .metrics import Counter
from .wire import MSG_EVENT, MSG_REQUEST, MSG_RESPONSE, WireError, recv_msg, send_msg

logger = get_logger("rpc")

_reconnects_total = Counter(
    "control_plane_reconnects_total",
    "Control-plane client connections re-established after a loss, by role",
)
_redials_throttled = Counter(
    "control_plane_redials_throttled_total",
    "Reconnect dial attempts delayed by the process-wide dial-rate cap",
)

# the served surface (N1's public API): anything else is rejected
_ALLOWED_METHODS: Set[str] = {
    "register_node", "mark_node_dead", "heartbeat", "heartbeat_bulk",
    "alive_nodes", "get_node", "all_nodes",
    "report_telemetry", "telemetry_snapshots", "postmortems",
    # profiling plane (util/profiler.py via cross_host.HeadService):
    # stack dumps / sampling profiles / xplane captures on any node
    "profile_start", "profile_fetch",
    "register_actor", "update_actor", "get_actor", "get_named_actor",
    "list_actors",
    "register_job", "finish_job", "list_jobs",
    "kv_put", "kv_get", "kv_del", "kv_keys",
    # object-directory ops for joined worker hosts (cross_host.HeadService)
    "dir_add_location", "dir_remove_location", "dir_locations",
    # ownership back-channel: nested submission from joined-host code
    # (cross_host.HeadService proxy_*, worker_api.WorkerAPIClient)
    "proxy_job_id", "proxy_submit_task", "proxy_create_actor",
    "proxy_submit_actor_task", "proxy_kill_actor", "proxy_ref_state",
    "proxy_put", "proxy_pin", "proxy_free", "proxy_get_value",
    "proxy_keepalive", "proxy_submit_streaming",
    # pubsub registration: dispatched before the allowlist check in the
    # handler (it mutates per-connection push state), but it belongs here
    # so the registry invariant (idempotent ⊆ allowed) holds
    "subscribe",
}

# Methods safe to resend after an ambiguous connection loss (the reply may
# have been lost AFTER the head applied the request): reads, liveness
# refreshes, and set-semantics writes. Everything else (register_actor,
# proxy_submit_*, ...) surfaces ControlPlaneUnavailable instead — a blind
# resend could duplicate the mutation, so the caller decides.
_IDEMPOTENT_METHODS: Set[str] = {
    "heartbeat", "heartbeat_bulk", "alive_nodes", "get_node", "all_nodes",
    # telemetry: metrics replace the prior snapshot, spans dedupe by id,
    # timeline events are cursor-guarded — a resend is absorbed
    "report_telemetry", "telemetry_snapshots", "postmortems",
    # profile_start is a no-op while a window is already open and
    # profile_fetch re-reads the same accumulation — resends absorb
    "profile_start", "profile_fetch",
    "get_actor", "get_named_actor", "list_actors", "list_jobs",
    "kv_put", "kv_get", "kv_del", "kv_keys",
    "dir_add_location", "dir_remove_location", "dir_locations",
    "subscribe",
    "proxy_job_id", "proxy_ref_state", "proxy_keepalive", "proxy_free",
    "proxy_pin", "proxy_get_value",
}


def shard_for_key(key: str, nshards: int) -> int:
    """Consistent key→shard routing for the federated control plane.

    Stable across processes and Python runs (crc32, not hash()): every
    client and every shard service must agree on ownership, including a
    client that reconnects after a shard failover. Keys hash as raw
    strings — no namespace stripping — so a key's owner never depends on
    how callers spell prefixes."""
    if nshards <= 1:
        return 0
    return zlib.crc32(key.encode("utf-8", "surrogatepass")) % nshards


class _DialGate:
    """Process-wide reconnect dial-rate cap (token bucket).

    128 agents that all lost the same shard must not thundering-herd the
    restarted/promoted listener with simultaneous SYNs + resubscribe
    bursts: every reconnect dial in this process first takes a token
    here (config ``control_plane_redial_rate`` tokens/s, burst of one
    second's worth). First dials at construction are NOT gated — join
    latency is user-visible; only the storm-prone redial path is."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._tokens = 0.0
        self._stamp = time.monotonic()

    def acquire(self, cancel: threading.Event) -> None:
        rate = float(config.control_plane_redial_rate)
        if rate <= 0:
            return  # cap disabled
        throttled = False
        while not cancel.is_set():
            with self._lock:
                now = time.monotonic()
                self._tokens = min(rate, self._tokens + (now - self._stamp) * rate)
                self._stamp = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                wait = (1.0 - self._tokens) / rate
            if not throttled:
                throttled = True
                _redials_throttled.inc()
            cancel.wait(min(wait, 0.5))


_dial_gate = _DialGate()


class ControlPlaneUnavailable(ConnectionError):
    """Retryable: the control plane is unreachable (head down or
    restarting) or the call's deadline elapsed before a reply landed.
    Idempotent methods never raise this while the deadline allows a
    retry; for non-idempotent methods the caller owns the retry decision
    (the request MAY have been applied)."""


class _ConnLost(Exception):
    """Internal: the connection died before this call's reply arrived."""


class _DeadlineExceeded(Exception):
    """Internal: the per-call deadline elapsed while waiting for a reply."""


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: "ControlPlaneServer" = self.server  # type: ignore[assignment]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        send_lock = threading.Lock()
        unsubscribes = []
        server._track(sock)
        try:
            while True:
                msg_type, req = recv_msg(sock)
                if msg_type != MSG_REQUEST:
                    raise WireError(f"unexpected message type {msg_type}")
                method = req.get("method", "")
                if method == "subscribe":
                    # push this channel's events to the client as EVENT
                    # frames; on the first push failure the subscription is
                    # dropped immediately — a client that reconnects many
                    # times must not accumulate dead sinks head-side until
                    # the next request on this (gone) handler
                    channel = req["args"][0]
                    unsub_cell: List[Callable[[], None]] = []

                    def push(message, _ch=channel, _cell=unsub_cell):
                        try:
                            with send_lock:
                                send_msg(sock, MSG_EVENT,
                                         {"channel": _ch, "message": message})
                        except OSError:
                            if _cell:
                                _cell[0]()

                    unsub = server.control_plane.pubsub.subscribe(channel, push)
                    unsub_cell.append(unsub)
                    unsubscribes.append(unsub)
                    resp = {"id": req["id"], "ok": True, "value": True}
                elif method not in server.allowed_methods:
                    resp = {"id": req["id"], "ok": False,
                            "error": f"method {method!r} not served", "exc": None}
                else:
                    try:
                        value = getattr(server.control_plane, method)(
                            *req.get("args", ()), **req.get("kwargs", {})
                        )
                        resp = {"id": req["id"], "ok": True, "value": value}
                    except Exception as e:  # noqa: BLE001 — serialized to caller
                        resp = {"id": req["id"], "ok": False,
                                "error": repr(e), "exc": e}
                try:
                    with send_lock:
                        send_msg(sock, MSG_RESPONSE, resp)
                except (TypeError, ValueError, AttributeError) as e:
                    # unpicklable value/exception: degrade to a string error
                    # rather than tearing down the connection
                    with send_lock:
                        send_msg(sock, MSG_RESPONSE, {
                            "id": req["id"], "ok": False,
                            "error": f"unserializable response: {e!r}",
                            "exc": None,
                        })
        except (WireError, OSError):
            pass  # client disconnected
        finally:
            server._untrack(sock)
            for unsub in unsubscribes:
                try:
                    unsub()
                except Exception:
                    pass


class ControlPlaneServer(socketserver.ThreadingTCPServer):
    """Serves one ControlPlane on host:port (0 = ephemeral)."""

    daemon_threads = True
    allow_reuse_address = True
    # handler threads are daemons blocked in recv: joining them on close
    # would hang until every client disconnects — stop() severs them instead
    block_on_close = False

    def __init__(self, control_plane, host: str = "127.0.0.1", port: int = 0,
                 allowed_methods: Optional[Set[str]] = None):
        super().__init__((host, port), _Handler)
        self.control_plane = control_plane
        # per-service registry: shard / aggregator services reuse this
        # server with their own (raylint-R3-checked) literal allowlists
        self.allowed_methods = (allowed_methods if allowed_methods is not None
                                else _ALLOWED_METHODS)
        self._conn_lock = threading.Lock()
        self._conns: Set[socket.socket] = set()
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="cp-rpc-server"
        )
        self._thread.start()
        logger.info("control-plane RPC on %s:%d", *self.server_address)

    def _track(self, sock: socket.socket) -> None:
        with self._conn_lock:
            self._conns.add(sock)

    def _untrack(self, sock: socket.socket) -> None:
        with self._conn_lock:
            self._conns.discard(sock)

    @property
    def address(self) -> str:
        host, port = self.server_address
        return f"{host}:{port}"

    def stop(self) -> None:
        self.shutdown()
        self.server_close()
        # sever established connections: a stopped head must look exactly
        # like a dead one to its clients (their read loops wake with a
        # WireError and begin reconnecting), and the handler threads exit
        with self._conn_lock:
            conns = list(self._conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


def serve_control_plane(control_plane, host: str = "127.0.0.1",
                        port: int = 0) -> ControlPlaneServer:
    """host: bind address — 127.0.0.1 for same-host attach (default),
    0.0.0.0 (config control_plane_rpc_host) for cross-host."""
    return ControlPlaneServer(control_plane, host, port)


class _Conn:
    """One TCP connection's request/reply state. Replies land in THIS
    connection's map only, so a stale response delivered after a reconnect
    cannot be confused with a reply to a request on the new connection
    (request ids restart at 1 per connection by design)."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.send_lock = threading.Lock()
        self.next_id = 0
        self.replies: Dict[int, Any] = {}
        self.cv = threading.Condition()
        self.dead = threading.Event()

    def fail(self) -> None:
        with self.cv:
            self.dead.set()
            self.cv.notify_all()

    def close(self) -> None:
        self.fail()
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class RemoteControlPlane:
    """Client proxy with ControlPlane's duck-typed surface.

    Method calls serialize over one socket; `subscribe(channel, cb)`
    transparently registers a server-side push and dispatches EVENT frames
    from a reader thread into a local Pubsub. The connection self-heals
    (see module docstring); callers observe at most a retryable
    ControlPlaneUnavailable, bounded by the per-call deadline."""

    def __init__(self, address: str, connect_timeout: float = 10.0,
                 role: str = "client",
                 allowed: Optional[Set[str]] = None,
                 idempotent: Optional[Set[str]] = None):
        from .control_plane import Pubsub

        self._address = address
        self._connect_timeout = connect_timeout
        self._role = role
        # per-service registries (default: the head surface) — shard and
        # aggregator clients pass their own literal sets
        self._allowed = allowed if allowed is not None else _ALLOWED_METHODS
        self._idempotent = (idempotent if idempotent is not None
                            else _IDEMPOTENT_METHODS)
        self.pubsub = Pubsub()
        self._subscribed: Set[str] = set()
        self._sub_lock = threading.Lock()
        # events are delivered off-reader (see module docstring): the
        # dispatcher thread starts lazily with the first pushed event
        self._event_q: "queue.SimpleQueue[Optional[tuple]]" = queue.SimpleQueue()
        self._event_thread: Optional[threading.Thread] = None
        self._event_lock = threading.Lock()
        self._closed = threading.Event()
        self._conn_cv = threading.Condition()
        self._conn: Optional[_Conn] = None
        self._reconnect_listeners: List[Callable[[], None]] = []
        self.reconnect_count = 0
        # the first dial is synchronous: an unreachable head at construction
        # surfaces to the caller (join-time errors must not become silent
        # background retries)
        conn = self._dial()
        with self._conn_cv:
            self._conn = conn
            self._conn_cv.notify_all()

    # -- connection lifecycle ------------------------------------------------
    def _dial(self) -> _Conn:
        host, _, port = self._address.rpartition(":")
        sock = socket.create_connection((host, int(port)), self._connect_timeout)
        # create_connection leaves its timeout on the socket: clear it, or
        # an idle read loop dies with TimeoutError after connect_timeout
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Conn(sock)
        threading.Thread(
            target=self._read_loop, args=(conn,), daemon=True,
            name="cp-rpc-client",
        ).start()
        return conn

    def _read_loop(self, conn: _Conn) -> None:
        try:
            while True:
                msg_type, payload = recv_msg(conn.sock)
                if msg_type == MSG_EVENT:
                    self._enqueue_event(payload["channel"], payload["message"])
                elif msg_type == MSG_RESPONSE:
                    with conn.cv:
                        conn.replies[payload["id"]] = payload
                        conn.cv.notify_all()
        except Exception:  # noqa: BLE001 — ANY reader death must wake waiters
            pass
        finally:
            conn.close()
            self._on_conn_lost(conn)

    def _enqueue_event(self, channel: str, message: Any) -> None:
        self._event_q.put((channel, message))
        t = self._event_thread
        if t is not None and t.is_alive():
            return
        with self._event_lock:
            t = self._event_thread
            if (t is None or not t.is_alive()) and not self._closed.is_set():
                t = threading.Thread(target=self._event_loop, daemon=True,
                                     name="cp-rpc-events")
                self._event_thread = t
                t.start()

    def _event_loop(self) -> None:
        while True:
            item = self._event_q.get()
            if item is None or self._closed.is_set():
                return
            self.pubsub.publish(*item)

    def _on_conn_lost(self, conn: _Conn) -> None:
        with self._conn_cv:
            if self._conn is not conn:
                return  # stale connection; the current one is healthy
            self._conn = None
            self._conn_cv.notify_all()
        if self._closed.is_set():
            return
        logger.warning("control-plane connection to %s lost; reconnecting",
                       self._address)
        threading.Thread(
            target=self._reconnect_loop, daemon=True, name="cp-rpc-reconnect"
        ).start()

    def _reconnect_loop(self) -> None:
        # Decorrelated jitter (not pure doubling): N clients that lost the
        # same shard at the same instant must desynchronize, or every
        # backoff round re-delivers the whole herd at once. Each sleep is
        # drawn from [base, 3*previous], capped at the config maximum; the
        # process-wide _DialGate then rate-limits the dials themselves.
        cap = max(0.05, config.control_plane_reconnect_max_s)
        backoff = random.uniform(0.05, 0.15)
        while not self._closed.is_set():
            _dial_gate.acquire(self._closed)
            if self._closed.is_set():
                return
            try:
                conn = self._dial()
            except OSError:
                self._closed.wait(backoff)
                backoff = min(cap, random.uniform(0.05, backoff * 3))
                continue
            # re-register every subscribed channel BEFORE installing the
            # connection, so pubsub resumes atomically with the reconnect
            with self._sub_lock:
                channels = list(self._subscribed)
            try:
                deadline = time.monotonic() + max(5.0, self._connect_timeout)
                for ch in channels:
                    self._roundtrip(conn, "subscribe", (ch,), {}, deadline)
            except Exception:  # noqa: BLE001 — died mid-resubscribe: redial
                conn.close()
                self._closed.wait(backoff)
                backoff = min(cap, random.uniform(0.05, backoff * 3))
                continue
            with self._conn_cv:
                if self._closed.is_set():
                    conn.close()
                    return
                if conn.dead.is_set():
                    # the reader died BEFORE install, so its _on_conn_lost
                    # saw a non-current conn and spawned nothing: installing
                    # this corpse would strand the client with no reconnect
                    # thread — retry the dial instead
                    continue
                self._conn = conn
                self.reconnect_count += 1
                self._conn_cv.notify_all()
            _reconnects_total.inc(tags={"role": self._role})
            logger.info(
                "control-plane connection to %s re-established "
                "(%d channels resubscribed)", self._address, len(channels))
            for cb in list(self._reconnect_listeners):
                try:
                    cb()
                except Exception:  # noqa: BLE001 — listeners are best-effort
                    logger.warning("reconnect listener failed", exc_info=True)
            return

    def add_reconnect_listener(self, cb: Callable[[], None]) -> Callable[[], None]:
        """Run cb after every re-established connection (on the reconnect
        thread) — the hook worker hosts use to re-register their NodeInfo
        and re-advertise held objects. Returns a remover."""
        self._reconnect_listeners.append(cb)

        def remove() -> None:
            try:
                self._reconnect_listeners.remove(cb)
            except ValueError:
                pass

        return remove

    # -- plumbing -----------------------------------------------------------
    def _wait_conn(self, deadline: float, method: str) -> _Conn:
        with self._conn_cv:
            while True:
                if self._closed.is_set():
                    raise WireError("control-plane client closed")
                conn = self._conn
                if conn is not None and not conn.dead.is_set():
                    return conn
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ControlPlaneUnavailable(
                        f"control plane at {self._address} unreachable: "
                        f"{method!r} deadline exceeded")
                self._conn_cv.wait(min(0.5, remaining))

    def _roundtrip(self, conn: _Conn, method: str, args, kwargs,
                   deadline: float) -> Any:
        with conn.send_lock:
            if conn.dead.is_set():
                raise _ConnLost()
            conn.next_id += 1
            req_id = conn.next_id
            try:
                send_msg(conn.sock, MSG_REQUEST,
                         {"id": req_id, "method": method,
                          "args": args, "kwargs": kwargs})
            except (WireError, OSError):
                # close so the blocked reader wakes and triggers reconnect
                # even when only the send path is broken (e.g. chaos drop)
                conn.close()
                raise _ConnLost() from None
        with conn.cv:
            while req_id not in conn.replies:
                if conn.dead.is_set():
                    raise _ConnLost()
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise _DeadlineExceeded()
                conn.cv.wait(min(0.5, remaining))
            return conn.replies.pop(req_id)

    def _call(self, method: str, *args, _deadline_s: Optional[float] = None,
              **kwargs) -> Any:
        """One RPC under a deadline. `_deadline_s` overrides the config
        default (it is consumed here — never forwarded to the server)."""
        if _deadline_s is None:
            _deadline_s = config.control_plane_call_deadline_s
        deadline = time.monotonic() + _deadline_s
        retryable = method in self._idempotent
        while True:
            conn = self._wait_conn(deadline, method)
            try:
                resp = self._roundtrip(conn, method, args, kwargs, deadline)
            except _ConnLost:
                if retryable:
                    continue  # _wait_conn enforces the deadline
                raise ControlPlaneUnavailable(
                    f"control-plane connection lost during non-idempotent "
                    f"{method!r}; the request may or may not have been "
                    f"applied — the caller owns the retry") from None
            except _DeadlineExceeded:
                raise ControlPlaneUnavailable(
                    f"control-plane call {method!r} exceeded its "
                    f"{_deadline_s:.1f}s deadline") from None
            if resp["ok"]:
                return resp["value"]
            if resp.get("exc") is not None:
                raise resp["exc"]
            raise RuntimeError(resp["error"])

    def subscribe(self, channel: str, callback) -> Any:
        """Subscribe via the local pubsub, lazily registering the remote
        push for this channel. The channel is recorded FIRST: if the head
        is unreachable right now, the reconnect path registers it as soon
        as a connection lands, so the subscription still takes effect."""
        with self._sub_lock:
            first = channel not in self._subscribed
            self._subscribed.add(channel)
        if first:
            try:
                # short deadline: if the head is down, don't park the caller
                # for the full default — the reconnect path registers the
                # channel anyway
                self._call("subscribe", channel, _deadline_s=5.0)
            except ControlPlaneUnavailable:
                logger.warning(
                    "subscribe(%r) deferred: head unreachable (will "
                    "register on reconnect)", channel)
        return self.pubsub.subscribe(channel, callback)

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        with self._conn_cv:
            conn, self._conn = self._conn, None
            self._conn_cv.notify_all()
        if conn is not None:
            conn.close()
        self._event_q.put(None)  # unblock the dispatcher so it exits
        t = self._event_thread
        if t is not None and t is not threading.current_thread():
            # in-flight callbacks fail fast post-close (_wait_conn raises),
            # so this join is a bounded courtesy for the leak guard
            t.join(timeout=5.0)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._allowed:
            raise AttributeError(f"{name!r} is not part of the served surface")

        def call(*args, **kwargs):
            return self._call(name, *args, **kwargs)

        call.__name__ = name
        return call


# methods whose first positional argument is the routing key (a KV key or a
# pubsub channel): these go to the owning shard, the rest of the surface
# rides the head connection
_SHARD_ROUTED_METHODS: Set[str] = {
    "kv_put", "kv_get", "kv_del",
    "publish", "subscribe",
}

# object-location gossip routes to the shards only when the client opts in
# (route_directory=True — the scale harness / pure-gossip fleets). Real
# worker hosts keep dir_* on the head connection: the head's in-process
# ObjectDirectory is the authority its scheduler, lineage reconstruction
# and pull planner read, so splitting writes away from it would fork the
# directory view.
_SHARD_DIR_METHODS: Set[str] = {
    "dir_add_location", "dir_remove_location", "dir_locations",
}


class ShardedControlPlane:
    """Client for a federated control plane: one head connection for the
    node/actor/job/telemetry tables, K shard connections for the KV store,
    pubsub fan-out, and (opt-in) object-directory gossip (consistent
    routing via `shard_for_key`). Duck-compatible with RemoteControlPlane —
    a worker runtime swaps it in without caring. Every underlying
    connection keeps its own PR 4 reconnect loop, so a shard failover is
    ridden out per-connection while head traffic continues untouched."""

    def __init__(self, head_address, shard_addresses: List[str],
                 connect_timeout: float = 10.0, role: str = "client",
                 route_directory: bool = False):
        from .shard import _SHARD_ALLOWED_METHODS, _SHARD_IDEMPOTENT_METHODS

        # an already-connected head client may be handed over (the worker
        # join path probes the shard map on its head connection first)
        self._head = (head_address
                      if isinstance(head_address, RemoteControlPlane)
                      else RemoteControlPlane(
                          head_address, connect_timeout=connect_timeout,
                          role=role))
        self._routed = (_SHARD_ROUTED_METHODS | _SHARD_DIR_METHODS
                        if route_directory else _SHARD_ROUTED_METHODS)
        self._shards = [
            RemoteControlPlane(
                addr, connect_timeout=connect_timeout,
                role=f"{role}-shard{i}",
                allowed=_SHARD_ALLOWED_METHODS,
                idempotent=_SHARD_IDEMPOTENT_METHODS)
            for i, addr in enumerate(shard_addresses)
        ]
        self.pubsub = self._head.pubsub  # head-channel events land here

    # -- routing -------------------------------------------------------------
    @property
    def head(self) -> RemoteControlPlane:
        return self._head

    @property
    def shards(self) -> List[RemoteControlPlane]:
        return list(self._shards)

    def _shard_client(self, key: str) -> RemoteControlPlane:
        return self._shards[shard_for_key(key, len(self._shards))]

    def _call(self, method: str, *args, **kwargs) -> Any:
        if method in self._routed and self._shards and args:
            return self._shard_client(args[0])._call(method, *args, **kwargs)
        if method == "kv_keys":
            return self.kv_keys(*args, **kwargs)
        return self._head._call(method, *args, **kwargs)

    def kv_keys(self, prefix: str = "", **kwargs) -> List[str]:
        """Prefix listing fans out: a prefix does not pin a shard (keys
        route on their FULL string), so the union across shards is the
        authoritative listing."""
        out: List[str] = []
        for client in self._shards:
            out.extend(client._call("kv_keys", prefix, **kwargs))
        return out

    def subscribe(self, channel: str, callback) -> Any:
        return self._shard_client(channel).subscribe(channel, callback)

    def add_reconnect_listener(self, cb: Callable[[], None]) -> Callable[[], None]:
        """Fires after ANY underlying connection re-establishes: rejoin
        logic re-puts KV (shard-owned) and re-registers the node (head-
        owned), and the whole sequence is idempotent, so re-running it on
        either kind of reconnect is safe and always sufficient."""
        removers = [self._head.add_reconnect_listener(cb)]
        removers += [s.add_reconnect_listener(cb) for s in self._shards]

        def remove() -> None:
            for r in removers:
                r()

        return remove

    @property
    def reconnect_count(self) -> int:
        return self._head.reconnect_count + sum(
            s.reconnect_count for s in self._shards)

    def close(self) -> None:
        self._head.close()
        for s in self._shards:
            s.close()

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self._routed and self._shards:

            def routed(*args, **kwargs):
                return self._call(name, *args, **kwargs)

            routed.__name__ = name
            return routed
        return getattr(self._head, name)
