"""Control-plane RPC: the single-authority tables served over TCP.

Reference analogue: `src/ray/rpc/gcs_server/` (GcsRpcServer) and
`gcs_client/` — every daemon talks to the GCS over gRPC. Here the same
shape: `serve_control_plane` exposes a ControlPlane's public methods on a
socket, `RemoteControlPlane` is a drop-in client with the same duck-typed
surface, so a Runtime on another host (or another OS process on the same
host) can share one authority. Pubsub crosses the wire as pushed EVENT
frames feeding the client's local Pubsub — subscribers are oblivious.

Threading model: one handler thread per connection (control-plane call
rates are low; no need for an event loop), one push thread per subscribed
client. The client proxy serializes request/response pairs over one
socket with a lock and routes pushed events to its Pubsub from a reader
thread.
"""

from __future__ import annotations

import socket
import socketserver
import threading
from typing import Any, Dict, Optional, Set

from .logging import get_logger
from .wire import MSG_EVENT, MSG_REQUEST, MSG_RESPONSE, WireError, recv_msg, send_msg

logger = get_logger("rpc")

# the served surface (N1's public API): anything else is rejected
_ALLOWED_METHODS: Set[str] = {
    "register_node", "mark_node_dead", "heartbeat", "alive_nodes",
    "get_node", "all_nodes",
    "register_actor", "update_actor", "get_actor", "get_named_actor",
    "list_actors",
    "register_job", "finish_job", "list_jobs",
    "kv_put", "kv_get", "kv_del", "kv_keys",
    # object-directory ops for joined worker hosts (cross_host.HeadService)
    "dir_add_location", "dir_remove_location", "dir_locations",
    # ownership back-channel: nested submission from joined-host code
    # (cross_host.HeadService proxy_*, worker_api.WorkerAPIClient)
    "proxy_job_id", "proxy_submit_task", "proxy_create_actor",
    "proxy_submit_actor_task", "proxy_kill_actor", "proxy_ref_state",
    "proxy_put", "proxy_pin", "proxy_free", "proxy_get_value",
    "proxy_keepalive", "proxy_submit_streaming",
}


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: "ControlPlaneServer" = self.server  # type: ignore[assignment]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        send_lock = threading.Lock()
        unsubscribes = []
        try:
            while True:
                msg_type, req = recv_msg(sock)
                if msg_type != MSG_REQUEST:
                    raise WireError(f"unexpected message type {msg_type}")
                method = req.get("method", "")
                if method == "subscribe":
                    # push this channel's events to the client as EVENT frames
                    channel = req["args"][0]

                    def push(message, _ch=channel):
                        try:
                            with send_lock:
                                send_msg(sock, MSG_EVENT,
                                         {"channel": _ch, "message": message})
                        except OSError:
                            pass  # client gone; reaped on next request

                    unsubscribes.append(
                        server.control_plane.pubsub.subscribe(channel, push)
                    )
                    resp = {"id": req["id"], "ok": True, "value": True}
                elif method not in _ALLOWED_METHODS:
                    resp = {"id": req["id"], "ok": False,
                            "error": f"method {method!r} not served", "exc": None}
                else:
                    try:
                        value = getattr(server.control_plane, method)(
                            *req.get("args", ()), **req.get("kwargs", {})
                        )
                        resp = {"id": req["id"], "ok": True, "value": value}
                    except Exception as e:  # noqa: BLE001 — serialized to caller
                        resp = {"id": req["id"], "ok": False,
                                "error": repr(e), "exc": e}
                try:
                    with send_lock:
                        send_msg(sock, MSG_RESPONSE, resp)
                except (TypeError, ValueError, AttributeError) as e:
                    # unpicklable value/exception: degrade to a string error
                    # rather than tearing down the connection
                    with send_lock:
                        send_msg(sock, MSG_RESPONSE, {
                            "id": req["id"], "ok": False,
                            "error": f"unserializable response: {e!r}",
                            "exc": None,
                        })
        except (WireError, OSError):
            pass  # client disconnected
        finally:
            for unsub in unsubscribes:
                try:
                    unsub()
                except Exception:
                    pass


class ControlPlaneServer(socketserver.ThreadingTCPServer):
    """Serves one ControlPlane on host:port (0 = ephemeral)."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, control_plane, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _Handler)
        self.control_plane = control_plane
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="cp-rpc-server"
        )
        self._thread.start()
        logger.info("control-plane RPC on %s:%d", *self.server_address)

    @property
    def address(self) -> str:
        host, port = self.server_address
        return f"{host}:{port}"

    def stop(self) -> None:
        self.shutdown()
        self.server_close()


def serve_control_plane(control_plane, host: str = "127.0.0.1",
                        port: int = 0) -> ControlPlaneServer:
    """host: bind address — 127.0.0.1 for same-host attach (default),
    0.0.0.0 (config control_plane_rpc_host) for cross-host."""
    return ControlPlaneServer(control_plane, host, port)


class RemoteControlPlane:
    """Client proxy with ControlPlane's duck-typed surface.

    Method calls serialize over one socket; `pubsub.subscribe(channel, cb)`
    transparently registers a server-side push and dispatches EVENT frames
    from a reader thread into a local Pubsub."""

    def __init__(self, address: str, connect_timeout: float = 10.0):
        from .control_plane import Pubsub

        host, _, port = address.rpartition(":")
        self._sock = socket.create_connection((host, int(port)), connect_timeout)
        # create_connection leaves its timeout on the socket: clear it, or
        # an idle read loop dies with TimeoutError after connect_timeout
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self._next_id = 0
        self._replies: Dict[int, Any] = {}
        self._reply_cv = threading.Condition()
        self.pubsub = Pubsub()
        self._subscribed: Set[str] = set()
        self._closed = threading.Event()
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True, name="cp-rpc-client"
        )
        self._reader.start()

    # -- plumbing -----------------------------------------------------------
    def _read_loop(self) -> None:
        try:
            while not self._closed.is_set():
                msg_type, payload = recv_msg(self._sock)
                if msg_type == MSG_EVENT:
                    self.pubsub.publish(payload["channel"], payload["message"])
                elif msg_type == MSG_RESPONSE:
                    with self._reply_cv:
                        self._replies[payload["id"]] = payload
                        self._reply_cv.notify_all()
        except Exception:  # noqa: BLE001 — ANY reader death must wake waiters
            with self._reply_cv:
                self._replies[-1] = None  # poison: wake waiters
                self._closed.set()
                self._reply_cv.notify_all()

    def _call(self, method: str, *args, **kwargs) -> Any:
        with self._lock:
            self._next_id += 1
            req_id = self._next_id
            send_msg(self._sock, MSG_REQUEST,
                     {"id": req_id, "method": method,
                      "args": args, "kwargs": kwargs})
        with self._reply_cv:
            while req_id not in self._replies:
                if self._closed.is_set():
                    raise WireError("control-plane connection lost")
                self._reply_cv.wait(timeout=1.0)
            resp = self._replies.pop(req_id)
        if resp["ok"]:
            return resp["value"]
        if resp.get("exc") is not None:
            raise resp["exc"]
        raise RuntimeError(resp["error"])

    def subscribe(self, channel: str, callback) -> Any:
        """Subscribe via the local pubsub, lazily registering the remote
        push for this channel."""
        if channel not in self._subscribed:
            self._call("subscribe", channel)
            self._subscribed.add(channel)
        return self.pubsub.subscribe(channel, callback)

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in _ALLOWED_METHODS:
            raise AttributeError(f"{name!r} is not part of the served surface")

        def call(*args, **kwargs):
            return self._call(name, *args, **kwargs)

        call.__name__ = name
        return call
