// Native object-transfer data plane over the shm store.
//
// Reference analogue: src/ray/object_manager/ — the C++ transfer plane
// (PullManager/PushManager + ObjectManagerService) that moves sealed
// plasma objects between nodes without the driver language in the loop.
// Same split here: Python owns the CONTROL path (who holds what, which
// address; see core/object_transfer.py), while this file is the DATA
// path — a serving thread streams a sealed object straight out of the
// mmap'd arena (shm_obj_get pins it; no intermediate buffer, no
// per-chunk RPC framing), and the pulling side receives into a single
// caller-provided buffer with the GIL released (ctypes).
//
// Protocol (one TCP connection, many sequential pulls):
//   request : [1B op=1][20B object id]
//   response: [1B status]                 status 1 = not found
//             [8B big-endian size][size bytes]   when status 0
//
// Compiled into libshm_store.so together with shm_store.cc (see
// Makefile); the store functions below resolve within the same .so.
// TSAN builds cover the serving threads via the in-process tests in
// tests/test_shm_store.py (fork-free, like the store's own TSAN tier).

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <utility>
#include <vector>

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

extern "C" {
// from shm_store.cc (same shared object)
void* shm_obj_get(void* handle, const uint8_t* id, uint64_t* size_out);
int shm_obj_release(void* handle, const uint8_t* id);
void* shm_obj_create(void* handle, const uint8_t* id, uint64_t size);
int shm_obj_seal(void* handle, const uint8_t* id);
int shm_obj_delete(void* handle, const uint8_t* id);
}

namespace {

constexpr int kIdSize = 20;
constexpr uint8_t kOpPull = 1;
constexpr uint8_t kStatusOk = 0;
constexpr uint8_t kStatusMissing = 1;

// A stalled puller (zero TCP window) must not block a serving thread
// forever: the thread holds a pin on the blob it is streaming, and a
// pinned entry can never be evicted — an unbounded send would strand
// that arena region for the holder's lifetime. Receives stay unbounded
// on the server (idle pooled connections are normal).
constexpr int kServerSendTimeoutMs = 30000;

bool SendAll(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t w = send(fd, p, n, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool RecvAll(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (r == 0) return false;  // peer closed mid-message
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void PackU64(uint8_t* out, uint64_t v) {
  for (int i = 7; i >= 0; i--) {
    out[i] = static_cast<uint8_t>(v & 0xff);
    v >>= 8;
  }
}

uint64_t UnpackU64(const uint8_t* in) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | in[i];
  return v;
}

struct TransferServer {
  void* store = nullptr;
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stopping{false};
  std::thread accept_thread;
  std::mutex mu;                    // guards conn_fds + active_conns
  std::condition_variable done_cv;  // stop() waits for active_conns == 0
  std::vector<int> conn_fds;        // slot table; -1 = free (slots reused,
                                    // so churn does not grow the vector)
  int active_conns = 0;
};

// Serve sequential pulls on one connection until EOF/error/stop. Runs
// detached; clears its slot under the lock BEFORE closing the fd, so
// stop() can never shutdown() an fd number the OS has reassigned.
void ServeConn(TransferServer* srv, int fd, size_t slot) {
  uint8_t req[1 + kIdSize];
  while (!srv->stopping.load(std::memory_order_relaxed)) {
    if (!RecvAll(fd, req, sizeof(req))) break;
    if (req[0] != kOpPull) break;  // unknown op: drop the connection
    uint64_t size = 0;
    void* ptr = shm_obj_get(srv->store, req + 1, &size);
    if (ptr == nullptr) {
      uint8_t status = kStatusMissing;
      if (!SendAll(fd, &status, 1)) break;
      continue;
    }
    uint8_t head[9];
    head[0] = kStatusOk;
    PackU64(head + 1, size);
    bool ok = SendAll(fd, head, sizeof(head)) && SendAll(fd, ptr, size);
    shm_obj_release(srv->store, req + 1);
    if (!ok) break;
  }
  {
    std::lock_guard<std::mutex> g(srv->mu);
    srv->conn_fds[slot] = -1;
    srv->active_conns--;
    srv->done_cv.notify_all();
  }
  close(fd);  // after the slot is cleared: stop() no longer sees this fd
}

void AcceptLoop(TransferServer* srv) {
  while (!srv->stopping.load(std::memory_order_relaxed)) {
    int fd = accept(srv->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      break;  // listen fd shut down (stop) or fatal
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    timeval tv;
    tv.tv_sec = kServerSendTimeoutMs / 1000;
    tv.tv_usec = (kServerSendTimeoutMs % 1000) * 1000;
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    std::lock_guard<std::mutex> g(srv->mu);
    if (srv->stopping.load(std::memory_order_relaxed)) {
      close(fd);
      break;
    }
    size_t slot = 0;
    while (slot < srv->conn_fds.size() && srv->conn_fds[slot] != -1) slot++;
    if (slot == srv->conn_fds.size()) srv->conn_fds.push_back(fd);
    else srv->conn_fds[slot] = fd;
    srv->active_conns++;
    std::thread(ServeConn, srv, fd, slot).detach();
  }
}

}  // namespace

extern "C" {

// Start serving `store` on `host`:`port` (port 0 = ephemeral). Returns
// an opaque handle or null; *port_out receives the bound port.
void* shm_transfer_server_start(void* store, const char* host, int port,
                                int* port_out) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return nullptr;
  }
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  if (getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len) != 0) {
    close(fd);
    return nullptr;
  }
  TransferServer* srv = new TransferServer();
  srv->store = store;
  srv->listen_fd = fd;
  srv->port = ntohs(addr.sin_port);
  if (port_out != nullptr) *port_out = srv->port;
  srv->accept_thread = std::thread(AcceptLoop, srv);
  return srv;
}

int shm_transfer_server_port(void* handle) {
  return static_cast<TransferServer*>(handle)->port;
}

void shm_transfer_server_stop(void* handle) {
  TransferServer* srv = static_cast<TransferServer*>(handle);
  srv->stopping.store(true, std::memory_order_relaxed);
  shutdown(srv->listen_fd, SHUT_RDWR);
  srv->accept_thread.join();
  close(srv->listen_fd);
  {
    std::unique_lock<std::mutex> lk(srv->mu);
    for (int fd : srv->conn_fds)
      if (fd != -1) shutdown(fd, SHUT_RDWR);  // wakes blocked recv/send
    srv->done_cv.wait(lk, [srv] { return srv->active_conns == 0; });
  }
  delete srv;
}

// Client side. One fd per holder, reused across pulls (mirrors the
// pooled connections of the Python control path). `timeout_ms` bounds
// the connect AND every subsequent send/recv on the fd — a holder whose
// native port is blackholed must fail fast so the puller can fall back
// to the chunked control-path transfer (which carries its own timeout).
int shm_transfer_connect(const char* host, int port, int timeout_ms) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return -1;
  }
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
  if (rc != 0) {
    if (errno != EINPROGRESS) {
      close(fd);
      return -1;
    }
    pollfd pfd = {fd, POLLOUT, 0};
    if (poll(&pfd, 1, timeout_ms) != 1) {
      close(fd);
      return -1;  // timed out (or poll error)
    }
    int err = 0;
    socklen_t len = sizeof(err);
    if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) != 0 || err != 0) {
      close(fd);
      return -1;
    }
  }
  fcntl(fd, F_SETFL, flags);  // back to blocking, bounded by SO_*TIMEO
  timeval tv;
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

// Pull object `id` into caller buffer `buf` (capacity `cap`). Returns the
// object size on success; -1 on connection error; -2 if the holder does
// not have the object; -3 if the object exceeds `cap` (the payload is
// drained so the connection stays usable).
int64_t shm_transfer_pull_buf(int fd, const uint8_t* id, void* buf,
                              uint64_t cap) {
  uint8_t req[1 + kIdSize];
  req[0] = kOpPull;
  memcpy(req + 1, id, kIdSize);
  if (!SendAll(fd, req, sizeof(req))) return -1;
  uint8_t status;
  if (!RecvAll(fd, &status, 1)) return -1;
  if (status == kStatusMissing) return -2;
  if (status != kStatusOk) return -1;
  uint8_t size_be[8];
  if (!RecvAll(fd, size_be, sizeof(size_be))) return -1;
  uint64_t size = UnpackU64(size_be);
  if (size > cap) {
    uint8_t scratch[1 << 16];
    uint64_t left = size;
    while (left > 0) {
      size_t n = left < sizeof(scratch) ? static_cast<size_t>(left)
                                        : sizeof(scratch);
      if (!RecvAll(fd, scratch, n)) return -1;
      left -= n;
    }
    return -3;
  }
  if (!RecvAll(fd, buf, size)) return -1;
  return static_cast<int64_t>(size);
}

// Pull object `id` straight into `dst_store` (create -> recv into the
// mapped arena -> seal): no caller-side allocation at all, which matters
// because the puller's buffer would otherwise be zero-filled by the
// allocator before the recv overwrites it. Returns the size on success;
// -1 on connection error; -2 if the holder does not have the object;
// -3 if the local create failed (duplicate / table full / exceeds
// arena — payload drained so the connection stays usable).
int64_t shm_transfer_pull_store(int fd, const uint8_t* id, void* dst_store) {
  uint8_t req[1 + kIdSize];
  req[0] = kOpPull;
  memcpy(req + 1, id, kIdSize);
  if (!SendAll(fd, req, sizeof(req))) return -1;
  uint8_t status;
  if (!RecvAll(fd, &status, 1)) return -1;
  if (status == kStatusMissing) return -2;
  if (status != kStatusOk) return -1;
  uint8_t size_be[8];
  if (!RecvAll(fd, size_be, sizeof(size_be))) return -1;
  uint64_t size = UnpackU64(size_be);
  void* ptr = shm_obj_create(dst_store, id, size);
  if (ptr == nullptr) {
    uint8_t scratch[1 << 16];
    uint64_t left = size;
    while (left > 0) {
      size_t n = left < sizeof(scratch) ? static_cast<size_t>(left)
                                        : sizeof(scratch);
      if (!RecvAll(fd, scratch, n)) return -1;
      left -= n;
    }
    return -3;
  }
  if (!RecvAll(fd, ptr, size)) {
    shm_obj_release(dst_store, id);  // drop the creator pin, then reclaim
    shm_obj_delete(dst_store, id);
    return -1;
  }
  shm_obj_seal(dst_store, id);
  return static_cast<int64_t>(size);
}

void shm_transfer_close_fd(int fd) { close(fd); }

}  // extern "C"
