// Host shared-memory object store: the plasma-store equivalent
// (reference: src/ray/object_manager/plasma/ — store.cc,
// object_lifecycle_manager.cc, dlmalloc.cc arena on /dev/shm).
//
// Design, TPU-host reality: device arrays live in HBM and move over ICI —
// this store only holds HOST objects (serialized task args/returns, CPU
// tensors, arrow blocks), so the design favors simplicity + zero-copy
// reads over plasma's full feature set:
//   * one POSIX shm segment (shm_open + mmap), fixed capacity
//   * robust process-shared pthread mutex (survives client crash)
//   * open-addressed hash table of fixed max_objects entries
//   * bump allocator with LRU eviction of sealed, unpinned objects
//   * create -> write into mapped memory -> seal; get pins, release unpins
//
// C ABI for ctypes (no pybind11 in the image).

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x52545055;  // "RTPU"
constexpr int kIdSize = 20;

struct Entry {
  uint8_t id[kIdSize];
  uint64_t offset;      // data offset from arena base
  uint64_t size;
  int64_t lru_tick;     // last touch; -1 = free slot
  int32_t pins;         // readers holding the buffer
  uint8_t sealed;       // visible to get() only when sealed
  uint8_t used;         // slot occupied
  uint8_t pad[2];
};

struct Header {
  uint32_t magic;
  uint32_t max_objects;
  uint64_t capacity;        // arena bytes
  uint64_t bump;            // next free offset (monotonic until wrap)
  uint64_t live_bytes;
  int64_t tick;             // LRU clock
  pthread_mutex_t mutex;    // process-shared, robust
  // Entry table follows; arena follows that.
};

struct Store {
  Header* hdr;
  Entry* entries;
  uint8_t* arena;
  uint64_t map_size;
  int fd;
  char name[256];
  bool owner;
};

uint64_t TableBytes(uint32_t max_objects) {
  return sizeof(Header) + uint64_t(max_objects) * sizeof(Entry);
}

uint32_t Hash(const uint8_t* id) {
  // FNV-1a over the 20-byte id
  uint32_t h = 2166136261u;
  for (int i = 0; i < kIdSize; i++) {
    h ^= id[i];
    h *= 16777619u;
  }
  return h;
}

Entry* FindSlot(Store* s, const uint8_t* id, bool for_insert) {
  uint32_t n = s->hdr->max_objects;
  uint32_t idx = Hash(id) % n;
  Entry* first_free = nullptr;
  for (uint32_t probe = 0; probe < n; probe++) {
    Entry* e = &s->entries[(idx + probe) % n];
    if (e->used) {
      if (memcmp(e->id, id, kIdSize) == 0) return e;
    } else {
      if (!for_insert) {
        // keep probing: deleted slots use used=0 but sealed=2 tombstone
        if (e->sealed != 2) return nullptr;
        continue;
      }
      if (first_free == nullptr) first_free = e;
      if (e->sealed != 2) return first_free;  // true end of chain
    }
  }
  return for_insert ? first_free : nullptr;
}

void Lock(Store* s) {
  int rc = pthread_mutex_lock(&s->hdr->mutex);
  if (rc == EOWNERDEAD) pthread_mutex_consistent(&s->hdr->mutex);
}

void Unlock(Store* s) { pthread_mutex_unlock(&s->hdr->mutex); }

// Try to reclaim `needed` contiguous bytes at the end of the arena by
// evicting sealed+unpinned objects (oldest first) and compacting. Returns
// the offset to place the new object at, or UINT64_MAX.
uint64_t ReserveSpace(Store* s, uint64_t needed) {
  Header* h = s->hdr;
  if (needed > h->capacity) return UINT64_MAX;
  if (h->bump + needed <= h->capacity) {
    uint64_t off = h->bump;
    h->bump += needed;
    return off;
  }
  // Evict LRU sealed/unpinned until (live bytes + needed) fits, then compact.
  while (h->live_bytes + needed > h->capacity) {
    Entry* victim = nullptr;
    for (uint32_t i = 0; i < h->max_objects; i++) {
      Entry* e = &s->entries[i];
      if (e->used && e->sealed == 1 && e->pins == 0) {
        if (victim == nullptr || e->lru_tick < victim->lru_tick) victim = e;
      }
    }
    if (victim == nullptr) return UINT64_MAX;  // everything pinned/unsealed
    h->live_bytes -= victim->size;
    victim->used = 0;
    victim->sealed = 2;  // tombstone for probe chains
  }
  // Compact: slide surviving objects down in offset order (stable).
  // Collect used entries sorted by offset (insertion sort; table is small).
  uint32_t n = h->max_objects;
  Entry** order = new Entry*[n];
  uint32_t m = 0;
  for (uint32_t i = 0; i < n; i++)
    if (s->entries[i].used) order[m++] = &s->entries[i];
  for (uint32_t i = 1; i < m; i++) {
    Entry* key = order[i];
    uint32_t j = i;
    while (j > 0 && order[j - 1]->offset > key->offset) {
      order[j] = order[j - 1];
      j--;
    }
    order[j] = key;
  }
  // Slide only movable objects (sealed, unpinned). Pinned/unsealed entries
  // have live raw pointers outstanding and act as barriers; processing in
  // offset order keeps targets clear of every earlier entry, moved or not.
  uint64_t cursor = 0;
  for (uint32_t i = 0; i < m; i++) {
    Entry* e = order[i];
    if (e->pins > 0 || e->sealed != 1) {
      cursor = e->offset + e->size;
      continue;
    }
    if (e->offset != cursor) {
      memmove(s->arena + cursor, s->arena + e->offset, e->size);
      e->offset = cursor;
    }
    cursor += e->size;
  }
  delete[] order;
  h->bump = cursor;
  if (h->bump + needed > h->capacity) return UINT64_MAX;
  uint64_t off = h->bump;
  h->bump += needed;
  return off;
}

}  // namespace

extern "C" {

// Create (owner) or open a store. Returns opaque handle or null.
void* shm_store_create(const char* name, uint64_t capacity, uint32_t max_objects) {
  shm_unlink(name);  // fresh
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = TableBytes(max_objects) + capacity;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Store* s = new Store();
  s->hdr = (Header*)base;
  s->entries = (Entry*)((uint8_t*)base + sizeof(Header));
  s->arena = (uint8_t*)base + TableBytes(max_objects);
  s->map_size = total;
  s->fd = fd;
  s->owner = true;
  strncpy(s->name, name, sizeof(s->name) - 1);

  memset(s->hdr, 0, TableBytes(max_objects));
  s->hdr->magic = kMagic;
  s->hdr->max_objects = max_objects;
  s->hdr->capacity = capacity;
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&s->hdr->mutex, &attr);
  pthread_mutexattr_destroy(&attr);
  return s;
}

void* shm_store_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* hdr = (Header*)base;
  if (hdr->magic != kMagic) {
    munmap(base, (size_t)st.st_size);
    close(fd);
    return nullptr;
  }
  Store* s = new Store();
  s->hdr = hdr;
  s->entries = (Entry*)((uint8_t*)base + sizeof(Header));
  s->arena = (uint8_t*)base + TableBytes(hdr->max_objects);
  s->map_size = (uint64_t)st.st_size;
  s->fd = fd;
  s->owner = false;
  strncpy(s->name, name, sizeof(s->name) - 1);
  return s;
}

// Reserve an object buffer; returns writable pointer or null (exists/full).
void* shm_obj_create(void* handle, const uint8_t* id, uint64_t size) {
  Store* s = (Store*)handle;
  Lock(s);
  Entry* e = FindSlot(s, id, true);
  if (e != nullptr && e->used && memcmp(e->id, id, kIdSize) == 0) {
    Unlock(s);
    return nullptr;  // duplicate
  }
  if (e == nullptr) {
    // Table full: evict the LRU sealed+unpinned entry. ReserveSpace only
    // evicts under BYTE pressure — many small sealed objects can exhaust
    // the slot table long before the arena fills, and without this path
    // the store would refuse all new objects forever.
    Entry* victim = nullptr;
    for (uint32_t i = 0; i < s->hdr->max_objects; i++) {
      Entry* c = &s->entries[i];
      if (c->used && c->sealed == 1 && c->pins == 0) {
        if (victim == nullptr || c->lru_tick < victim->lru_tick) victim = c;
      }
    }
    if (victim == nullptr) {
      Unlock(s);
      return nullptr;  // everything pinned/unsealed
    }
    s->hdr->live_bytes -= victim->size;
    victim->used = 0;
    victim->sealed = 2;  // tombstone for probe chains
    e = FindSlot(s, id, true);
    if (e == nullptr) {
      Unlock(s);
      return nullptr;
    }
  }
  uint64_t off = ReserveSpace(s, size);
  if (off == UINT64_MAX) {
    Unlock(s);
    return nullptr;
  }
  memcpy(e->id, id, kIdSize);
  e->offset = off;
  e->size = size;
  e->pins = 1;  // creator holds it until seal
  e->sealed = 0;
  e->used = 1;
  e->lru_tick = ++s->hdr->tick;
  s->hdr->live_bytes += size;
  void* ptr = s->arena + off;
  Unlock(s);
  return ptr;
}

int shm_obj_seal(void* handle, const uint8_t* id) {
  Store* s = (Store*)handle;
  Lock(s);
  Entry* e = FindSlot(s, id, false);
  if (e == nullptr || !e->used || e->sealed == 1) {
    Unlock(s);
    return -1;
  }
  e->sealed = 1;
  e->pins = 0;
  e->lru_tick = ++s->hdr->tick;
  Unlock(s);
  return 0;
}

// Pinning get: returns pointer or null; *size_out set on success.
void* shm_obj_get(void* handle, const uint8_t* id, uint64_t* size_out) {
  Store* s = (Store*)handle;
  Lock(s);
  Entry* e = FindSlot(s, id, false);
  if (e == nullptr || !e->used || e->sealed != 1) {
    Unlock(s);
    return nullptr;
  }
  e->pins++;
  e->lru_tick = ++s->hdr->tick;
  *size_out = e->size;
  void* ptr = s->arena + e->offset;
  Unlock(s);
  return ptr;
}

int shm_obj_release(void* handle, const uint8_t* id) {
  Store* s = (Store*)handle;
  Lock(s);
  Entry* e = FindSlot(s, id, false);
  if (e == nullptr || !e->used || e->pins <= 0) {
    Unlock(s);
    return -1;
  }
  e->pins--;
  Unlock(s);
  return 0;
}

int shm_obj_delete(void* handle, const uint8_t* id) {
  Store* s = (Store*)handle;
  Lock(s);
  Entry* e = FindSlot(s, id, false);
  if (e == nullptr || !e->used || e->pins > 0) {
    Unlock(s);
    return -1;
  }
  s->hdr->live_bytes -= e->size;
  e->used = 0;
  e->sealed = 2;  // tombstone
  Unlock(s);
  return 0;
}

int shm_obj_contains(void* handle, const uint8_t* id) {
  Store* s = (Store*)handle;
  Lock(s);
  Entry* e = FindSlot(s, id, false);
  int ok = (e != nullptr && e->used && e->sealed == 1) ? 1 : 0;
  Unlock(s);
  return ok;
}

uint64_t shm_store_live_bytes(void* handle) {
  Store* s = (Store*)handle;
  Lock(s);
  uint64_t v = s->hdr->live_bytes;
  Unlock(s);
  return v;
}

uint64_t shm_store_capacity(void* handle) {
  return ((Store*)handle)->hdr->capacity;
}

void shm_store_close(void* handle) {
  Store* s = (Store*)handle;
  munmap((void*)s->hdr, s->map_size);
  close(s->fd);
  if (s->owner) shm_unlink(s->name);
  delete s;
}

}  // extern "C"
