"""Dedicated worker processes for actors: crash isolation with a mailbox RPC.

Reference analogue: every reference actor IS a worker process — the raylet
leases a worker (`src/ray/raylet/worker_pool.cc`), the actor instance lives
in it, and method calls arrive over gRPC (`core_worker/transport/
task_receiver.cc` in-order delivery). Here the same contract for CPU
actors: the instance is constructed in a spawned child; the parent holds an
`_InstanceProxy` whose attribute access returns shipping stubs, so the node
agent's existing mailbox/`_run_actor_task` machinery is oblivious — a
method call pickles (args, kwargs) to the child, executes there, and the
result (or the user exception) pickles back. A dead child surfaces as
`ActorProcessCrash` → the agent's normal actor-death path (restarts,
`RayActorError` to callers).

Device actors are exempt by explicit contract (node_agent._should_isolate):
a child importing jax would race the parent for the TPU client. In-process
execution also remains the fallback whenever the creation payload cannot
cross a process boundary.
"""

from __future__ import annotations

import os
import pickle
import sys
import threading
import uuid
from typing import Any, Dict, Optional, Tuple

import cloudpickle

from .logging import get_logger

logger = get_logger("actor_process")


class ActorProcessCrash(RuntimeError):
    """The actor's dedicated worker process died."""


class ActorNotSerializableError(RuntimeError):
    """Creation payload can't cross the process boundary."""


def _child_main(req_q, resp_q, log_dir: str = "") -> None:
    """Actor worker entry: construct the instance, then serve method calls.

    Runs max_concurrency threads over one request queue so blocking methods
    (queues, batchers) don't wedge the whole actor; per-call tags route
    responses. Imports stay minimal — user code decides what else loads."""
    from ._pdeathsig import set_pdeathsig

    set_pdeathsig()  # die with the runtime, never orphan (chaos tests)
    os.environ["RAY_TPU_IN_POOL_WORKER"] = "1"  # api.py guards private inits
    if log_dir:
        try:
            path = os.path.join(log_dir, f"actor-{os.getpid()}.out")
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            os.dup2(fd, 1)
            os.dup2(fd, 2)
            os.close(fd)
            sys.stdout = os.fdopen(1, "w", buffering=1, closefd=False)
            sys.stderr = os.fdopen(2, "w", buffering=1, closefd=False)
        except OSError:
            pass
    try:
        # flight recorder: mirror this child's recent spans/logs/events to
        # disk so a SIGKILL still leaves a postmortem (util/flight_recorder)
        from ..util import flight_recorder

        flight_recorder.attach(log_dir, "actor")
    except Exception:  # noqa: BLE001 — observability must not block startup
        pass
    try:
        # profiling plane: SIGUSR2 → all-threads stack dump (works even when
        # every serve thread is wedged — faulthandler is C, no GIL needed),
        # SIGUSR1 → toggle the sampling profiler (util/profiler)
        from ..util import profiler

        profiler.install_child_handlers(log_dir)
    except Exception:  # noqa: BLE001 — observability must not block startup
        pass

    kind, payload = req_q.get()
    if kind != "init":
        return
    try:
        cls, args, kwargs, concurrency, renv, head_addr = pickle.loads(payload)
        # the back-channel address travels in the payload, not the spawn
        # env: the forkserver snapshots env at ITS start (see
        # process_pool._worker_main), so inheritance is unreliable
        if head_addr:
            os.environ["RAY_TPU_HEAD_ADDRESS"] = head_addr
        else:
            # clear a stale forkserver-snapshot value (same staleness fix
            # as process_pool._worker_main): no back-channel must mean the
            # clear error, not a connect to a dead/reused port
            os.environ.pop("RAY_TPU_HEAD_ADDRESS", None)
        from .runtime_env import applied

        ctx = applied(renv)
        ctx.__enter__()  # actor-scoped: env stays applied for its lifetime
        instance = cls(*args, **kwargs)
    except BaseException as e:  # noqa: BLE001 — reported, not raised
        try:
            err = cloudpickle.dumps(e)
        except Exception:
            err = cloudpickle.dumps(RuntimeError(repr(e)))
        resp_q.put(("init", False, err))
        return
    resp_q.put(("init", True, b""))

    send_lock = threading.Lock()

    # spans recorded in this child ride back on call replies (there is no
    # heartbeat loop here): one cursor shared by the serve threads
    tele_lock = threading.Lock()
    tele_cursor = [0]

    def serve_loop():
        from ..util import tracing

        while True:
            item = req_q.get()
            if item is None or item[0] == "stop":
                # one sentinel per thread: re-post for siblings then exit
                req_q.put(("stop",))
                return
            _, tag, method, call_payload = item
            try:
                loaded = pickle.loads(call_payload)
                args, kwargs = loaded[0], loaded[1]
                trace_ctx = loaded[2] if len(loaded) > 2 else None
                if trace_ctx is not None:
                    with tracing.start_span(
                            f"actor_exec:{method}", context=trace_ctx):
                        out = getattr(instance, method)(*args, **kwargs)
                else:
                    out = getattr(instance, method)(*args, **kwargs)
                # ship anything newly buffered: the execute span above,
                # but also roots the method opened itself (sampled serve
                # requests). The untraced path stays lock-free.
                spans = []
                if tracing._total != tele_cursor[0]:
                    with tele_lock:
                        tele_cursor[0], spans = tracing.drain_since(
                            tele_cursor[0])
                body = cloudpickle.dumps((True, out, spans))
            except BaseException as e:  # noqa: BLE001 — user methods raise anything
                try:
                    body = cloudpickle.dumps((False, e))
                except Exception:
                    body = cloudpickle.dumps((False, RuntimeError(repr(e))))
            with send_lock:
                resp_q.put(("done", tag, body))

    threads = [
        threading.Thread(target=serve_loop, daemon=True, name=f"serve-{i}")
        for i in range(max(1, concurrency))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


class ActorProcess:
    """Parent-side handle on one actor's dedicated worker process."""

    def __init__(self, cls, args, kwargs, *, max_concurrency: int = 1,
                 runtime_env: Optional[dict] = None):
        # creation payload must cross the boundary NOW (fail fast into the
        # in-process fallback, before a process is spawned); the pool's
        # pickler rejects inline-only types (ObjectRef/ActorHandle) whose
        # methods could not work from inside a worker process
        from .process_pool import _cloudpickle_dumps

        try:
            payload = _cloudpickle_dumps(
                (cls, tuple(args), dict(kwargs or {}), max(1, max_concurrency),
                 runtime_env, os.environ.get("RAY_TPU_HEAD_ADDRESS", ""))
            )
        except Exception as e:
            raise ActorNotSerializableError(repr(e)) from e

        from .logging import log_dir
        from .process_pool import _mp_context, _suppress_main_reimport

        # all teardown-visible state exists BEFORE anything can fail, so
        # terminate() on the init-error path below never masks the actor's
        # real __init__ exception with an AttributeError
        self._lock = threading.Lock()
        self._waiters: Dict[str, Tuple[threading.Event, list]] = {}
        self._dead = threading.Event()
        self._reader: Optional[threading.Thread] = None

        ctx = _mp_context()
        self._req_q = ctx.Queue()
        self._resp_q = ctx.Queue()
        self._proc = ctx.Process(
            target=_child_main,
            args=(self._req_q, self._resp_q, log_dir()),
            daemon=True,
        )
        with _suppress_main_reimport():
            self._proc.start()
        self._req_q.put(("init", payload))
        kind, ok, body = self._get_resp(timeout=300.0, init=True)
        if not ok:
            err = cloudpickle.loads(body)
            self.terminate()
            raise err
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"actor-proc-{self._proc.pid}",
        )
        self._reader.start()

    # -- plumbing -----------------------------------------------------------

    def _get_resp(self, timeout: float, init: bool = False):
        """Blocking read used only during init (before the reader starts)."""
        import queue as _q

        deadline = timeout
        while True:
            try:
                return self._resp_q.get(timeout=min(0.1, deadline))
            except _q.Empty:
                deadline -= 0.1
                if not self._proc.is_alive():
                    self._note_crash("actor process died during init")
                    raise ActorProcessCrash(
                        f"actor process died during init "
                        f"(exitcode {self._proc.exitcode})"
                    )
                if deadline <= 0:
                    raise ActorProcessCrash("actor init timed out")

    def _read_loop(self) -> None:
        import queue as _q

        while not self._dead.is_set():
            try:
                item = self._resp_q.get(timeout=0.1)
            except _q.Empty:
                if not self._proc.is_alive():
                    # _dead set means terminate() beat us here: planned
                    # teardown, not a crash — no postmortem
                    if not self._dead.is_set():
                        self._note_crash("actor process died")
                    self._fail_all_waiters()
                    return
                continue
            if item[0] != "done":
                continue
            _, tag, body = item
            with self._lock:
                waiter = self._waiters.pop(tag, None)
            if waiter is not None:
                event, box = waiter
                box.append(body)
                event.set()

    def _note_crash(self, cause: str) -> None:
        """Reap an UNEXPECTED child death into a postmortem artifact (the
        child's flight mirror + stdout tail; see util/flight_recorder).
        terminate() never calls this — normal teardown is not a crash.
        write_postmortem dedups by pid, so racing detection sites are safe."""
        try:
            from ..util import flight_recorder

            flight_recorder.write_postmortem(
                self._proc.pid, cause, exitcode=self._proc.exitcode,
                stdout_hint="actor")
        except Exception:  # noqa: BLE001 — reaping must not mask the crash
            pass

    def _fail_all_waiters(self) -> None:
        self._dead.set()
        with self._lock:
            waiters = list(self._waiters.values())
            self._waiters.clear()
        for event, box in waiters:
            box.append(None)  # None body => crashed
            event.set()

    # -- api ----------------------------------------------------------------

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid

    def alive(self) -> bool:
        return not self._dead.is_set() and self._proc.is_alive()

    def call(self, method: str, args: tuple, kwargs: dict,
             timeout: Optional[float] = None) -> Any:
        if self._dead.is_set():
            raise ActorProcessCrash("actor process is dead")
        from ..util import tracing
        from .process_pool import _cloudpickle_dumps

        try:
            # the caller's span context (the agent-side execute span) rides
            # along so the child's actor_exec span joins the same trace
            payload = _cloudpickle_dumps(
                (tuple(args), dict(kwargs or {}), tracing.current_context()))
        except Exception as e:
            raise ActorNotSerializableError(
                f"args of {method}() can't cross to the actor process: {e!r}"
            ) from e
        tag = uuid.uuid4().hex
        event = threading.Event()
        box: list = []
        with self._lock:
            self._waiters[tag] = (event, box)
        # _fail_all_waiters may have snapshotted BEFORE our registration
        # (child died concurrently): re-check so this call fails instead of
        # waiting on an event no reader thread will ever set
        if self._dead.is_set():
            with self._lock:
                self._waiters.pop(tag, None)
            raise ActorProcessCrash("actor process is dead")
        self._req_q.put(("call", tag, method, payload))
        if not event.wait(timeout=timeout):
            with self._lock:
                self._waiters.pop(tag, None)
            raise TimeoutError(f"actor call {method}() timed out")
        body = box[0]
        if body is None:
            self._note_crash(f"actor process died executing {method}()")
            raise ActorProcessCrash(
                f"actor process died executing {method}() "
                f"(exitcode {self._proc.exitcode})"
            )
        loaded = cloudpickle.loads(body)
        ok, value = loaded[0], loaded[1]
        if len(loaded) > 2 and loaded[2]:
            from ..util import tracing

            # child-process spans land in this (agent) process's buffer,
            # keeping their origin pid; worker-host federation then ships
            # them on to the head like any local span
            tracing.ingest(loaded[2])
        if not ok:
            raise value
        return value

    def terminate(self) -> None:
        self._dead.set()
        try:
            self._proc.terminate()
            self._proc.join(timeout=5.0)
            if self._proc.is_alive():
                self._proc.kill()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass
        self._fail_all_waiters()


class _InstanceProxy:
    """Drop-in for `_ActorRunner.instance`: attribute access returns stubs
    that ship the call to the actor's worker process. The node agent's
    `getattr(instance, method)(*args)` path works unchanged."""

    def __init__(self, proc: ActorProcess, class_name: str):
        object.__setattr__(self, "_proc", proc)
        object.__setattr__(self, "_class_name", class_name)

    def __getattr__(self, name: str):
        proc: ActorProcess = object.__getattribute__(self, "_proc")

        def stub(*args, **kwargs):
            return proc.call(name, args, kwargs)

        stub.__name__ = name
        return stub

    def __repr__(self):
        cls = object.__getattribute__(self, "_class_name")
        proc: ActorProcess = object.__getattribute__(self, "_proc")
        return f"<{cls} in worker process {proc.pid}>"
