"""Parent-death watchdog for helper processes.

Chaos tests and crashed drivers SIGKILL the runtime process; its
multiprocessing forkserver + resource-tracker daemons reparent to init
and live forever (VERDICT r3 weak #7 found hours-old orphans).

Why NOT prctl(PR_SET_PDEATHSIG): that signal fires when the creating
THREAD exits, not the process — the forkserver is often booted from a
short-lived warmup thread, so the arm would kill it moments later (and a
forkserver lazily booted from a worker thread would cascade-kill every
live worker when that thread ends). A ppid watchdog has process-level
semantics: when the parent PROCESS dies, the child reparents (ppid
flips, typically to 1/subreaper) and the watchdog exits this process.

Used as a multiprocessing forkserver PRELOAD (import side effect arms
the watchdog inside the forkserver — the only hook multiprocessing
offers into that process) and called explicitly from pool-worker and
actor-process entry points. The cascade: runtime dies -> forkserver's
watchdog exits it -> each worker's parent (the forkserver) is gone ->
their watchdogs exit them -> the resource tracker's pipe closes -> it
exits on its own.
"""

from __future__ import annotations

import os
import threading


def set_pdeathsig(_sig: int = 15, poll_s: float = 1.0) -> bool:
    """Arm a die-with-parent watchdog for THIS process (name kept for the
    call sites; implemented as a ppid poll, see module docstring)."""
    parent = os.getppid()
    if parent <= 1:
        return False  # already orphaned or direct init child: nothing to watch

    def watch() -> None:
        while True:
            if os.getppid() != parent:
                os._exit(1)  # parent died: no cleanup, just stop existing
            threading.Event().wait(poll_s)

    t = threading.Thread(target=watch, daemon=True, name="parent-watchdog")
    t.start()
    return True


# forkserver preload hook: importing this module inside the forkserver
# (multiprocessing.set_forkserver_preload) arms the watchdog there
set_pdeathsig()
