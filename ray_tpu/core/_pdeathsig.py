"""Parent-death signal for helper processes (Linux prctl).

Chaos tests and crashed drivers SIGKILL the runtime process; its
multiprocessing forkserver + resource-tracker daemons reparent to init
and live forever (VERDICT r3 weak #7 found hours-old orphans). Arming
PR_SET_PDEATHSIG in each helper makes the kernel deliver SIGTERM the
moment the parent dies — no cleanup code needs to run in the killed
process.

This module is also used as a multiprocessing forkserver PRELOAD: import
side effect arms the signal inside the forkserver itself (the only hook
multiprocessing offers into that process).
"""

from __future__ import annotations

import signal
import sys


def set_pdeathsig(sig: int = signal.SIGTERM) -> bool:
    """Arm parent-death signal for THIS process. Linux-only; returns
    False (no-op) elsewhere."""
    if not sys.platform.startswith("linux"):
        return False
    try:
        import ctypes

        PR_SET_PDEATHSIG = 1
        libc = ctypes.CDLL(None, use_errno=True)
        return libc.prctl(PR_SET_PDEATHSIG, sig, 0, 0, 0) == 0
    except Exception:  # noqa: BLE001 — hardening is best-effort
        return False


# forkserver preload hook: importing this module inside the forkserver
# (multiprocessing.set_forkserver_preload) arms the signal there
set_pdeathsig()
