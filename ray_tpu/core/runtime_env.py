"""Runtime environments: per-task dependency/environment isolation.

Reference analogue: `python/ray/_private/runtime_env/` (env_vars,
working_dir, py_modules, pip plugins applied when the raylet starts a
worker for the task). TPU-native scope and its honest limits:

- **CPU pool tasks**: full support. The runtime_env ships with the task
  payload; the worker process applies env_vars / working_dir (chdir +
  sys.path) / py_modules / pip (cached per-hash install dir prepended to
  sys.path) around the call and restores afterwards — workers execute
  tasks serially, so scoped mutation is race-free.
- **Jobs** (`job_submission`): env_vars + working_dir on the entrypoint
  subprocess (already supported there; this module is the shared schema).
- **Device tasks and actors**: REJECTED with a clear error. They execute
  in the device-owning process by design (node_agent docstring); mutating
  that process's env/cwd would leak across every concurrent task. The
  reference can isolate these because every actor gets its own worker
  process — that is the documented gap, not silently dropped config.
- **Streaming tasks**: applied in-process (a generator cannot cross the
  pool boundary incrementally) under a process-wide mutual-exclusion lock
  (`_apply_lock`) held for the stream's whole lifetime, so concurrent
  appliers can never corrupt each other's save/restore. Two consequences:
  unrelated tasks in the same process can observe the env for the
  stream's duration (visibility, not corruption, is the accepted
  in-process limit), and one renv stream must not block on another renv
  stream's output on the same node — the second stream waits for the
  lock, so such a dependency would deadlock until the consumer's timeout.
  Keep renv streams independent (or give only one of them a runtime_env).

Cross-host code shipping (reference: `runtime_env/working_dir.py` GCS
package upload): at submission the driver zips `working_dir` into the
control-plane KV (`package_working_dir`); an executing node — possibly a
JOINED host that has never seen the driver's filesystem — resolves the
`kv://<sha>` uri back into a local cached extraction (`resolve`).

pip (reference: `runtime_env/pip.py` virtualenv-per-hash): requirements
install once into a content-hashed target dir (file-locked, shared across
workers on the host) that is prepended to sys.path for the task. Local
wheel paths work offline; index-backed requirements need egress.

Schema: {"env_vars": {str: str}, "working_dir": str,
"working_dir_uri": "kv://<sha>", "py_modules": [str], "pip": [str]}.
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import os
import sys
import threading
import zipfile
from typing import Any, Dict, Optional

_KNOWN_KEYS = {"env_vars", "working_dir", "working_dir_uri", "py_modules", "pip"}

_PKG_KV_PREFIX = "runtime_env/pkg/"
_MAX_PKG_BYTES = 200 << 20  # refuse to stuff >200MB into the control plane


def _cache_root() -> str:
    root = os.environ.get("RAY_TPU_ENV_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "ray_tpu")
    os.makedirs(root, exist_ok=True)
    return root


class RuntimeEnvError(RuntimeError):
    pass


def validate(renv: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    if not renv:
        return None
    unknown = set(renv) - _KNOWN_KEYS
    if unknown:
        raise RuntimeEnvError(
            f"unknown runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(_KNOWN_KEYS)}"
        )
    wd = renv.get("working_dir")
    if wd and not renv.get("working_dir_uri") and not os.path.isdir(wd):
        raise RuntimeEnvError(f"runtime_env working_dir does not exist: {wd}")
    for p in renv.get("py_modules") or []:
        if not os.path.exists(p):
            raise RuntimeEnvError(f"runtime_env py_module path missing: {p}")
    pip = renv.get("pip")
    if pip is not None and (
        not isinstance(pip, (list, tuple))
        or not all(isinstance(r, str) for r in pip)
    ):
        raise RuntimeEnvError("runtime_env 'pip' must be a list of requirement "
                              f"strings, got {pip!r}")
    return renv


# ---------------------------------------------------------------------------
# working_dir shipping through the control-plane KV
# ---------------------------------------------------------------------------


def package_working_dir(renv: Optional[Dict[str, Any]], control_plane):
    """Driver side: zip working_dir into the KV, return a renv whose
    working_dir travels as a content-addressed kv:// uri (idempotent:
    same content -> same key, overwrite=False)."""
    if not renv or not renv.get("working_dir") or renv.get("working_dir_uri"):
        return renv
    wd = renv["working_dir"]
    if not os.path.isdir(wd):
        raise RuntimeEnvError(f"runtime_env working_dir does not exist: {wd}")
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in os.walk(wd):
            for name in files:
                full = os.path.join(root, name)
                zf.write(full, os.path.relpath(full, wd))
    blob = buf.getvalue()
    if len(blob) > _MAX_PKG_BYTES:
        raise RuntimeEnvError(
            f"working_dir {wd} zips to {len(blob)} bytes (> "
            f"{_MAX_PKG_BYTES}); ship big inputs through the Data layer")
    sha = hashlib.sha256(blob).hexdigest()[:32]
    control_plane.kv_put(_PKG_KV_PREFIX + sha, blob, overwrite=False)
    out = dict(renv)
    out.pop("working_dir")
    out["working_dir_uri"] = f"kv://{sha}"
    return out


def resolve(renv: Optional[Dict[str, Any]], control_plane):
    """Executing-node side: materialize kv:// working_dir uris into a
    local cached extraction, so the renv handed to the worker contains
    only local paths. Safe to call with no uri (returns renv as-is)."""
    if not renv or not renv.get("working_dir_uri"):
        return renv
    uri = renv["working_dir_uri"]
    sha = uri.split("://", 1)[1]
    dest = os.path.join(_cache_root(), "pkgs", sha)
    if not os.path.isdir(dest):
        blob = control_plane.kv_get(_PKG_KV_PREFIX + sha)
        if blob is None:
            raise RuntimeEnvError(f"working_dir package {uri} not in KV")
        import shutil
        import tempfile

        # unique tmp per extractor: two processes racing on the same sha
        # must never interleave writes into one directory
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        tmp = tempfile.mkdtemp(prefix=sha + ".", dir=os.path.dirname(dest))
        with zipfile.ZipFile(io.BytesIO(blob)) as zf:
            zf.extractall(tmp)
        try:
            os.rename(tmp, dest)  # atomic publish; losers of the race clean up
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
    out = dict(renv)
    out.pop("working_dir_uri")
    out["working_dir"] = dest
    return out


# ---------------------------------------------------------------------------
# pip environments (per-hash cached install dirs)
# ---------------------------------------------------------------------------


def _pip_env_dir(reqs) -> str:
    canon = "\n".join(sorted(str(r) for r in reqs))
    sha = hashlib.sha256(canon.encode()).hexdigest()[:32]
    return os.path.join(_cache_root(), "pip_envs", sha)


def ensure_pip_env(reqs) -> str:
    """Install requirements into a content-hashed target dir ONCE per
    host (file-locked against concurrent workers); returns the dir to
    prepend to sys.path. The reference builds a full virtualenv; a
    --target dir layered over the interpreter's site gives the same
    per-task dependency view without re-execing the worker."""
    import fcntl
    import subprocess

    target = _pip_env_dir(reqs)
    done = os.path.join(target, ".ray_tpu_done")
    if os.path.exists(done):
        return target
    os.makedirs(target, exist_ok=True)
    lock_path = target + ".lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if os.path.exists(done):
            return target
        cmd = [sys.executable, "-m", "pip", "install", "--target", target,
               "--no-input", "--disable-pip-version-check", *reqs]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeEnvError(
                f"pip install failed for {list(reqs)}:\n{proc.stderr[-2000:]}")
        with open(done, "w") as f:
            f.write("ok")
    return target


# Serializes concurrent appliers in ONE process (streaming tasks in the
# node agent): interleaved save/restore of env/cwd/sys.path would corrupt
# both envs and leak the loser's values permanently. Pool workers run
# serially, so there the lock is uncontended. The residual limit — other
# non-renv tasks in the same process can OBSERVE the env while a stream
# holds it — is the documented in-process tradeoff (module docstring).
_apply_lock = threading.RLock()


@contextlib.contextmanager
def applied(renv: Optional[Dict[str, Any]]):
    """Apply a runtime_env for the duration of one task, then restore.
    Appliers are mutually exclusive per process (see _apply_lock); full
    isolation needs a worker process."""
    if not renv:
        yield
        return
    with _apply_lock:
        with _applied_locked(renv):
            yield


@contextlib.contextmanager
def _applied_locked(renv: Dict[str, Any]):
    # failure-prone setup FIRST, before any process mutation: a pip
    # install that raises must not leak env_vars into the serially-reused
    # worker (nothing below the mutations may raise outside the finally)
    pip_dir = ensure_pip_env(renv["pip"]) if renv.get("pip") else None
    saved_env: Dict[str, Optional[str]] = {}
    for k, v in (renv.get("env_vars") or {}).items():
        saved_env[k] = os.environ.get(k)
        os.environ[k] = str(v)
    added_paths = []
    modules_before = set(sys.modules)
    if pip_dir is not None:
        sys.path.insert(0, pip_dir)
        added_paths.append(pip_dir)
    saved_cwd = None
    wd = renv.get("working_dir")
    if wd:
        saved_cwd = os.getcwd()
        os.chdir(wd)
        sys.path.insert(0, wd)
        added_paths.append(wd)
    for p in renv.get("py_modules") or []:
        sys.path.insert(0, p)
        added_paths.append(p)
    try:
        yield
    finally:
        # purge modules imported FROM the env's paths: a cached
        # sys.modules entry would leak the package (or a stale pinned
        # version) into the next task on this serially-reused worker
        for name in set(sys.modules) - modules_before:
            mod_file = getattr(sys.modules.get(name), "__file__", None) or ""
            if any(mod_file.startswith(p + os.sep) for p in added_paths):
                sys.modules.pop(name, None)
        for p in added_paths:
            try:
                sys.path.remove(p)
            except ValueError:
                pass
        if saved_cwd is not None:
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
        for k, old in saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
