"""Runtime environments: per-task dependency/environment isolation.

Reference analogue: `python/ray/_private/runtime_env/` (env_vars,
working_dir, py_modules plugins applied when the raylet starts a worker
for the task). TPU-native scope and its honest limits:

- **CPU pool tasks**: full support. The runtime_env ships with the task
  payload; the worker process applies env_vars / working_dir (chdir +
  sys.path) / py_modules around the call and restores afterwards —
  workers execute tasks serially, so scoped mutation is race-free.
- **Jobs** (`job_submission`): env_vars + working_dir on the entrypoint
  subprocess (already supported there; this module is the shared schema).
- **Device tasks and actors**: REJECTED with a clear error. They execute
  in the device-owning process by design (node_agent docstring); mutating
  that process's env/cwd would leak across every concurrent task. The
  reference can isolate these because every actor gets its own worker
  process — that is the documented gap, not silently dropped config.

Schema: {"env_vars": {str: str}, "working_dir": str, "py_modules": [str]}.
"""

from __future__ import annotations

import contextlib
import os
import sys
from typing import Any, Dict, Optional

_KNOWN_KEYS = {"env_vars", "working_dir", "py_modules"}


class RuntimeEnvError(RuntimeError):
    pass


def validate(renv: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    if not renv:
        return None
    unknown = set(renv) - _KNOWN_KEYS
    if unknown:
        raise RuntimeEnvError(
            f"unknown runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(_KNOWN_KEYS)}"
        )
    wd = renv.get("working_dir")
    if wd and not os.path.isdir(wd):
        raise RuntimeEnvError(f"runtime_env working_dir does not exist: {wd}")
    for p in renv.get("py_modules") or []:
        if not os.path.exists(p):
            raise RuntimeEnvError(f"runtime_env py_module path missing: {p}")
    return renv


@contextlib.contextmanager
def applied(renv: Optional[Dict[str, Any]]):
    """Apply a runtime_env for the duration of one task, then restore.
    Only safe where the process runs tasks serially (pool workers)."""
    if not renv:
        yield
        return
    saved_env: Dict[str, Optional[str]] = {}
    for k, v in (renv.get("env_vars") or {}).items():
        saved_env[k] = os.environ.get(k)
        os.environ[k] = str(v)
    added_paths = []
    saved_cwd = None
    wd = renv.get("working_dir")
    if wd:
        saved_cwd = os.getcwd()
        os.chdir(wd)
        sys.path.insert(0, wd)
        added_paths.append(wd)
    for p in renv.get("py_modules") or []:
        sys.path.insert(0, p)
        added_paths.append(p)
    try:
        yield
    finally:
        for p in added_paths:
            try:
                sys.path.remove(p)
            except ValueError:
                pass
        if saved_cwd is not None:
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
        for k, old in saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
