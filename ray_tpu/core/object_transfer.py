"""Object transfer plane: chunked pull of sealed objects between runtimes.

Reference analogue: `src/ray/object_manager/` — `PullManager`/`PushManager`
move plasma objects between nodes as ~1MB chunks over a dedicated gRPC
service (`object_manager.proto :: ObjectManagerService`). Same shape here:
each runtime can serve its object store on a TCP port; a remote runtime
locates the holder (control-plane KV carries `object_transfer/{node}` →
address) and pulls the object as fixed-size chunks, reassembling and
sealing it into its own store. Pull-based (the receiver drives), like the
reference — admission control stays with the consumer.

Intra-slice device arrays never cross this plane: jax arrays travel as
compiled collectives over ICI. This is the HOST object plane (CPU tensors,
rollouts, checkpoint shards, pickled results) between loosely-coupled
runtimes.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import pickle
import socket
import socketserver
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import object_ledger
from .config import config
from .ids import ObjectID
from .logging import get_logger
from .metrics import MICRO_BUCKETS, Counter, Gauge, Histogram
from .object_store import SealedBytes
from .wire import MSG_REQUEST, MSG_RESPONSE, WireError, recv_msg, send_msg

logger = get_logger("object_transfer")

DEFAULT_CHUNK_BYTES = 1 << 20  # ~1MB, the reference's chunk size

KV_PREFIX = "object_transfer/"  # control-plane KV key prefix for addresses
# holder-side outstanding-pull load, gossiped so pullers can rank holders
LOAD_PREFIX = "object_transfer_load/"

# Native fast path (_shm/transfer.cc): the holder stages the serialized
# blob in a shm arena once, a C++ thread streams it zero-copy, and the
# puller lands it straight in its own arena — Python never allocates or
# copies on the data path. Sized by this env knob; objects larger than
# the staging arena ride the chunked Python path below.
STAGING_BYTES = int(os.environ.get("RAY_TPU_TRANSFER_STAGING_BYTES",
                                   str(256 << 20)))


_staging_seq = itertools.count()  # unique arena names (id() can be reused)


def _staging_name(tag: str) -> str:
    return f"/rtpu_{tag}_{os.getpid()}_{next(_staging_seq)}"


def _stage_id(oid: bytes, raw: bool) -> bytes:
    """Staging-arena id for (object, raw-flag): sha1 maps the 28-byte
    ObjectID onto the store's 20-byte ids, deterministically on both ends
    of the pull. raw=True serves the SEALED payload — a different blob
    for the same object — so it hashes to a distinct staging id."""
    return hashlib.sha1(oid + (b"r" if raw else b"")).digest()

_pulled_chunks = Counter(
    "object_transfer_chunks_pulled", "Chunks pulled from remote runtimes."
)
_pulled_bytes = Counter(
    "object_transfer_bytes_pulled", "Bytes pulled from remote runtimes."
)
_pull_seconds = Histogram(
    "object_pull_seconds",
    "Wall seconds per completed remote pull, tagged by data path.",
    buckets=MICRO_BUCKETS,
)
_pull_bytes = Counter(
    "object_pull_bytes", "Bytes that crossed the network on remote pulls."
)
_pull_inflight = Gauge(
    "object_pull_inflight", "Remote pulls currently in flight on this side."
)
# pull-through cache outcomes (incremented by the get paths in
# core_worker/worker_api; defined here because the cache IS the object
# plane's replica mechanism)
_cache_hits = Counter(
    "object_cache_hits",
    "Gets served from the local store for objects a prior get pulled "
    "through from a remote holder.",
)
_cache_misses = Counter(
    "object_cache_misses",
    "Gets that had to pull the object from a remote holder.",
)


class ObjectPullError(RuntimeError):
    pass


class ObjectPullConnectionError(ObjectPullError):
    """Transport-class pull failure (connection lost / garbled response):
    the CONNECTION is suspect, not the holder's answer. Retrying the same
    holder on a fresh socket makes sense; an application-level refusal
    (plain ObjectPullError — e.g. the object is not there) does not."""


_NATIVE_MISS = object()  # sentinel: native path unavailable, use chunks


def _make_client_native():
    from .shm_store import NativeTransferClient, ShmObjectStore

    staging = ShmObjectStore(
        _staging_name("xc"), capacity=STAGING_BYTES, max_objects=1024,
    )
    try:
        native = NativeTransferClient()
    except Exception:
        staging.close()
        raise
    return staging, native, lambda n: n.close()


def _serialize_for_wire(value: Any) -> bytes:
    """One flat payload per object; cloudpickle for closures/lambdas."""
    try:
        return pickle.dumps(value, protocol=5)
    except Exception:
        import cloudpickle

        return cloudpickle.dumps(value, protocol=5)


class _TransferHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: "ObjectTransferServer" = self.server  # type: ignore[assignment]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                msg_type, req = recv_msg(sock)
                if msg_type != MSG_REQUEST:
                    raise WireError(f"unexpected message type {msg_type}")
                server._load_add(1)
                try:
                    resp = self._dispatch(server, req)
                except Exception as e:  # noqa: BLE001 — serialized to caller
                    resp = {"id": req.get("id"), "ok": False, "error": repr(e)}
                finally:
                    server._load_add(-1)
                send_msg(sock, MSG_RESPONSE, resp)
        except (WireError, OSError):
            pass  # puller disconnected

    def _dispatch(self, server: "ObjectTransferServer", req: dict) -> dict:
        method = req.get("method")
        # args may carry a trailing raw flag: raw=True ships the SEALED
        # payload (SealedBytes pickled as-is) so sealing survives the hop
        # (store.get_raw parity for cross-runtime pulls)
        if method == "meta":
            oid_hex, *rest = req["args"]
            blob = server._blob_for(oid_hex, raw=bool(rest and rest[0]))
            return {"id": req["id"], "ok": True, "value": len(blob)}
        if method == "stage":
            oid_hex, raw = req["args"]
            size, native_port = server._stage(oid_hex, bool(raw))
            return {"id": req["id"], "ok": True,
                    "value": {"size": size, "native_port": native_port}}
        if method == "chunk":
            oid_hex, offset, length, *rest = req["args"]
            blob = server._blob_for(oid_hex, raw=bool(rest and rest[0]))
            return {"id": req["id"], "ok": True,
                    "value": bytes(blob[offset:offset + length])}
        if method == "contains":
            (oid_hex,) = req["args"]
            oid = ObjectID.from_hex(oid_hex)
            return {"id": req["id"], "ok": True,
                    "value": bool(server._store.contains(oid))}
        if method == "load":
            # holders serve their own outstanding-pull count so pullers
            # can rank them directly (the KV gossip is the cached form)
            return {"id": req["id"], "ok": True, "value": server.outstanding}
        raise WireError(f"unknown method {method!r}")


class _NativePlane:
    """Owns one side's native-path pair (staging arena + C++ endpoint)
    with the init/commit/teardown choreography the server and client
    share. `make()` runs on a background thread (a cold environment may
    have to COMPILE the shm library — no request or pull ever waits on
    that); `acquire()/release()` hold a use count so `teardown()` never
    munmaps the arena under an in-flight, GIL-released native call."""

    def __init__(self, name: str, make):
        self._name = name
        self._make = make  # () -> (staging, native, stop_native)
        self.staging = None
        self.native = None
        self._stop_native = None
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._started = False
        self._users = 0

    def start_async(self) -> None:
        with self._lock:
            if self._started or self._closed:
                return
            self._started = True
        threading.Thread(target=self._init, daemon=True,
                         name=self._name).start()

    def _init(self) -> None:
        try:
            staging, native, stop_native = self._make()
        except Exception:  # noqa: BLE001 — the chunked path remains
            logger.warning("%s unavailable", self._name, exc_info=True)
            return
        with self._lock:
            if not self._closed:
                self.staging = staging
                self.native = native
                self._stop_native = stop_native
                return
        stop_native(native)  # teardown() won the race
        staging.close()

    def acquire(self):
        """-> (native, staging) with a use hold, or (None, None). A
        non-None acquire MUST be paired with release()."""
        with self._lock:
            if self._closed or self.native is None:
                return None, None
            self._users += 1
            return self.native, self.staging

    def release(self) -> None:
        with self._lock:
            self._users -= 1
            if self._users == 0:
                self._cond.notify_all()

    def teardown(self, wait_s: float = 5.0) -> None:
        with self._lock:
            self._closed = True
            native, staging = self.native, self.staging
            stop_native = self._stop_native
            self.native = self.staging = self._stop_native = None
            deadline = time.monotonic() + wait_s
            while self._users > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    # leaking the MAPPING beats munmapping it under a live
                    # native call (use-after-unmap in the C recv/send) —
                    # but the /dev/shm NAME must still go, or the segment
                    # outlives the process and fills /dev/shm on restarts
                    logger.warning("%s busy at teardown; leaking arena "
                                   "mapping (name unlinked)", self._name)
                    if staging is not None:
                        staging.unlink_name()
                    native = staging = None
                    break
                self._cond.wait(left)
        if native is not None:
            stop_native(native)
        if staging is not None:
            staging.close()


class ObjectTransferServer(socketserver.ThreadingTCPServer):
    """Serves one runtime's object store for remote pulls.

    The serialized blob for an object is cached per object id while any
    pull is in flight (pulls are chunked across many requests), and
    dropped once the store drops the object."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, store, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _TransferHandler)
        self._store = store
        self._blob_cache: Dict[Tuple[str, bool], bytes] = {}
        self._cache_lock = threading.Lock()
        # outstanding-pull load: requests currently being served. Gossiped
        # to the control-plane KV (start_load_gossip) so pullers rank
        # lightly-loaded holders first.
        self._load = 0
        self._load_lock = threading.Lock()
        self._gossip_stop = threading.Event()
        self._plane = _NativePlane("native-transfer-server",
                                   self._make_native)
        self._plane.start_async()
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="object-transfer"
        )
        self._thread.start()
        logger.info("object transfer plane on %s:%d", *self.server_address)

    def _load_add(self, delta: int) -> None:
        with self._load_lock:
            self._load += delta

    @property
    def outstanding(self) -> int:
        with self._load_lock:
            return self._load

    def start_load_gossip(self, control_plane, node_hex: str,
                          period_s: float = 0.25) -> None:
        """Publish this holder's outstanding-pull count to the control
        plane KV (`object_transfer_load/{node}`) on change; pull_from_any
        ranks holders by it. Best-effort: a stale or missing value only
        degrades ranking, never correctness."""

        def loop() -> None:
            last: Optional[int] = None
            while not self._gossip_stop.wait(period_s):
                load = self.outstanding
                if load == last:
                    continue
                try:
                    control_plane.kv_put(LOAD_PREFIX + node_hex, str(load))
                    last = load
                except Exception:  # noqa: BLE001 — control plane gone
                    return

        threading.Thread(target=loop, daemon=True,
                         name="transfer-load-gossip").start()

    def _make_native(self):
        from .shm_store import NativeTransferServer, ShmObjectStore

        staging = ShmObjectStore(
            _staging_name("xs"), capacity=STAGING_BYTES, max_objects=1024,
        )
        try:
            native = NativeTransferServer(staging,
                                          host=self.server_address[0])
        except Exception:
            staging.close()
            raise
        logger.info("native transfer plane on port %d", native.port)
        return staging, native, lambda n: n.stop()

    def _stage(self, oid_hex: str, raw: bool) -> Tuple[int, Optional[int]]:
        """Ensure the blob for (oid, raw) sits in the staging arena; ->
        (size, native_port). native_port None = use the chunked path."""
        try:
            sid = _stage_id(ObjectID.from_hex(oid_hex).binary(), raw)
        except (ValueError, TypeError):
            sid = None  # non-ObjectID key: chunked path only
        native, staging = self._plane.acquire() if sid is not None \
            else (None, None)
        if native is None:
            return len(self._blob_for(oid_hex, raw=raw)), None
        try:
            view = staging.get_view(sid)
            if view is not None:  # already staged: size from the arena,
                try:              # no re-pickle of the value
                    return len(view), native.port
                finally:
                    staging.release(sid)
            blob = self._blob_for(oid_hex, raw=raw)
            if len(blob) > (STAGING_BYTES * 3) // 4:
                return len(blob), None
            try:
                staging.put(sid, blob)
            except Exception:  # noqa: BLE001 — races/arena pressure
                if not staging.contains(sid):
                    return len(blob), None  # cannot stage: chunked fallback
            # the arena copy now serves all pulls; dropping the byte-cache
            # entry halves holder-side residency for large objects
            with self._cache_lock:
                self._blob_cache.pop((oid_hex, raw), None)
            return len(blob), native.port
        finally:
            self._plane.release()

    @property
    def address(self) -> str:
        host, port = self.server_address
        return f"{host}:{port}"

    def _blob_for(self, oid_hex: str, raw: bool = False) -> bytes:
        key = (oid_hex, raw)
        with self._cache_lock:
            blob = self._blob_cache.get(key)
            if blob is not None:
                return blob
        oid = ObjectID.from_hex(oid_hex)
        if not self._store.contains(oid):
            raise KeyError(f"object {oid_hex} not in local store")
        if raw:
            value = self._store.get_raw(oid, timeout=0.0)
        else:
            value = self._store.get(oid, timeout=0.0)
        blob = _serialize_for_wire(value)
        with self._cache_lock:
            # bound the cache: drop the oldest entries past 64
            if len(self._blob_cache) >= 64:
                self._blob_cache.pop(next(iter(self._blob_cache)))
            self._blob_cache[key] = blob
        return blob

    def stop(self) -> None:
        self._gossip_stop.set()
        self.shutdown()
        self.server_close()
        self._plane.teardown()


class _PoolSlot:
    """One pooled connection. The socket stays tracked here from dial to
    close, so _ConnPool.close() can reach every fd it ever created —
    including ones checked out by in-flight pulls."""

    __slots__ = ("sock", "busy", "dead")

    def __init__(self):
        self.sock: Optional[socket.socket] = None
        self.busy = True  # born checked-out by the dialing thread
        self.dead = False


def _close_sock(sock: Optional[socket.socket]) -> None:
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class _ConnPool:
    """Bounded per-address connection pool. Concurrent pulls from one
    holder each get their own socket (up to max_conns) instead of
    serializing on a single connection lock; a checked-out socket is
    exclusively held, which is what makes client-side request pipelining
    on it safe."""

    def __init__(self, address: str, max_conns: int):
        self.address = address
        self.max_conns = max(1, int(max_conns))
        self._cv = threading.Condition()
        self._slots: List[_PoolSlot] = []
        self._closed = False

    def checkout(self, timeout: float = 30.0) -> _PoolSlot:
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                if self._closed:
                    raise ObjectPullConnectionError(
                        f"transfer client closed ({self.address})")
                slot = next((s for s in self._slots
                             if not s.busy and not s.dead), None)
                if slot is not None:
                    slot.busy = True
                    return slot
                # idle dead slots free their capacity for a fresh dial
                self._slots = [s for s in self._slots if s.busy or not s.dead]
                if len(self._slots) < self.max_conns:
                    slot = _PoolSlot()
                    self._slots.append(slot)
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ObjectPullConnectionError(
                        f"no transfer connection to {self.address} "
                        f"within {timeout}s")
                self._cv.wait(min(remaining, 1.0))
        # dial OUTSIDE the lock (slow); the slot reserves our seat
        try:
            host, _, port = self.address.rpartition(":")
            sock = socket.create_connection((host, int(port)), timeout=30.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError as e:
            with self._cv:
                if slot in self._slots:
                    self._slots.remove(slot)
                self._cv.notify_all()
            raise ObjectPullConnectionError(
                f"cannot connect to {self.address}: {e}")
        with self._cv:
            if self._closed:
                if slot in self._slots:
                    self._slots.remove(slot)
                self._cv.notify_all()
                _close_sock(sock)
                raise ObjectPullConnectionError(
                    f"transfer client closed ({self.address})")
            slot.sock = sock
        return slot

    def checkin(self, slot: _PoolSlot, dead: bool = False) -> None:
        sock = None
        with self._cv:
            slot.busy = False
            if dead or self._closed or slot.dead:
                slot.dead = True
                sock, slot.sock = slot.sock, None
                if slot in self._slots:
                    self._slots.remove(slot)
            self._cv.notify_all()
        _close_sock(sock)

    def idle_count(self) -> int:
        with self._cv:
            return sum(1 for s in self._slots if not s.busy and not s.dead)

    def close(self) -> None:
        """Close EVERY tracked socket, including checked-out ones: an
        in-flight pull fails fast with a connection error instead of
        holding a leaked fd. Busy slots fully retire at their checkin."""
        with self._cv:
            self._closed = True
            socks = [s.sock for s in self._slots if s.sock is not None]
            for s in self._slots:
                s.dead = True
                if not s.busy:
                    s.sock = None
            self._slots = [s for s in self._slots if s.busy]
            self._cv.notify_all()
        for sock in socks:
            _close_sock(sock)


class ObjectTransferClient:
    """Chunked puller with a small per-address connection pool (the
    reference pools object-manager RPC channels likewise; here the pool
    additionally lets concurrent pulls from one holder overlap)."""

    def __init__(self, chunk_bytes: Optional[int] = None,
                 pool_conns: Optional[int] = None,
                 chunk_window: Optional[int] = None):
        self.chunk_bytes = int(chunk_bytes if chunk_bytes is not None
                               else config.object_transfer_chunk_bytes)
        self.pool_conns = int(pool_conns if pool_conns is not None
                              else config.object_transfer_pool_conns)
        self.chunk_window = max(1, int(
            chunk_window if chunk_window is not None
            else config.object_transfer_chunk_window))
        self._pools: Dict[str, _ConnPool] = {}
        self._global_lock = threading.Lock()
        self._next_id = 0
        self._closed = False
        self._plane = _NativePlane("native-transfer-client",
                                   _make_client_native)
        self._inflight: set = set()  # sids being pulled by THIS client
        self._inflight_lock = threading.Lock()
        # flow-accounting identity of the pulling side; empty means the
        # process-wide node id (set per-client in tests/benches that run
        # several logical pullers in one process)
        self.local_node = ""

    def _flow_dst(self) -> str:
        return self.local_node or object_ledger.local_node()

    def _pool(self, address: str) -> _ConnPool:
        with self._global_lock:
            if self._closed:
                raise ObjectPullConnectionError("transfer client closed")
            pool = self._pools.get(address)
            if pool is None:
                pool = self._pools[address] = _ConnPool(
                    address, self.pool_conns)
            return pool

    def _new_id(self) -> int:
        with self._global_lock:
            self._next_id += 1
            return self._next_id

    def _request_on(self, sock: socket.socket, address: str,
                    method: str, *args) -> Any:
        """One request/response round trip on an exclusively-held socket."""
        req_id = self._new_id()
        try:
            send_msg(sock, MSG_REQUEST,
                     {"id": req_id, "method": method, "args": args})
            msg_type, resp = recv_msg(sock)
        except (WireError, OSError) as e:
            raise ObjectPullConnectionError(
                f"transfer connection to {address} lost: {e}")
        if msg_type != MSG_RESPONSE or resp.get("id") != req_id:
            raise ObjectPullConnectionError(
                f"bad transfer response from {address}")
        if not resp.get("ok"):
            raise ObjectPullError(resp.get("error", "pull failed"))
        return resp["value"]

    def _call(self, address: str, method: str, *args) -> Any:
        slot = self._pool(address).checkout()
        dead = True
        try:
            value = self._request_on(slot.sock, address, method, *args)
            dead = False
            return value
        except ObjectPullError as e:
            # app-level refusal: the connection itself is fine
            dead = isinstance(e, ObjectPullConnectionError)
            raise
        finally:
            self._pool(address).checkin(slot, dead=dead)

    def _drop(self, address: str) -> None:
        """Retire every pooled connection for an address (holder restarted
        or unreachable); the next call dials fresh."""
        with self._global_lock:
            pool = self._pools.pop(address, None)
        if pool is not None:
            pool.close()

    def pull(self, address: str, object_id, raw: bool = False,
             peers: Sequence[str] = (), src_node: str = "") -> Any:
        """Pull one object from the holder at `address`; returns the value
        (raw=True: the sealed payload, store.get_raw parity).

        Fast path: one "stage" round trip on the control connection, then
        the C++ plane streams the blob arena-to-arena (_shm/transfer.cc)
        and the value unpickles from a zero-copy view. Fallback: ~1MB
        chunks, pipelined `chunk_window` requests deep per connection;
        large fallback pulls stripe byte ranges across `peers` that also
        hold the object (pull_from_any passes the ranked remainder)."""
        oid_hex = object_id.hex() if hasattr(object_id, "hex") else str(object_id)
        src_node = src_node or object_ledger.peer_node(address)
        t0 = time.monotonic()
        with _pull_inflight.track():
            try:
                staged = self._call(address, "stage", oid_hex, raw)
                total, native_port = staged["size"], staged["native_port"]
            except ObjectPullError as e:
                if "unknown method" not in str(e):
                    raise
                # holder predates the staged protocol: chunked via "meta"
                total, native_port = self._call(address, "meta", oid_hex,
                                                raw), None
            if native_port is not None:
                value = self._pull_native(address, native_port, oid_hex, raw,
                                          total, src_node)
                if value is not _NATIVE_MISS:
                    _pull_seconds.observe(time.monotonic() - t0,
                                          {"path": "native"})
                    return value
            blob = None
            if (peers and total >= config.object_transfer_stripe_min_bytes):
                blob = self._pull_striped(address, peers, oid_hex, raw, total,
                                          src_node)
            if blob is None:
                blob = self._pull_chunked(address, oid_hex, raw, 0, total,
                                          src_node=src_node)
            _pull_seconds.observe(time.monotonic() - t0, {"path": "chunked"})
            return pickle.loads(blob)

    def _pull_chunked(self, address: str, oid_hex: str, raw: bool,
                      start: int, end: int, src_node: str = "",
                      flow_path: str = "chunked") -> bytes:
        """Pull bytes [start, end) as pipelined chunk requests: a window of
        chunk_window requests stays outstanding on one exclusively-held
        connection instead of one synchronous round trip per ~1MB. The
        server handles a connection's requests strictly in order, so
        responses return in request order and match by id."""
        pool = self._pool(address)
        slot = pool.checkout()
        dead = True
        parts: List[bytes] = []
        pending: "deque[Tuple[int, int, int]]" = deque()  # (req_id, off, len)
        offset = start
        src_node = src_node or object_ledger.peer_node(address)
        flow_dst = self._flow_dst()
        try:
            sock = slot.sock
            while offset < end or pending:
                while offset < end and len(pending) < self.chunk_window:
                    length = min(self.chunk_bytes, end - offset)
                    req_id = self._new_id()
                    send_msg(sock, MSG_REQUEST,
                             {"id": req_id, "method": "chunk",
                              "args": (oid_hex, offset, length, raw)})
                    pending.append((req_id, offset, length))
                    offset += length
                req_id, off, _length = pending.popleft()
                msg_type, resp = recv_msg(sock)
                if msg_type != MSG_RESPONSE or resp.get("id") != req_id:
                    raise ObjectPullConnectionError(
                        f"bad transfer response from {address}")
                if not resp.get("ok"):
                    raise ObjectPullError(resp.get("error", "pull failed"))
                chunk = resp["value"]
                if not chunk:
                    raise ObjectPullError(
                        f"short read at {off}/{end} for {oid_hex}")
                parts.append(chunk)
                _pulled_chunks.inc()
                _pulled_bytes.inc(len(chunk))
                _pull_bytes.inc(len(chunk))
                object_ledger.record_flow(src_node, flow_dst, flow_path,
                                          len(chunk))
            dead = False
            object_ledger.record_flow(src_node, flow_dst, flow_path, 0,
                                      transfers=1)
        except (WireError, OSError) as e:
            raise ObjectPullConnectionError(
                f"transfer connection to {address} lost: {e}")
        except ObjectPullError as e:
            # app-level refusal mid-stream: responses for the rest of the
            # window are still queued on the socket — retire it rather
            # than desync the next caller
            dead = True if pending else isinstance(
                e, ObjectPullConnectionError)
            raise
        finally:
            pool.checkin(slot, dead=dead)
        return b"".join(parts)

    def _pull_striped(self, address: str, peers: Sequence[str],
                      oid_hex: str, raw: bool, total: int,
                      src_node: str = "") -> Optional[bytes]:
        """Stripe a large chunked pull across holders: confirmed peers each
        serve a contiguous byte range in parallel. Returns None when no
        peer confirms (caller falls back to the single-holder path); any
        stripe failure also falls back — striping is an optimization,
        never a correctness dependency."""
        holders = [address]
        for peer in peers:
            if len(holders) >= 4:  # diminishing returns past a few stripes
                break
            try:
                if self._call(peer, "contains", oid_hex):
                    holders.append(peer)
            except ObjectPullError:
                continue
        if len(holders) < 2:
            return None
        # contiguous ranges, chunk-aligned so stripes pipeline internally
        n = len(holders)
        per = ((total // n) // self.chunk_bytes + 1) * self.chunk_bytes
        ranges = []
        off = 0
        for h in holders:
            if off >= total:
                break
            ranges.append((h, off, min(off + per, total)))
            off += per
        results: List[Optional[bytes]] = [None] * len(ranges)
        errors: List[Optional[BaseException]] = [None] * len(ranges)

        def work(i: int, holder: str, lo: int, hi: int) -> None:
            try:
                # each stripe is its own edge: bytes flow from the stripe's
                # holder, not from the primary address
                src = src_node if holder == address else \
                    object_ledger.peer_node(holder)
                results[i] = self._pull_chunked(holder, oid_hex, raw, lo, hi,
                                                src_node=src,
                                                flow_path="stripe")
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors[i] = e

        threads = [threading.Thread(
            target=work, args=(i, h, lo, hi), daemon=True,
            name=f"stripe-{i}") for i, (h, lo, hi) in enumerate(ranges)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if any(e is not None for e in errors) or any(
                r is None for r in results):
            failed = next(e for e in errors if e is not None)
            logger.debug("striped pull of %s fell back to one holder: %r",
                         oid_hex[:16], failed)
            return None
        return b"".join(results)  # type: ignore[arg-type]

    def _pull_native(self, address: str, native_port: int, oid_hex: str,
                     raw: bool, total: int, src_node: str = "") -> Any:
        """One native arena-to-arena pull; returns _NATIVE_MISS to send the
        caller down the chunked path (never raises for availability-class
        failures — the chunked path is the answer to all of them)."""
        from .shm_store import PullRejected, ShmStoreError

        self._plane.start_async()  # idempotent; first pull rides chunks
        native, staging = self._plane.acquire()
        if native is None:
            return _NATIVE_MISS
        host = address.rpartition(":")[0]
        try:
            sid = _stage_id(ObjectID.from_hex(oid_hex).binary(), raw)
        except (ValueError, TypeError):
            self._plane.release()
            return _NATIVE_MISS
        try:
            transferred = False
            if not staging.contains(sid):
                with self._inflight_lock:
                    winner = sid not in self._inflight
                    if winner:
                        self._inflight.add(sid)
                if not winner:
                    # another thread of THIS client is pulling the same
                    # object (clients never share staging arenas, so this
                    # is the only duplicate source): wait for it to finish
                    # rather than re-downloading the same bytes
                    deadline = time.monotonic() + 30.0
                    while time.monotonic() < deadline:
                        with self._inflight_lock:
                            if sid not in self._inflight:
                                break
                        time.sleep(0.01)
                    if not staging.contains(sid):
                        return _NATIVE_MISS  # winner failed; use chunks
                else:
                    try:
                        n = native.pull_into(host, native_port, sid, staging)
                        if n is None:
                            # staged blob evicted between stage and pull:
                            # restage once (the holder re-pins it), then
                            # give up to chunks. The holder may have
                            # restarted its native plane (or resealed a
                            # different-size blob) since the first stage —
                            # retry against the RESPONSE's port/size, not
                            # the stale ones
                            restaged = self._call(address, "stage", oid_hex,
                                                  raw)
                            native_port = restaged.get("native_port")
                            total = restaged.get("size", total)
                            if native_port is None:
                                return _NATIVE_MISS
                            n = native.pull_into(host, native_port, sid,
                                                 staging)
                            if n is None:
                                return _NATIVE_MISS
                        transferred = True
                    finally:
                        with self._inflight_lock:
                            self._inflight.discard(sid)
            view = staging.get_view(sid)
            if view is None:
                return _NATIVE_MISS  # evicted locally before the read
            try:
                value = pickle.loads(view)
            finally:
                # release the pin but keep the sealed blob: concurrent and
                # repeat pulls of the same (immutable) object hit it here,
                # and the arena's LRU/slot eviction bounds total residency
                staging.release(sid)
            if transferred:  # count only bytes that crossed the network
                _pulled_chunks.inc()
                _pulled_bytes.inc(total)
                _pull_bytes.inc(total)
                object_ledger.record_flow(
                    src_node or object_ledger.peer_node(address),
                    self._flow_dst(), "native", total, transfers=1)
            return value
        except PullRejected:
            return _NATIVE_MISS  # does not fit the local arena
        except ShmStoreError as e:
            logger.warning("native pull from %s:%d failed (%s); "
                           "falling back to chunks", host, native_port, e)
            return _NATIVE_MISS
        finally:
            self._plane.release()

    def close(self) -> None:
        """Close every pooled connection (including ones held by in-flight
        pulls, which fail fast with a connection error) and tear down the
        native plane. Safe to race with concurrent pulls: each socket is
        tracked in exactly one pool slot from dial to close, so nothing
        leaks even if a pull checked its socket out before we got here."""
        with self._global_lock:
            self._closed = True
            pools = list(self._pools.values())
            self._pools.clear()
        for pool in pools:
            pool.close()
        self._plane.teardown()


def serve_object_transfer(runtime, host: str = "127.0.0.1",
                          port: int = 0) -> ObjectTransferServer:
    """Start the transfer plane for a Runtime's driver store and advertise
    the address in the control plane KV (`object_transfer/{node_id}`), so
    remote runtimes sharing the control plane can locate the holder."""
    store = runtime.driver_agent.store
    server = ObjectTransferServer(store, host, port)
    node_hex = runtime.driver_agent.node_id.hex()
    object_ledger.note_peer(server.address, node_hex)
    try:
        runtime.control_plane.kv_put(KV_PREFIX + node_hex, server.address)
    except Exception:  # noqa: BLE001 — advertising is best-effort
        logger.warning("could not advertise transfer address", exc_info=True)
    server.start_load_gossip(runtime.control_plane, node_hex)
    return server


_default_client: Optional[ObjectTransferClient] = None
_default_client_lock = threading.Lock()


def _shared_client() -> ObjectTransferClient:
    """Process-wide default puller. Long-lived so the native path's
    connections and staging arena amortize across calls — a per-call
    client would pay arena setup/teardown per object."""
    global _default_client
    with _default_client_lock:
        if _default_client is None:
            _default_client = ObjectTransferClient()
        return _default_client


def _ranked_holders(control_plane) -> List[str]:
    """Advertised transfer addresses, least-loaded first. Load is each
    holder's gossiped outstanding-request count (`object_transfer_load/*`
    KV, published by start_load_gossip); holders that never gossiped rank
    as idle, preserving the old iteration order among ties."""
    ranked: List[Tuple[float, int, str]] = []
    for idx, key in enumerate(control_plane.kv_keys(KV_PREFIX)):
        address = control_plane.kv_get(key)
        if not address:
            continue
        node_hex = key[len(KV_PREFIX):]
        object_ledger.note_peer(address, node_hex)
        load = 0.0
        try:
            raw = control_plane.kv_get(LOAD_PREFIX + node_hex)
            if raw:
                load = float(raw)
        except Exception:  # noqa: BLE001 — load is advisory
            pass
        ranked.append((load, idx, address))
    ranked.sort()
    return [addr for _, _, addr in ranked]


def pull_from_any(control_plane, object_id,
                  client: Optional[ObjectTransferClient] = None,
                  cache_store=None, on_cached=None) -> Any:
    """Resolve `object_transfer/*` advertisements from the control plane
    and try holders in ascending gossiped-load order until one serves the
    object. The unranked remainder is offered to the client as striping
    peers for large chunked pulls.

    With `cache_store`, the pull fetches the sealed payload and seals it
    into that (local) store before returning the loaded value — the
    pull-through replica. `on_cached(object_id)` then fires so the caller
    can register the new location in its directory; both steps are
    best-effort and never fail the get (objects are immutable once sealed,
    so a cached replica can never go stale)."""
    from ..util import tracing

    client = client or _shared_client()
    want_raw = cache_store is not None
    holders = _ranked_holders(control_plane)
    with tracing.span_if_traced("object_pull",
                                {"object_id": object_id.hex()[:16],
                                 "holders": len(holders)}):
        return _pull_from_holders(client, object_id, want_raw, holders,
                                  cache_store, on_cached)


def _pull_from_holders(client, object_id, want_raw, holders,
                       cache_store, on_cached) -> Any:
    errors = []
    for pos, address in enumerate(holders):
        peers = holders[pos + 1:] + holders[:pos]
        # two attempts per holder, but ONLY for transport-class failures:
        # the shared client pools connections, so the first failure after
        # a holder restart (or an idle conn being dropped) is just the
        # stale socket — the client drops it and the retry dials fresh. An
        # application-level refusal ("object not here") is the holder's
        # real answer; re-asking the same holder just doubles pull latency
        # across a large fleet.
        for attempt in (0, 1):
            try:
                value = client.pull(address, object_id, raw=want_raw,
                                    peers=peers)
            except ObjectPullConnectionError as e:
                if attempt == 1:
                    errors.append((address, str(e)))
                continue
            except ObjectPullError as e:
                errors.append((address, str(e)))
                break
            if not want_raw:
                return value
            try:
                cache_store.put(object_id, value)
                if on_cached is not None:
                    on_cached(object_id)
            except Exception:  # noqa: BLE001 — caching is best-effort
                logger.debug("pull-through cache of %s failed", object_id,
                             exc_info=True)
            return value.load() if isinstance(value, SealedBytes) else value
    raise ObjectPullError(
        f"no advertised holder served {object_id}: {errors}"
    )
