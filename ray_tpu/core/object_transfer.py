"""Object transfer plane: chunked pull of sealed objects between runtimes.

Reference analogue: `src/ray/object_manager/` — `PullManager`/`PushManager`
move plasma objects between nodes as ~1MB chunks over a dedicated gRPC
service (`object_manager.proto :: ObjectManagerService`). Same shape here:
each runtime can serve its object store on a TCP port; a remote runtime
locates the holder (control-plane KV carries `object_transfer/{node}` →
address) and pulls the object as fixed-size chunks, reassembling and
sealing it into its own store. Pull-based (the receiver drives), like the
reference — admission control stays with the consumer.

Intra-slice device arrays never cross this plane: jax arrays travel as
compiled collectives over ICI. This is the HOST object plane (CPU tensors,
rollouts, checkpoint shards, pickled results) between loosely-coupled
runtimes.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import object_ledger
from .config import config
from .ids import ObjectID
from .logging import get_logger
from .metrics import MICRO_BUCKETS, Counter, Gauge, Histogram
from .object_store import SealedBytes
from .wire import (MSG_BLOB, MSG_REQUEST, MSG_RESPONSE, WireError,
                   recv_frame_into, recv_msg, send_blob, send_msg)

logger = get_logger("object_transfer")

DEFAULT_CHUNK_BYTES = 1 << 20  # ~1MB, the reference's chunk size

KV_PREFIX = "object_transfer/"  # control-plane KV key prefix for addresses
# holder-side outstanding-pull load, gossiped so pullers can rank holders
LOAD_PREFIX = "object_transfer_load/"
# per-node host identity token (hostname + boot id), advertised so pullers
# can recognize a same-host holder and rank it first / attach its arena
HOST_PREFIX = "object_transfer_host/"
# relay-tree slot claims: object_transfer_relay/{oid_hex}/{slot:06d} ->
# "address|flow_label|node_hex". Claimed atomically (kv_put overwrite=False)
# by pullers joining a broadcast; slot k's parent is slot (k-fanout)//fanout
RELAY_PREFIX = "object_transfer_relay/"

# Native fast path (_shm/transfer.cc): the holder stages the serialized
# blob in a shm arena once, a C++ thread streams it zero-copy, and the
# puller lands it straight in its own arena — Python never allocates or
# copies on the data path. Sized by this env knob; objects larger than
# the staging arena ride the chunked Python path below.
STAGING_BYTES = int(os.environ.get("RAY_TPU_TRANSFER_STAGING_BYTES",
                                   str(256 << 20)))


_staging_seq = itertools.count()  # unique arena names (id() can be reused)


def _staging_name(tag: str) -> str:
    return f"/rtpu_{tag}_{os.getpid()}_{next(_staging_seq)}"


def _stage_id(oid: bytes, raw: bool) -> bytes:
    """Staging-arena id for (object, raw-flag): sha1 maps the 28-byte
    ObjectID onto the store's 20-byte ids, deterministically on both ends
    of the pull. raw=True serves the SEALED payload — a different blob
    for the same object — so it hashes to a distinct staging id."""
    return hashlib.sha1(oid + (b"r" if raw else b"")).digest()

_pulled_chunks = Counter(
    "object_transfer_chunks_pulled", "Chunks pulled from remote runtimes."
)
_pulled_bytes = Counter(
    "object_transfer_bytes_pulled", "Bytes pulled from remote runtimes."
)
_pull_seconds = Histogram(
    "object_pull_seconds",
    "Wall seconds per completed remote pull, tagged by data path.",
    buckets=MICRO_BUCKETS,
)
_pull_bytes = Counter(
    "object_pull_bytes", "Bytes that crossed the network on remote pulls."
)
_pull_inflight = Gauge(
    "object_pull_inflight", "Remote pulls currently in flight on this side."
)
# pull-through cache outcomes (incremented by the get paths in
# core_worker/worker_api; defined here because the cache IS the object
# plane's replica mechanism)
_cache_hits = Counter(
    "object_cache_hits",
    "Gets served from the local store for objects a prior get pulled "
    "through from a remote holder.",
)
_cache_misses = Counter(
    "object_cache_misses",
    "Gets that had to pull the object from a remote holder.",
)


class ObjectPullError(RuntimeError):
    pass


class ObjectPullConnectionError(ObjectPullError):
    """Transport-class pull failure (connection lost / garbled response):
    the CONNECTION is suspect, not the holder's answer. Retrying the same
    holder on a fresh socket makes sense; an application-level refusal
    (plain ObjectPullError — e.g. the object is not there) does not."""


_NATIVE_MISS = object()  # sentinel: native path unavailable, use chunks
_SHM_MISS = object()  # sentinel: same-host arena handoff unavailable
_RELAY_MISS = object()  # sentinel: relay tree not joined, flat pull


def _make_client_native():
    from .shm_store import NativeTransferClient, ShmObjectStore

    staging = ShmObjectStore(
        _staging_name("xc"), capacity=STAGING_BYTES, max_objects=1024,
    )
    try:
        native = NativeTransferClient()
    except Exception:
        staging.close()
        raise
    return staging, native, lambda n: n.close()


def _raw_alloc(n: int):
    """Uninitialized receive buffer. bytearray(n) zero-fills — a full
    extra memory pass that roughly doubles the cost of landing a large
    blob; np.empty is a bare malloc. Every byte is overwritten by
    recv_into before anything reads it."""
    try:
        import numpy as np

        return np.empty(n, dtype=np.uint8)
    except Exception:  # noqa: BLE001 — numpy-less install
        return bytearray(n)


class _BufferPool:
    """Recycles large transfer receive buffers across pulls.

    glibc mmaps every allocation above its threshold cap (32MB), so a
    fresh multi-MB buffer pays a full page-fault pass per pull and is
    munmapped on free — the kernel-side cost dominates large transfers.
    The pool keeps recent buffers mapped and hands one back only when
    nothing outside the pool references it (sys.getrefcount: zero-copy
    decode hands out memoryviews that hold refs, so an in-use buffer can
    never be recycled under its consumers). Total retained bytes are
    bounded by object_transfer_buffer_pool_bytes, evicting idle-largest
    first; 0 disables pooling entirely."""

    def __init__(self):
        self._lock = threading.Lock()
        self._bufs: List[Any] = []  # LRU order: oldest first

    def take(self, n: int):
        import sys

        cap = int(config.object_transfer_buffer_pool_bytes)
        if cap <= 0 or n > cap:
            return _raw_alloc(n)
        with self._lock:
            for i in range(len(self._bufs)):
                a = self._bufs[i]
                # 3 == the pool's list slot + loop local + getrefcount arg
                if len(a) == n and sys.getrefcount(a) == 3:
                    del self._bufs[i]
                    self._bufs.append(a)  # most-recently-used
                    return a
            buf = _raw_alloc(n)
            self._bufs.append(buf)
            total = sum(len(a) for a in self._bufs)
            while total > cap and len(self._bufs) > 1:
                # drop the oldest pool ref: an idle buffer unmaps now, an
                # in-use one when its consumers drop — either way it stops
                # counting against the retained bound
                total -= len(self._bufs.pop(0))
            return buf


_buffer_pool = _BufferPool()


def _alloc_buf(n: int):
    return _buffer_pool.take(n)


_host_token_cache: Optional[str] = None


def _host_token() -> str:
    """Stable identity of THIS host across processes: hostname + boot id.
    Two runtimes with equal tokens share /dev/shm, so a pull between them
    can attach the holder's staging arena instead of copying over a
    socket. The boot id guards against recycled hostnames in containers
    that still don't share a shm namespace-worth of trust — equal boot
    ids on one kernel are the practical same-machine signal."""
    global _host_token_cache
    if _host_token_cache is None:
        boot = ""
        try:
            with open("/proc/sys/kernel/random/boot_id") as f:
                boot = f.read().strip()
        except OSError:
            pass
        _host_token_cache = f"{socket.gethostname()}|{boot}"
    return _host_token_cache


# v2 wire blob: out-of-band buffers ride as raw trailing bytes so the
# receiving side can reconstruct the value ZERO-COPY over its receive
# buffer (pickle protocol 5 `buffers=`), instead of paying a full-blob
# pickle.loads memcpy per puller. The magic cannot collide with a plain
# pickle (protocol>=2 starts b"\x80"); unmagiced blobs decode as v1.
_BLOB_MAGIC = b"\x93RTB"
_U32 = struct.Struct(">I")


def _encode_blob(value: Any) -> bytes:
    """[magic][u32 meta_len][meta][head][raw buffers...]; meta = pickled
    (head_len, [buffer lengths]). Falls back to an unmagiced flat pickle
    whenever out-of-band extraction can't work (exotic buffers,
    cloudpickle-only values)."""
    bufs: List[pickle.PickleBuffer] = []
    try:
        head = pickle.dumps(value, protocol=5, buffer_callback=bufs.append)
        raws = [b.raw() for b in bufs]
    except Exception:  # noqa: BLE001 — non-contiguous buffer / closure
        import cloudpickle

        return cloudpickle.dumps(value, protocol=5)
    meta = pickle.dumps((len(head), [len(r) for r in raws]), protocol=2)
    return b"".join([_BLOB_MAGIC, _U32.pack(len(meta)), meta, head, *raws])


def _decode_blob(blob, zero_copy: bool = True) -> Any:
    """Inverse of _encode_blob. zero_copy=True reconstructs buffer-backed
    leaves as read-only views over `blob` (the views keep it alive) — use
    when the caller owns the bytes. zero_copy=False materializes copies —
    required when `blob` is a borrowed mapping (shm arena view) that may
    be released/unmapped after decode."""
    mv = memoryview(blob)
    if mv.nbytes < 8 or bytes(mv[:4]) != _BLOB_MAGIC:
        return pickle.loads(mv)  # v1 flat pickle
    (meta_len,) = _U32.unpack(mv[4:8])
    off = 8 + meta_len
    head_len, buf_lens = pickle.loads(mv[8:off])
    head = mv[off:off + head_len]
    off += head_len
    buffers = []
    for n in buf_lens:
        b = mv[off:off + n]
        off += n
        buffers.append(b.toreadonly() if zero_copy else bytes(b))
    return pickle.loads(head, buffers=buffers)


class _TransferHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: "ObjectTransferServer" = self.server  # type: ignore[assignment]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                msg_type, req = recv_msg(sock)
                if msg_type != MSG_REQUEST:
                    raise WireError(f"unexpected message type {msg_type}")
                if req.get("method") == "chunk_stream":
                    # zero-copy lane: ONE request streams a whole byte
                    # range as MSG_BLOB frames (header + memoryview
                    # scatter-gather per chunk, no per-chunk pickling on
                    # either side), then a RESPONSE closes the stream.
                    # An app-level error mid-stream also arrives as a
                    # RESPONSE — the connection stays in sync either way
                    server._load_add(1)
                    try:
                        resp = self._stream_chunks(server, sock, req)
                    finally:
                        server._load_add(-1)
                    send_msg(sock, MSG_RESPONSE, resp)
                    continue
                server._load_add(1)
                try:
                    resp = self._dispatch(server, req)
                except Exception as e:  # noqa: BLE001 — serialized to caller
                    resp = {"id": req.get("id"), "ok": False, "error": repr(e)}
                finally:
                    server._load_add(-1)
                send_msg(sock, MSG_RESPONSE, resp)
        except (WireError, OSError):
            pass  # puller disconnected

    def _stream_chunks(self, server: "ObjectTransferServer",
                       sock: socket.socket, req: dict) -> dict:
        """Push blob frames for [start, end) in `step`-sized chunks. On a
        relay node each _read_range parks until its range commits, so the
        stream is naturally paced by the upstream pull (chunk-pipelined
        dissemination). Returns the closing response; transport errors
        propagate and kill the connection (the puller sees them as a
        connection failure and retries elsewhere)."""
        try:
            oid_hex, start, end, step, *rest = req["args"]
            raw = bool(rest and rest[0])
            off = int(start)
            end = int(end)
            step = max(1, int(step))
            while off < end:
                n = min(step, end - off)
                view = server._read_range(oid_hex, raw, off, n)
                send_blob(sock, req["id"], off, view)
                off += n
        except (WireError, OSError):
            raise
        except Exception as e:  # noqa: BLE001 — serialized to caller
            return {"id": req.get("id"), "ok": False, "error": repr(e)}
        return {"id": req["id"], "ok": True, "value": None}

    def _dispatch(self, server: "ObjectTransferServer", req: dict) -> dict:
        method = req.get("method")
        # args may carry a trailing raw flag: raw=True ships the SEALED
        # payload (SealedBytes pickled as-is) so sealing survives the hop
        # (store.get_raw parity for cross-runtime pulls)
        if method == "meta":
            oid_hex, *rest = req["args"]
            raw = bool(rest and rest[0])
            partial = server._partial_for(oid_hex, raw)
            size = partial.total if partial is not None else \
                len(server._blob_for(oid_hex, raw=raw))
            return {"id": req["id"], "ok": True, "value": size}
        if method == "stage":
            oid_hex, raw = req["args"]
            size, native_port, shm = server._stage(oid_hex, bool(raw))
            return {"id": req["id"], "ok": True,
                    "value": {"size": size, "native_port": native_port,
                              "shm": shm}}
        if method == "chunk":
            oid_hex, offset, length, *rest = req["args"]
            view = server._read_range(oid_hex, bool(rest and rest[0]),
                                      int(offset), int(length))
            return {"id": req["id"], "ok": True, "value": bytes(view)}
        if method == "contains":
            (oid_hex,) = req["args"]
            oid = ObjectID.from_hex(oid_hex)
            return {"id": req["id"], "ok": True,
                    "value": bool(server._store.contains(oid))}
        if method == "load":
            # holders serve their own outstanding-pull count so pullers
            # can rank them directly (the KV gossip is the cached form)
            return {"id": req["id"], "ok": True, "value": server.outstanding}
        raise WireError(f"unknown method {method!r}")


class _NativePlane:
    """Owns one side's native-path pair (staging arena + C++ endpoint)
    with the init/commit/teardown choreography the server and client
    share. `make()` runs on a background thread (a cold environment may
    have to COMPILE the shm library — no request or pull ever waits on
    that); `acquire()/release()` hold a use count so `teardown()` never
    munmaps the arena under an in-flight, GIL-released native call."""

    def __init__(self, name: str, make):
        self._name = name
        self._make = make  # () -> (staging, native, stop_native)
        self.staging = None
        self.native = None
        self._stop_native = None
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._started = False
        self._users = 0

    def start_async(self) -> None:
        with self._lock:
            if self._started or self._closed:
                return
            self._started = True
        threading.Thread(target=self._init, daemon=True,
                         name=self._name).start()

    def _init(self) -> None:
        try:
            staging, native, stop_native = self._make()
        except Exception:  # noqa: BLE001 — the chunked path remains
            logger.warning("%s unavailable", self._name, exc_info=True)
            return
        with self._lock:
            if not self._closed:
                self.staging = staging
                self.native = native
                self._stop_native = stop_native
                return
        stop_native(native)  # teardown() won the race
        staging.close()

    def acquire(self):
        """-> (native, staging) with a use hold, or (None, None). A
        non-None acquire MUST be paired with release()."""
        with self._lock:
            if self._closed or self.native is None:
                return None, None
            self._users += 1
            return self.native, self.staging

    def release(self) -> None:
        with self._lock:
            self._users -= 1
            if self._users == 0:
                self._cond.notify_all()

    def teardown(self, wait_s: float = 5.0) -> None:
        with self._lock:
            self._closed = True
            native, staging = self.native, self.staging
            stop_native = self._stop_native
            self.native = self.staging = self._stop_native = None
            deadline = time.monotonic() + wait_s
            while self._users > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    # leaking the MAPPING beats munmapping it under a live
                    # native call (use-after-unmap in the C recv/send) —
                    # but the /dev/shm NAME must still go, or the segment
                    # outlives the process and fills /dev/shm on restarts
                    logger.warning("%s busy at teardown; leaking arena "
                                   "mapping (name unlinked)", self._name)
                    if staging is not None:
                        staging.unlink_name()
                    native = staging = None
                    break
                self._cond.wait(left)
        if native is not None:
            stop_native(native)
        if staging is not None:
            staging.close()


class _Partial:
    """A blob mid-arrival on a relay node: the receive buffer doubles as
    the serving source. The puller commits each landed chunk (a strictly
    growing byte prefix); downstream chunk requests for a not-yet-landed
    range park on `cond` until the range commits, the upstream pull fails,
    or the relay timeout expires."""

    __slots__ = ("buf", "total", "committed", "cond", "failed", "done")

    def __init__(self, total: int):
        self.buf = _alloc_buf(total)
        self.total = total
        self.committed = 0
        self.cond = threading.Condition()
        self.failed: Optional[str] = None
        self.done = False

    def commit(self, upto: int) -> None:
        with self.cond:
            if upto > self.committed:
                self.committed = upto
                self.cond.notify_all()


class ObjectTransferServer(socketserver.ThreadingTCPServer):
    """Serves one runtime's object store for remote pulls.

    The serialized blob for an object is cached per object id while any
    pull is in flight (pulls are chunked across many requests), and
    dropped once the store drops the object. A relay pull additionally
    registers a _Partial here, so the node serves committed byte ranges
    to downstream pullers while its own pull is still in flight."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, store, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _TransferHandler)
        self._store = store
        self._blob_cache: Dict[Tuple[str, bool], bytes] = {}
        self._partials: Dict[Tuple[str, bool], _Partial] = {}
        self._cache_lock = threading.Lock()
        # outstanding-pull load: requests currently being served. Gossiped
        # to the control-plane KV (start_load_gossip) so pullers rank
        # lightly-loaded holders first.
        self._load = 0
        self._load_lock = threading.Lock()
        self._gossip_stop = threading.Event()
        self._plane = _NativePlane("native-transfer-server",
                                   self._make_native)
        self._plane.start_async()
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="object-transfer"
        )
        self._thread.start()
        logger.info("object transfer plane on %s:%d", *self.server_address)

    def _load_add(self, delta: int) -> None:
        with self._load_lock:
            self._load += delta

    @property
    def outstanding(self) -> int:
        with self._load_lock:
            return self._load

    def start_load_gossip(self, control_plane, node_hex: str,
                          period_s: float = 0.25) -> None:
        """Publish this holder's outstanding-pull count to the control
        plane KV (`object_transfer_load/{node}`) on change; pull_from_any
        ranks holders by it. Best-effort: a stale or missing value only
        degrades ranking, never correctness."""

        def loop() -> None:
            last: Optional[int] = None
            while not self._gossip_stop.wait(period_s):
                load = self.outstanding
                if load == last:
                    continue
                try:
                    control_plane.kv_put(LOAD_PREFIX + node_hex, str(load))
                    last = load
                except Exception:  # noqa: BLE001 — control plane gone
                    return

        threading.Thread(target=loop, daemon=True,
                         name="transfer-load-gossip").start()

    def _make_native(self):
        from .shm_store import NativeTransferServer, ShmObjectStore

        staging = ShmObjectStore(
            _staging_name("xs"), capacity=STAGING_BYTES, max_objects=1024,
        )
        try:
            native = NativeTransferServer(staging,
                                          host=self.server_address[0])
        except Exception:
            staging.close()
            raise
        logger.info("native transfer plane on port %d", native.port)
        return staging, native, lambda n: n.stop()

    def _stage(self, oid_hex: str, raw: bool) \
            -> Tuple[int, Optional[int], Optional[dict]]:
        """Ensure the blob for (oid, raw) sits in the staging arena; ->
        (size, native_port, shm). native_port None = use the chunked
        path. `shm` carries the arena name + host token once the blob is
        staged, so a same-host puller can map it directly (zero-copy
        handoff) instead of copying over any socket."""
        partial = self._partial_for(oid_hex, raw)
        if partial is not None and not partial.done:
            # mid-relay: serve the committed prefix over the chunk lane
            return partial.total, None, None
        try:
            sid = _stage_id(ObjectID.from_hex(oid_hex).binary(), raw)
        except (ValueError, TypeError):
            sid = None  # non-ObjectID key: chunked path only
        native, staging = self._plane.acquire() if sid is not None \
            else (None, None)
        if native is None:
            return len(self._blob_for(oid_hex, raw=raw)), None, None
        shm_info = {"arena": staging.name, "token": _host_token()}
        try:
            view = staging.get_view(sid)
            if view is not None:  # already staged: size from the arena,
                try:              # no re-pickle of the value
                    return len(view), native.port, shm_info
                finally:
                    staging.release(sid)
            blob = self._blob_for(oid_hex, raw=raw)
            if len(blob) > (STAGING_BYTES * 3) // 4:
                return len(blob), None, None
            try:
                staging.put(sid, blob)
            except Exception:  # noqa: BLE001 — races/arena pressure
                if not staging.contains(sid):
                    return len(blob), None, None  # cannot stage: chunked
            # the arena copy now serves all pulls; dropping the byte-cache
            # entry halves holder-side residency for large objects
            with self._cache_lock:
                self._blob_cache.pop((oid_hex, raw), None)
            return len(blob), native.port, shm_info
        finally:
            self._plane.release()

    # -- relay partials -----------------------------------------------------

    def _partial_for(self, oid_hex: str, raw: bool) -> Optional[_Partial]:
        with self._cache_lock:
            return self._partials.get((oid_hex, raw))

    def begin_partial(self, oid_hex: str, raw: bool,
                      total: int) -> Optional[_Partial]:
        """Register a partial for an inbound relay pull. The returned
        _Partial's buf IS the receive buffer: commit() after each landed
        chunk publishes the prefix to downstream pullers. Returns None if
        a partial already exists — exactly one pull per node feeds it."""
        with self._cache_lock:
            if (oid_hex, raw) in self._partials:
                return None
            p = _Partial(total)
            self._partials[(oid_hex, raw)] = p
            return p

    def finish_partial(self, oid_hex: str, raw: bool) -> None:
        """Promote a completed partial into the blob cache. The filled
        bytearray moves as-is — late chunk requests see byte-identical
        data whether they hit the partial or the cache."""
        with self._cache_lock:
            p = self._partials.pop((oid_hex, raw), None)
            if p is None:
                return
            if len(self._blob_cache) >= 64:
                self._blob_cache.pop(next(iter(self._blob_cache)))
            self._blob_cache[(oid_hex, raw)] = p.buf
        with p.cond:
            p.committed = p.total
            p.done = True
            p.cond.notify_all()

    def fail_partial(self, oid_hex: str, raw: bool, error: str) -> None:
        """The inbound relay pull died: wake every parked reader with an
        application-level error so downstream pullers fall back to a
        surviving holder instead of hanging."""
        with self._cache_lock:
            p = self._partials.pop((oid_hex, raw), None)
        if p is None:
            return
        with p.cond:
            p.failed = error or "relay source failed"
            p.cond.notify_all()

    def drop_cached(self, oid_hex: str) -> None:
        """Drop any cached wire blobs and partials for an object (both raw
        flavors); benches/teardown use it to bound holder residency."""
        for raw in (False, True):
            with self._cache_lock:
                self._blob_cache.pop((oid_hex, raw), None)
                p = self._partials.pop((oid_hex, raw), None)
            if p is not None:
                with p.cond:
                    p.failed = "partial dropped"
                    p.cond.notify_all()

    def _read_range(self, oid_hex: str, raw: bool, offset: int,
                    length: int) -> memoryview:
        """Byte range [offset, offset+length) of the wire blob, as a view
        (no copy). On a relay node with the blob mid-arrival, the read
        parks until the range commits; a dead upstream or an expired
        object_relay_timeout_s surfaces as an app-level error (KeyError),
        which tells the puller to fall back to another holder."""
        p = self._partial_for(oid_hex, raw)
        if p is None:
            blob = self._blob_for(oid_hex, raw=raw)
            return memoryview(blob)[offset:offset + length]
        end = min(offset + length, p.total)
        deadline = time.monotonic() + float(config.object_relay_timeout_s)
        with p.cond:
            while p.committed < end and p.failed is None:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise KeyError(
                        f"relay range [{offset}, {end}) of {oid_hex[:16]} "
                        f"not committed within "
                        f"{config.object_relay_timeout_s}s "
                        f"(have {p.committed}/{p.total})")
                p.cond.wait(min(left, 0.5))  # raylint: disable=R2 — parked reader wakes on commit/fail notify; the timeout re-check bounds the wait
            if p.committed >= end:
                # p.buf stays valid after finish_partial (the bytearray
                # itself is promoted into the blob cache, never copied)
                return memoryview(p.buf)[offset:end]
            raise KeyError(f"relay source for {oid_hex[:16]} failed: "
                           f"{p.failed}")

    @property
    def address(self) -> str:
        host, port = self.server_address
        return f"{host}:{port}"

    def _blob_for(self, oid_hex: str, raw: bool = False) -> bytes:
        key = (oid_hex, raw)
        with self._cache_lock:
            blob = self._blob_cache.get(key)
            if blob is not None:
                return blob
        oid = ObjectID.from_hex(oid_hex)
        if not self._store.contains(oid):
            raise KeyError(f"object {oid_hex} not in local store")
        if raw:
            value = self._store.get_raw(oid, timeout=0.0)
        else:
            value = self._store.get(oid, timeout=0.0)
        blob = _encode_blob(value)
        with self._cache_lock:
            # bound the cache: drop the oldest entries past 64
            if len(self._blob_cache) >= 64:
                self._blob_cache.pop(next(iter(self._blob_cache)))
            self._blob_cache[key] = blob
        return blob

    def stop(self) -> None:
        self._gossip_stop.set()
        with self._cache_lock:
            partials = list(self._partials.values())
            self._partials.clear()
        for p in partials:  # wake parked relay readers before the sockets go
            with p.cond:
                p.failed = "transfer server stopped"
                p.cond.notify_all()
        self.shutdown()
        self.server_close()
        self._plane.teardown()


class _PoolSlot:
    """One pooled connection. The socket stays tracked here from dial to
    close, so _ConnPool.close() can reach every fd it ever created —
    including ones checked out by in-flight pulls."""

    __slots__ = ("sock", "busy", "dead")

    def __init__(self):
        self.sock: Optional[socket.socket] = None
        self.busy = True  # born checked-out by the dialing thread
        self.dead = False


def _close_sock(sock: Optional[socket.socket]) -> None:
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class _ConnPool:
    """Bounded per-address connection pool. Concurrent pulls from one
    holder each get their own socket (up to max_conns) instead of
    serializing on a single connection lock; a checked-out socket is
    exclusively held, which is what makes client-side request pipelining
    on it safe."""

    def __init__(self, address: str, max_conns: int):
        self.address = address
        self.max_conns = max(1, int(max_conns))
        self._cv = threading.Condition()
        self._slots: List[_PoolSlot] = []
        self._closed = False

    def checkout(self, timeout: float = 30.0) -> _PoolSlot:
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                if self._closed:
                    raise ObjectPullConnectionError(
                        f"transfer client closed ({self.address})")
                slot = next((s for s in self._slots
                             if not s.busy and not s.dead), None)
                if slot is not None:
                    slot.busy = True
                    return slot
                # idle dead slots free their capacity for a fresh dial
                self._slots = [s for s in self._slots if s.busy or not s.dead]
                if len(self._slots) < self.max_conns:
                    slot = _PoolSlot()
                    self._slots.append(slot)
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ObjectPullConnectionError(
                        f"no transfer connection to {self.address} "
                        f"within {timeout}s")
                self._cv.wait(min(remaining, 1.0))
        # dial OUTSIDE the lock (slow); the slot reserves our seat
        try:
            host, _, port = self.address.rpartition(":")
            sock = socket.create_connection((host, int(port)), timeout=30.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError as e:
            with self._cv:
                if slot in self._slots:
                    self._slots.remove(slot)
                self._cv.notify_all()
            raise ObjectPullConnectionError(
                f"cannot connect to {self.address}: {e}")
        with self._cv:
            if self._closed:
                if slot in self._slots:
                    self._slots.remove(slot)
                self._cv.notify_all()
                _close_sock(sock)
                raise ObjectPullConnectionError(
                    f"transfer client closed ({self.address})")
            slot.sock = sock
        return slot

    def checkin(self, slot: _PoolSlot, dead: bool = False) -> None:
        sock = None
        with self._cv:
            slot.busy = False
            if dead or self._closed or slot.dead:
                slot.dead = True
                sock, slot.sock = slot.sock, None
                if slot in self._slots:
                    self._slots.remove(slot)
            self._cv.notify_all()
        _close_sock(sock)

    def idle_count(self) -> int:
        with self._cv:
            return sum(1 for s in self._slots if not s.busy and not s.dead)

    def close(self) -> None:
        """Close EVERY tracked socket, including checked-out ones: an
        in-flight pull fails fast with a connection error instead of
        holding a leaked fd. Busy slots fully retire at their checkin."""
        with self._cv:
            self._closed = True
            socks = [s.sock for s in self._slots if s.sock is not None]
            for s in self._slots:
                s.dead = True
                if not s.busy:
                    s.sock = None
            self._slots = [s for s in self._slots if s.busy]
            self._cv.notify_all()
        for sock in socks:
            _close_sock(sock)


class ObjectTransferClient:
    """Chunked puller with a small per-address connection pool (the
    reference pools object-manager RPC channels likewise; here the pool
    additionally lets concurrent pulls from one holder overlap)."""

    def __init__(self, chunk_bytes: Optional[int] = None,
                 pool_conns: Optional[int] = None,
                 chunk_window: Optional[int] = None):
        self.chunk_bytes = int(chunk_bytes if chunk_bytes is not None
                               else config.object_transfer_chunk_bytes)
        self.pool_conns = int(pool_conns if pool_conns is not None
                              else config.object_transfer_pool_conns)
        self.chunk_window = max(1, int(
            chunk_window if chunk_window is not None
            else config.object_transfer_chunk_window))
        self._pools: Dict[str, _ConnPool] = {}
        self._global_lock = threading.Lock()
        self._next_id = 0
        self._closed = False
        self._plane = _NativePlane("native-transfer-client",
                                   _make_client_native)
        self._inflight: set = set()  # sids being pulled by THIS client
        self._inflight_lock = threading.Lock()
        # same-host staging arenas attached by name (zero-copy handoff);
        # None marks an arena that failed to attach, so we don't re-dial it
        self._arenas: Dict[str, Any] = {}
        self._arena_lock = threading.Lock()
        # flow-accounting identity of the pulling side; empty means the
        # process-wide node id (set per-client in tests/benches that run
        # several logical pullers in one process)
        self.local_node = ""

    def _flow_dst(self) -> str:
        return self.local_node or object_ledger.local_node()

    def _pool(self, address: str) -> _ConnPool:
        with self._global_lock:
            if self._closed:
                raise ObjectPullConnectionError("transfer client closed")
            pool = self._pools.get(address)
            if pool is None:
                pool = self._pools[address] = _ConnPool(
                    address, self.pool_conns)
            return pool

    def _new_id(self) -> int:
        with self._global_lock:
            self._next_id += 1
            return self._next_id

    def _request_on(self, sock: socket.socket, address: str,
                    method: str, *args) -> Any:
        """One request/response round trip on an exclusively-held socket."""
        req_id = self._new_id()
        try:
            send_msg(sock, MSG_REQUEST,
                     {"id": req_id, "method": method, "args": args})
            msg_type, resp = recv_msg(sock)
        except (WireError, OSError) as e:
            raise ObjectPullConnectionError(
                f"transfer connection to {address} lost: {e}")
        if msg_type != MSG_RESPONSE or resp.get("id") != req_id:
            raise ObjectPullConnectionError(
                f"bad transfer response from {address}")
        if not resp.get("ok"):
            raise ObjectPullError(resp.get("error", "pull failed"))
        return resp["value"]

    def _call(self, address: str, method: str, *args) -> Any:
        slot = self._pool(address).checkout()
        dead = True
        try:
            value = self._request_on(slot.sock, address, method, *args)
            dead = False
            return value
        except ObjectPullError as e:
            # app-level refusal: the connection itself is fine
            dead = isinstance(e, ObjectPullConnectionError)
            raise
        finally:
            self._pool(address).checkin(slot, dead=dead)

    def _drop(self, address: str) -> None:
        """Retire every pooled connection for an address (holder restarted
        or unreachable); the next call dials fresh."""
        with self._global_lock:
            pool = self._pools.pop(address, None)
        if pool is not None:
            pool.close()

    def pull(self, address: str, object_id, raw: bool = False,
             peers: Sequence[str] = (), src_node: str = "") -> Any:
        """Pull one object from the holder at `address`; returns the value
        (raw=True: the sealed payload, store.get_raw parity).

        Fast path: one "stage" round trip on the control connection, then
        the C++ plane streams the blob arena-to-arena (_shm/transfer.cc)
        and the value unpickles from a zero-copy view. Fallback: ~1MB
        chunks, pipelined `chunk_window` requests deep per connection;
        large fallback pulls stripe byte ranges across `peers` that also
        hold the object (pull_from_any passes the ranked remainder)."""
        oid_hex = object_id.hex() if hasattr(object_id, "hex") else str(object_id)
        src_node = src_node or object_ledger.peer_node(address)
        t0 = time.monotonic()
        with _pull_inflight.track():
            shm = None
            try:
                staged = self._call(address, "stage", oid_hex, raw)
                total, native_port = staged["size"], staged["native_port"]
                shm = staged.get("shm")
            except ObjectPullError as e:
                if "unknown method" not in str(e):
                    raise
                # holder predates the staged protocol: chunked via "meta"
                total, native_port = self._call(address, "meta", oid_hex,
                                                raw), None
            if (shm is not None and config.object_transfer_shm_handoff
                    and shm.get("token") == _host_token()):
                # same host: map the holder's staging arena and decode in
                # place — zero socket bytes, so none of the transfer
                # counters/flow edges move (the flow matrix showing no
                # self-edge traffic is the regression-tested contract)
                value = self._pull_shm(shm.get("arena"), oid_hex, raw)
                if value is not _SHM_MISS:
                    _pull_seconds.observe(time.monotonic() - t0,
                                          {"path": "shm"})
                    return value
            if native_port is not None:
                value = self._pull_native(address, native_port, oid_hex, raw,
                                          total, src_node)
                if value is not _NATIVE_MISS:
                    _pull_seconds.observe(time.monotonic() - t0,
                                          {"path": "native"})
                    return value
            blob = None
            if (peers and total >= config.object_transfer_stripe_min_bytes):
                blob = self._pull_striped(address, peers, oid_hex, raw, total,
                                          src_node)
            if blob is None:
                blob = self._pull_chunked(address, oid_hex, raw, 0, total,
                                          src_node=src_node)
            _pull_seconds.observe(time.monotonic() - t0, {"path": "chunked"})
            return _decode_blob(blob)

    def _attach_arena(self, name: str):
        """Attach (once) a same-host holder's staging arena by name."""
        from .shm_store import ShmObjectStore

        with self._arena_lock:
            if name in self._arenas:
                return self._arenas[name]
        try:
            store = ShmObjectStore(name, create=False)
        except Exception:  # noqa: BLE001 — arena gone/renamed: socket path
            store = None
        with self._arena_lock:
            return self._arenas.setdefault(name, store)

    def _pull_shm(self, arena_name: Optional[str], oid_hex: str,
                  raw: bool) -> Any:
        """Zero-socket same-host pull: read the staged blob straight out
        of the holder's shm arena. Buffers are copied out of the mapping
        during decode (the arena may evict the entry after release), but
        no byte ever crosses a socket. Returns _SHM_MISS when the arena
        or the staged entry is unavailable."""
        if not arena_name:
            return _SHM_MISS
        store = self._attach_arena(arena_name)
        if store is None:
            return _SHM_MISS
        try:
            sid = _stage_id(ObjectID.from_hex(oid_hex).binary(), raw)
        except (ValueError, TypeError):
            return _SHM_MISS
        try:
            view = store.get_view(sid)
        except Exception:  # noqa: BLE001 — holder tore the arena down
            return _SHM_MISS
        if view is None:
            return _SHM_MISS
        try:
            return _decode_blob(view, zero_copy=False)
        finally:
            try:
                store.release(sid)
            except Exception:  # noqa: BLE001 — release is best-effort
                pass

    def _pull_chunked(self, address: str, oid_hex: str, raw: bool,
                      start: int, end: int, src_node: str = "",
                      flow_path: str = "chunked", sink=None, commit=None):
        """Pull bytes [start, end) as pipelined chunk requests: a window of
        chunk_window requests stays outstanding on one exclusively-held
        connection instead of one synchronous round trip per ~1MB. The
        server handles a connection's requests strictly in order, so
        responses return in request order and match by id.

        Chunks ride the MSG_BLOB lane: ONE chunk_stream request makes the
        server push the whole range as blob frames (header + memoryview
        scatter-gather per chunk), each payload recv_into'd straight into
        the destination buffer — no per-chunk request, pickling, or
        reassembly copy on either side; TCP flow control paces the
        stream.

        `sink=(buf, base)` lands blob offset `off` at buf[off - base];
        relay partials and stripe lanes share one caller-owned buffer
        this way (default: a fresh buffer covering [start, end)).
        `commit(upto)` fires after each landed chunk with the contiguous
        high-water offset — relay holders publish it to parked readers.
        Returns the destination buffer."""
        pool = self._pool(address)
        slot = pool.checkout()
        dead = True
        if sink is None:
            buf, base = _alloc_buf(end - start), start
        else:
            buf, base = sink
        mv = memoryview(buf)
        src_node = src_node or object_ledger.peer_node(address)
        flow_dst = self._flow_dst()
        req_id = self._new_id()
        expect = start

        def sink_for(rid: int, off: int, n: int) -> memoryview:
            if rid != req_id or off != expect or \
                    n != min(self.chunk_bytes, end - off):
                raise WireError(
                    f"blob stream out of order from {address}: frame "
                    f"(id {rid}, [{off}, {off + n})) at offset {expect}")
            return mv[off - base:off - base + n]

        # flow rows batch across chunks (flushed every flow_every bytes
        # and at stream end) — one ledger insert per ~8 chunks keeps the
        # edge-byte sums exact while pricing record_flow out of the
        # per-chunk hot path
        flow_pending = 0
        flow_every = 8 * self.chunk_bytes
        try:
            sock = slot.sock
            send_msg(sock, MSG_REQUEST,
                     {"id": req_id, "method": "chunk_stream",
                      "args": (oid_hex, start, end, self.chunk_bytes, raw)})
            while True:
                msg_type, payload = recv_frame_into(sock, sink_for)
                if msg_type == MSG_RESPONSE:
                    if payload.get("id") != req_id:
                        raise ObjectPullConnectionError(
                            f"bad transfer response from {address}")
                    if not payload.get("ok"):
                        # app-level refusal: the stream closed cleanly,
                        # the connection stays usable
                        dead = False
                        raise ObjectPullError(
                            payload.get("error", "pull failed"))
                    break
                if msg_type != MSG_BLOB:
                    raise ObjectPullConnectionError(
                        f"bad transfer response from {address}")
                _, off, n = payload
                expect = off + n
                _pulled_chunks.inc()
                _pulled_bytes.inc(n)
                _pull_bytes.inc(n)
                flow_pending += n
                if flow_pending >= flow_every:
                    object_ledger.record_flow(src_node, flow_dst,
                                              flow_path, flow_pending)
                    flow_pending = 0
                if commit is not None:
                    commit(expect)
            if expect != end:
                raise ObjectPullError(
                    f"short stream at {expect}/{end} for {oid_hex}")
            dead = False
            object_ledger.record_flow(src_node, flow_dst, flow_path,
                                      flow_pending, transfers=1)
            flow_pending = 0
        except (WireError, OSError) as e:
            raise ObjectPullConnectionError(
                f"transfer connection to {address} lost: {e}")
        finally:
            if flow_pending:
                # failed mid-stream: the landed bytes were counted, so
                # the ledger must see them too (exact conservation)
                object_ledger.record_flow(src_node, flow_dst, flow_path,
                                          flow_pending)
            pool.checkin(slot, dead=dead)
        return buf

    def _pull_striped(self, address: str, peers: Sequence[str],
                      oid_hex: str, raw: bool, total: int,
                      src_node: str = "") -> Optional[bytes]:
        """Stripe a large chunked pull across holders: confirmed peers each
        serve a contiguous byte range in parallel. Returns None when no
        peer confirms (caller falls back to the single-holder path); any
        stripe failure also falls back — striping is an optimization,
        never a correctness dependency."""
        holders = [address]
        max_stripes = max(1, int(config.object_transfer_max_stripes))
        for peer in peers:
            if len(holders) >= max_stripes:
                break  # diminishing returns past a few stripes
            try:
                if self._call(peer, "contains", oid_hex):
                    holders.append(peer)
            except ObjectPullError:
                continue
        if len(holders) < 2:
            return None
        # contiguous ranges, chunk-aligned so stripes pipeline internally
        n = len(holders)
        per = ((total // n) // self.chunk_bytes + 1) * self.chunk_bytes
        ranges = []
        off = 0
        for h in holders:
            if off >= total:
                break
            ranges.append((h, off, min(off + per, total)))
            off += per
        # every stripe recv_intos its range of ONE shared buffer — the
        # lanes never overlap, so no reassembly join afterwards
        buf = _alloc_buf(total)
        done: List[bool] = [False] * len(ranges)
        errors: List[Optional[BaseException]] = [None] * len(ranges)

        def work(i: int, holder: str, lo: int, hi: int) -> None:
            try:
                # each stripe is its own edge: bytes flow from the stripe's
                # holder, not from the primary address
                src = src_node if holder == address else \
                    object_ledger.peer_node(holder)
                self._pull_chunked(holder, oid_hex, raw, lo, hi,
                                   src_node=src, flow_path="stripe",
                                   sink=(buf, 0))
                done[i] = True
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors[i] = e

        threads = [threading.Thread(
            target=work, args=(i, h, lo, hi), daemon=True,
            name=f"stripe-{i}") for i, (h, lo, hi) in enumerate(ranges)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if not all(done):
            failed = next((e for e in errors if e is not None), None)
            logger.debug("striped pull of %s fell back to one holder: %r",
                         oid_hex[:16], failed)
            return None
        return buf

    def _pull_native(self, address: str, native_port: int, oid_hex: str,
                     raw: bool, total: int, src_node: str = "") -> Any:
        """One native arena-to-arena pull; returns _NATIVE_MISS to send the
        caller down the chunked path (never raises for availability-class
        failures — the chunked path is the answer to all of them)."""
        from .shm_store import PullRejected, ShmStoreError

        self._plane.start_async()  # idempotent; first pull rides chunks
        native, staging = self._plane.acquire()
        if native is None:
            return _NATIVE_MISS
        host = address.rpartition(":")[0]
        try:
            sid = _stage_id(ObjectID.from_hex(oid_hex).binary(), raw)
        except (ValueError, TypeError):
            self._plane.release()
            return _NATIVE_MISS
        try:
            transferred = False
            if not staging.contains(sid):
                with self._inflight_lock:
                    winner = sid not in self._inflight
                    if winner:
                        self._inflight.add(sid)
                if not winner:
                    # another thread of THIS client is pulling the same
                    # object (clients never share staging arenas, so this
                    # is the only duplicate source): wait for it to finish
                    # rather than re-downloading the same bytes
                    deadline = time.monotonic() + 30.0
                    while time.monotonic() < deadline:
                        with self._inflight_lock:
                            if sid not in self._inflight:
                                break
                        time.sleep(0.01)
                    if not staging.contains(sid):
                        return _NATIVE_MISS  # winner failed; use chunks
                else:
                    try:
                        n = native.pull_into(host, native_port, sid, staging)
                        if n is None:
                            # staged blob evicted between stage and pull:
                            # restage once (the holder re-pins it), then
                            # give up to chunks. The holder may have
                            # restarted its native plane (or resealed a
                            # different-size blob) since the first stage —
                            # retry against the RESPONSE's port/size, not
                            # the stale ones
                            restaged = self._call(address, "stage", oid_hex,
                                                  raw)
                            native_port = restaged.get("native_port")
                            total = restaged.get("size", total)
                            if native_port is None:
                                return _NATIVE_MISS
                            n = native.pull_into(host, native_port, sid,
                                                 staging)
                            if n is None:
                                return _NATIVE_MISS
                        transferred = True
                    finally:
                        with self._inflight_lock:
                            self._inflight.discard(sid)
            view = staging.get_view(sid)
            if view is None:
                return _NATIVE_MISS  # evicted locally before the read
            try:
                value = _decode_blob(view, zero_copy=False)
            finally:
                # release the pin but keep the sealed blob: concurrent and
                # repeat pulls of the same (immutable) object hit it here,
                # and the arena's LRU/slot eviction bounds total residency
                staging.release(sid)
            if transferred:  # count only bytes that crossed the network
                _pulled_chunks.inc()
                _pulled_bytes.inc(total)
                _pull_bytes.inc(total)
                object_ledger.record_flow(
                    src_node or object_ledger.peer_node(address),
                    self._flow_dst(), "native", total, transfers=1)
            return value
        except PullRejected:
            return _NATIVE_MISS  # does not fit the local arena
        except ShmStoreError as e:
            logger.warning("native pull from %s:%d failed (%s); "
                           "falling back to chunks", host, native_port, e)
            return _NATIVE_MISS
        finally:
            self._plane.release()

    def close(self) -> None:
        """Close every pooled connection (including ones held by in-flight
        pulls, which fail fast with a connection error) and tear down the
        native plane. Safe to race with concurrent pulls: each socket is
        tracked in exactly one pool slot from dial to close, so nothing
        leaks even if a pull checked its socket out before we got here."""
        with self._global_lock:
            self._closed = True
            pools = list(self._pools.values())
            self._pools.clear()
        for pool in pools:
            pool.close()
        # arena attachments: DROP the references, never close() them here.
        # A concurrent _pull_shm may be mid-read of a view into the
        # mapping; munmapping under it is a segfault. The holder owns the
        # segment — each attachment unmaps via __del__ once its last
        # in-flight reader drops the reference.
        with self._arena_lock:
            self._arenas.clear()
        self._plane.teardown()


def serve_object_transfer(runtime, host: str = "127.0.0.1",
                          port: int = 0) -> ObjectTransferServer:
    """Start the transfer plane for a Runtime's driver store and advertise
    the address in the control plane KV (`object_transfer/{node_id}`), so
    remote runtimes sharing the control plane can locate the holder."""
    store = runtime.driver_agent.store
    server = ObjectTransferServer(store, host, port)
    node_hex = runtime.driver_agent.node_id.hex()
    object_ledger.note_peer(server.address, node_hex)
    try:
        runtime.control_plane.kv_put(KV_PREFIX + node_hex, server.address)
        runtime.control_plane.kv_put(HOST_PREFIX + node_hex, _host_token())
    except Exception:  # noqa: BLE001 — advertising is best-effort
        logger.warning("could not advertise transfer address", exc_info=True)
    server.start_load_gossip(runtime.control_plane, node_hex)
    return server


_default_client: Optional[ObjectTransferClient] = None
_default_client_lock = threading.Lock()


def _shared_client() -> ObjectTransferClient:
    """Process-wide default puller. Long-lived so the native path's
    connections and staging arena amortize across calls — a per-call
    client would pay arena setup/teardown per object."""
    global _default_client
    with _default_client_lock:
        if _default_client is None:
            _default_client = ObjectTransferClient()
        return _default_client


def _holder_tier(control_plane, node_hex: str, local_token: str,
                 local_slice) -> int:
    """Locality tier of a holder: 0 same host (shm distance), 1 same
    slice/pod (ICI-adjacent hosts), 2 everything else. Missing topology
    info degrades to tier 2 — ranking is advisory, never correctness."""
    try:
        token = control_plane.kv_get(HOST_PREFIX + node_hex)
        if token and token == local_token:
            return 0
    except Exception:  # noqa: BLE001 — tokens are advisory
        pass
    if local_slice is not None:
        try:
            from .ids import NodeID

            info = control_plane.get_node(NodeID.from_hex(node_hex))
            if info is not None and info.slice_id == local_slice:
                return 1
        except Exception:  # noqa: BLE001 — topology is advisory
            pass
    return 2


def _ranked_holders(control_plane, local_token: Optional[str] = None,
                    local_slice=None) -> List[str]:
    """Advertised transfer addresses, nearest-and-least-loaded first:
    locality tier (same host < same slice < cross-pod, from the
    `object_transfer_host/*` tokens and node slice ids) then each
    holder's gossiped outstanding-request count (`object_transfer_load/*`
    KV, published by start_load_gossip); holders that never gossiped rank
    as idle, preserving the old iteration order among ties."""
    token = local_token if local_token is not None else _host_token()
    ranked: List[Tuple[int, float, int, str]] = []
    for idx, key in enumerate(control_plane.kv_keys(KV_PREFIX)):
        address = control_plane.kv_get(key)
        if not address:
            continue
        node_hex = key[len(KV_PREFIX):]
        object_ledger.note_peer(address, node_hex)
        load = 0.0
        try:
            raw = control_plane.kv_get(LOAD_PREFIX + node_hex)
            if raw:
                load = float(raw)
        except Exception:  # noqa: BLE001 — load is advisory
            pass
        tier = _holder_tier(control_plane, node_hex, token, local_slice)
        ranked.append((tier, load, idx, address))
    ranked.sort()
    return [addr for _, _, _, addr in ranked]


def _claim_relay_slot(control_plane, oid_hex: str, address: str,
                      label: str, node_hex: str,
                      max_slots: int = 4096) -> Optional[int]:
    """Atomically claim the lowest free relay-tree slot for this puller
    (kv_put overwrite=False is the compare-and-set). The claim value
    carries the puller's transfer address (children dial it), its flow
    label (children attribute the edge), and its node id (mark_node_dead
    purges a dead node's claims by this suffix)."""
    value = f"{address}|{label}|{node_hex}"
    slot = 0
    while slot < max_slots:
        key = f"{RELAY_PREFIX}{oid_hex}/{slot:06d}"
        try:
            if control_plane.kv_put(key, value, overwrite=False):
                return slot
        except TypeError:
            return None  # control plane without CAS puts: no relay
        slot += 1
    return None


def _relay_parent(control_plane, oid_hex: str, slot: int,
                  fanout: int) -> Optional[Tuple[str, str, str]]:
    """-> (address, flow_label, node_hex) of slot's tree parent, or None
    for root-tier slots (they pull from the sealed holders) and for
    purged parents (dead node: the child falls back to sealed holders)."""
    if slot < fanout:
        return None
    parent = (slot - fanout) // fanout
    try:
        val = control_plane.kv_get(f"{RELAY_PREFIX}{oid_hex}/{parent:06d}")
    except Exception:  # noqa: BLE001 — control plane hiccup: no parent
        return None
    if not val:
        return None
    address, _, rest = str(val).partition("|")
    label, _, node_hex = rest.partition("|")
    if not address:
        return None
    return address, label, node_hex


def purge_relay_claims(oid_hex: str, control_plane) -> None:
    """Best-effort removal of an object's relay-slot claims (broadcast
    epilogue / bench round teardown — claims are only needed while late
    pullers may still resolve their parent)."""
    try:
        for key in control_plane.kv_keys(f"{RELAY_PREFIX}{oid_hex}/"):
            control_plane.kv_del(key)
    except Exception:  # noqa: BLE001 — stale claims only waste KV bytes
        pass


def _relay_pull(control_plane, client, object_id, holders, relay_server,
                cache_store, on_cached, node_hex: str = "") -> Any:
    """Join the object's relay tree: claim a slot, register a _Partial on
    this node's transfer server (so downstream pullers stream our
    committed prefix mid-transfer), and pull from the claimed parent —
    falling back through the sealed holders, resuming from the committed
    offset, if the parent dies. Returns _RELAY_MISS whenever the relay
    is not worth it or not possible; the caller runs the flat path."""
    oid_hex = object_id.hex()
    if not holders:
        return _RELAY_MISS
    try:
        staged = client._call(holders[0], "stage", oid_hex, True)
        total = staged["size"]
        shm = staged.get("shm")
    except ObjectPullError:
        return _RELAY_MISS
    except (KeyError, TypeError):
        return _RELAY_MISS  # pre-staged-protocol holder
    if (shm is not None and config.object_transfer_shm_handoff
            and shm.get("token") == _host_token()):
        return _RELAY_MISS  # same host: the zero-copy handoff wins
    if total < int(config.object_relay_min_bytes):
        return _RELAY_MISS
    fanout = max(1, int(config.object_broadcast_fanout))
    label = client._flow_dst()
    # partial BEFORE claim: the instant the claim lands, children may
    # dial this node — the partial must already be there to park on
    partial = relay_server.begin_partial(oid_hex, True, total)
    if partial is None:
        return _RELAY_MISS  # another pull on this node already feeds it
    slot = _claim_relay_slot(control_plane, oid_hex, relay_server.address,
                             label, node_hex or label)
    if slot is None:
        relay_server.fail_partial(oid_hex, True, "no relay slot")
        return _RELAY_MISS
    # candidates: tree parent first (its partial streams to us chunk-by-
    # chunk as it lands), then the sealed holders nearest-first — never
    # ourselves (a self-pull would park on our own partial forever)
    candidates: List[Tuple[str, str, str]] = []
    parent = _relay_parent(control_plane, oid_hex, slot, fanout)
    if parent is not None and parent[0] != relay_server.address:
        candidates.append(("relay",) + parent[:2])
    for addr in holders:
        if addr != relay_server.address:
            candidates.append(("chunked", addr, ""))
    last_error: Optional[BaseException] = None
    for flow_path, address, src_label in candidates:
        start = partial.committed  # resume: chunks commit atomically
        try:
            client._pull_chunked(
                address, oid_hex, True, start, total,
                src_node=src_label or object_ledger.peer_node(address),
                flow_path=flow_path, sink=(partial.buf, 0),
                commit=partial.commit)
        except ObjectPullError as e:
            last_error = e
            continue
        value = _decode_blob(memoryview(partial.buf))
        relay_server.finish_partial(oid_hex, True)
        try:
            cache_store.put(object_id, value)
            if on_cached is not None:
                on_cached(object_id)
        except Exception:  # noqa: BLE001 — caching is best-effort
            logger.debug("pull-through cache of %s failed", object_id,
                         exc_info=True)
        return value.load() if isinstance(value, SealedBytes) else value
    # every candidate failed: release the slot and wake parked children
    # with an error so they fall back to surviving holders
    relay_server.fail_partial(oid_hex, True,
                              f"relay pull failed: {last_error!r}")
    try:
        control_plane.kv_del(f"{RELAY_PREFIX}{oid_hex}/{slot:06d}")
    except Exception:  # noqa: BLE001 — claim GC is best-effort
        pass
    return _RELAY_MISS


def pull_from_any(control_plane, object_id,
                  client: Optional[ObjectTransferClient] = None,
                  cache_store=None, on_cached=None,
                  relay_server: Optional[ObjectTransferServer] = None,
                  node_hex: str = "") -> Any:
    """Resolve `object_transfer/*` advertisements from the control plane
    and try holders nearest-first (same host, then same slice, then by
    ascending gossiped load) until one serves the object. The unranked
    remainder is offered to the client as striping peers for large
    chunked pulls.

    With `cache_store`, the pull fetches the sealed payload and seals it
    into that (local) store before returning the loaded value — the
    pull-through replica. `on_cached(object_id)` then fires so the caller
    can register the new location in its directory; both steps are
    best-effort and never fail the get (objects are immutable once sealed,
    so a cached replica can never go stale).

    With `relay_server` (this node's own ObjectTransferServer), large
    pulls join a collective relay tree: the puller claims a tree slot in
    the KV, streams from its parent's committed prefix, and serves its
    own partial to downstream pullers mid-transfer — N concurrent
    pullers disseminate as a pipelined tree instead of N independent
    full pulls from one sender."""
    from ..util import tracing

    client = client or _shared_client()
    want_raw = cache_store is not None
    holders = _ranked_holders(control_plane)
    with tracing.span_if_traced("object_pull",
                                {"object_id": object_id.hex()[:16],
                                 "holders": len(holders)}):
        if (relay_server is not None and want_raw
                and config.object_broadcast_relay):
            value = _relay_pull(control_plane, client, object_id, holders,
                                relay_server, cache_store, on_cached,
                                node_hex=node_hex)
            if value is not _RELAY_MISS:
                return value
        return _pull_from_holders(client, object_id, want_raw, holders,
                                  cache_store, on_cached)


def _pull_from_holders(client, object_id, want_raw, holders,
                       cache_store, on_cached) -> Any:
    errors = []
    for pos, address in enumerate(holders):
        peers = holders[pos + 1:] + holders[:pos]
        # two attempts per holder, but ONLY for transport-class failures:
        # the shared client pools connections, so the first failure after
        # a holder restart (or an idle conn being dropped) is just the
        # stale socket — the client drops it and the retry dials fresh. An
        # application-level refusal ("object not here") is the holder's
        # real answer; re-asking the same holder just doubles pull latency
        # across a large fleet.
        for attempt in (0, 1):
            try:
                value = client.pull(address, object_id, raw=want_raw,
                                    peers=peers)
            except ObjectPullConnectionError as e:
                if attempt == 1:
                    errors.append((address, str(e)))
                continue
            except ObjectPullError as e:
                errors.append((address, str(e)))
                break
            if not want_raw:
                return value
            try:
                cache_store.put(object_id, value)
                if on_cached is not None:
                    on_cached(object_id)
            except Exception:  # noqa: BLE001 — caching is best-effort
                logger.debug("pull-through cache of %s failed", object_id,
                             exc_info=True)
            return value.load() if isinstance(value, SealedBytes) else value
    raise ObjectPullError(
        f"no advertised holder served {object_id}: {errors}"
    )
