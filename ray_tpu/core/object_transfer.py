"""Object transfer plane: chunked pull of sealed objects between runtimes.

Reference analogue: `src/ray/object_manager/` — `PullManager`/`PushManager`
move plasma objects between nodes as ~1MB chunks over a dedicated gRPC
service (`object_manager.proto :: ObjectManagerService`). Same shape here:
each runtime can serve its object store on a TCP port; a remote runtime
locates the holder (control-plane KV carries `object_transfer/{node}` →
address) and pulls the object as fixed-size chunks, reassembling and
sealing it into its own store. Pull-based (the receiver drives), like the
reference — admission control stays with the consumer.

Intra-slice device arrays never cross this plane: jax arrays travel as
compiled collectives over ICI. This is the HOST object plane (CPU tensors,
rollouts, checkpoint shards, pickled results) between loosely-coupled
runtimes.
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import threading
from typing import Any, Dict, Optional, Tuple

from .ids import ObjectID
from .logging import get_logger
from .metrics import Counter
from .wire import MSG_REQUEST, MSG_RESPONSE, WireError, recv_msg, send_msg

logger = get_logger("object_transfer")

DEFAULT_CHUNK_BYTES = 1 << 20  # ~1MB, the reference's chunk size

KV_PREFIX = "object_transfer/"  # control-plane KV key prefix for addresses

_pulled_chunks = Counter(
    "object_transfer_chunks_pulled", "Chunks pulled from remote runtimes."
)
_pulled_bytes = Counter(
    "object_transfer_bytes_pulled", "Bytes pulled from remote runtimes."
)


class ObjectPullError(RuntimeError):
    pass


def _serialize_for_wire(value: Any) -> bytes:
    """One flat payload per object; cloudpickle for closures/lambdas."""
    try:
        return pickle.dumps(value, protocol=5)
    except Exception:
        import cloudpickle

        return cloudpickle.dumps(value, protocol=5)


class _TransferHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: "ObjectTransferServer" = self.server  # type: ignore[assignment]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                msg_type, req = recv_msg(sock)
                if msg_type != MSG_REQUEST:
                    raise WireError(f"unexpected message type {msg_type}")
                try:
                    resp = self._dispatch(server, req)
                except Exception as e:  # noqa: BLE001 — serialized to caller
                    resp = {"id": req.get("id"), "ok": False, "error": repr(e)}
                send_msg(sock, MSG_RESPONSE, resp)
        except (WireError, OSError):
            pass  # puller disconnected

    def _dispatch(self, server: "ObjectTransferServer", req: dict) -> dict:
        method = req.get("method")
        # args may carry a trailing raw flag: raw=True ships the SEALED
        # payload (SealedBytes pickled as-is) so sealing survives the hop
        # (store.get_raw parity for cross-runtime pulls)
        if method == "meta":
            oid_hex, *rest = req["args"]
            blob = server._blob_for(oid_hex, raw=bool(rest and rest[0]))
            return {"id": req["id"], "ok": True, "value": len(blob)}
        if method == "chunk":
            oid_hex, offset, length, *rest = req["args"]
            blob = server._blob_for(oid_hex, raw=bool(rest and rest[0]))
            return {"id": req["id"], "ok": True,
                    "value": bytes(blob[offset:offset + length])}
        if method == "contains":
            (oid_hex,) = req["args"]
            oid = ObjectID.from_hex(oid_hex)
            return {"id": req["id"], "ok": True,
                    "value": bool(server._store.contains(oid))}
        raise WireError(f"unknown method {method!r}")


class ObjectTransferServer(socketserver.ThreadingTCPServer):
    """Serves one runtime's object store for remote pulls.

    The serialized blob for an object is cached per object id while any
    pull is in flight (pulls are chunked across many requests), and
    dropped once the store drops the object."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, store, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _TransferHandler)
        self._store = store
        self._blob_cache: Dict[Tuple[str, bool], bytes] = {}
        self._cache_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="object-transfer"
        )
        self._thread.start()
        logger.info("object transfer plane on %s:%d", *self.server_address)

    @property
    def address(self) -> str:
        host, port = self.server_address
        return f"{host}:{port}"

    def _blob_for(self, oid_hex: str, raw: bool = False) -> bytes:
        key = (oid_hex, raw)
        with self._cache_lock:
            blob = self._blob_cache.get(key)
            if blob is not None:
                return blob
        oid = ObjectID.from_hex(oid_hex)
        if not self._store.contains(oid):
            raise KeyError(f"object {oid_hex} not in local store")
        if raw:
            value = self._store.get_raw(oid, timeout=0.0)
        else:
            value = self._store.get(oid, timeout=0.0)
        blob = _serialize_for_wire(value)
        with self._cache_lock:
            # bound the cache: drop the oldest entries past 64
            if len(self._blob_cache) >= 64:
                self._blob_cache.pop(next(iter(self._blob_cache)))
            self._blob_cache[key] = blob
        return blob

    def stop(self) -> None:
        self.shutdown()
        self.server_close()


class ObjectTransferClient:
    """Chunked puller. One connection per remote address, reused across
    pulls (the reference pools object-manager RPC channels likewise)."""

    def __init__(self, chunk_bytes: int = DEFAULT_CHUNK_BYTES):
        self.chunk_bytes = int(chunk_bytes)
        self._conns: Dict[str, socket.socket] = {}
        self._locks: Dict[str, threading.Lock] = {}
        self._global_lock = threading.Lock()
        self._next_id = 0

    def _conn(self, address: str) -> Tuple[socket.socket, threading.Lock]:
        with self._global_lock:
            sock = self._conns.get(address)
            if sock is None:
                host, _, port = address.rpartition(":")
                sock = socket.create_connection((host, int(port)), timeout=30.0)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._conns[address] = sock
                self._locks[address] = threading.Lock()
            return sock, self._locks[address]

    def _call(self, address: str, method: str, *args) -> Any:
        sock, lock = self._conn(address)
        with lock:
            with self._global_lock:
                self._next_id += 1
                req_id = self._next_id
            try:
                send_msg(sock, MSG_REQUEST,
                         {"id": req_id, "method": method, "args": args})
                msg_type, resp = recv_msg(sock)
            except (WireError, OSError) as e:
                self._drop(address)
                raise ObjectPullError(f"transfer connection to {address} lost: {e}")
        if msg_type != MSG_RESPONSE or resp.get("id") != req_id:
            self._drop(address)
            raise ObjectPullError(f"bad transfer response from {address}")
        if not resp.get("ok"):
            raise ObjectPullError(resp.get("error", "pull failed"))
        return resp["value"]

    def _drop(self, address: str) -> None:
        with self._global_lock:
            sock = self._conns.pop(address, None)
            self._locks.pop(address, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def pull(self, address: str, object_id, raw: bool = False) -> Any:
        """Pull one object from the holder at `address`; returns the value
        (raw=True: the sealed payload, store.get_raw parity).

        Chunks sequentially over one connection: the transfer is bandwidth
        -bound, not latency-bound, at ~1MB chunks (matching the reference's
        ObjectBufferPool sizing)."""
        oid_hex = object_id.hex() if hasattr(object_id, "hex") else str(object_id)
        total = self._call(address, "meta", oid_hex, raw)
        parts = []
        offset = 0
        while offset < total:
            length = min(self.chunk_bytes, total - offset)
            chunk = self._call(address, "chunk", oid_hex, offset, length, raw)
            if not chunk:
                raise ObjectPullError(
                    f"short read at {offset}/{total} for {oid_hex}"
                )
            parts.append(chunk)
            offset += len(chunk)
            _pulled_chunks.inc()
            _pulled_bytes.inc(len(chunk))
        return pickle.loads(b"".join(parts))

    def close(self) -> None:
        with self._global_lock:
            conns = list(self._conns.values())
            self._conns.clear()
            self._locks.clear()
        for sock in conns:
            try:
                sock.close()
            except OSError:
                pass


def serve_object_transfer(runtime, host: str = "127.0.0.1",
                          port: int = 0) -> ObjectTransferServer:
    """Start the transfer plane for a Runtime's driver store and advertise
    the address in the control plane KV (`object_transfer/{node_id}`), so
    remote runtimes sharing the control plane can locate the holder."""
    store = runtime.driver_agent.store
    server = ObjectTransferServer(store, host, port)
    try:
        runtime.control_plane.kv_put(
            KV_PREFIX + runtime.driver_agent.node_id.hex(), server.address
        )
    except Exception:  # noqa: BLE001 — advertising is best-effort
        logger.warning("could not advertise transfer address", exc_info=True)
    return server


def pull_from_any(control_plane, object_id,
                  client: Optional[ObjectTransferClient] = None) -> Any:
    """Resolve `object_transfer/*` advertisements from the control plane
    and try each holder until one serves the object."""
    own = client is None
    client = client or ObjectTransferClient()
    try:
        errors = []
        for key in control_plane.kv_keys(KV_PREFIX):
            address = control_plane.kv_get(key)
            if not address:
                continue
            try:
                return client.pull(address, object_id)
            except ObjectPullError as e:
                errors.append((address, str(e)))
        raise ObjectPullError(
            f"no advertised holder served {object_id}: {errors}"
        )
    finally:
        if own:
            client.close()
