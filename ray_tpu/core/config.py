"""Central flag registry.

Mirrors the reference's single-source-of-truth flag system (upstream ray
`src/ray/common/ray_config_def.h :: RAY_CONFIG` X-macro list): every runtime
knob is declared once here with a type, default and doc; values resolve with
precedence  init(system_config=...)  >  env RAY_TPU_<NAME>  >  default.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Callable, Dict, Optional

__all__ = ["Config", "config", "declare", "describe_flags"]

_TRUE = {"1", "true", "yes", "on"}
_FALSE = {"0", "false", "no", "off"}


def _parse_bool(s: str) -> bool:
    low = s.strip().lower()
    if low in _TRUE:
        return True
    if low in _FALSE:
        return False
    raise ValueError(f"not a boolean: {s!r}")


@dataclasses.dataclass(frozen=True)
class _Field:
    name: str
    default: Any
    doc: str
    parser: Callable[[str], Any]


_REGISTRY: Dict[str, _Field] = {}


def declare(name: str, default: Any, doc: str = "") -> None:
    """Declare a config flag. Types are inferred from the default."""
    if name in _REGISTRY:
        raise ValueError(f"duplicate config flag: {name}")
    if isinstance(default, bool):
        parser: Callable[[str], Any] = _parse_bool
    elif isinstance(default, int):
        parser = int
    elif isinstance(default, float):
        parser = float
    else:
        parser = str
    _REGISTRY[name] = _Field(name, default, doc, parser)


# ---------------------------------------------------------------------------
# Flag declarations (the ray_config_def.h equivalent — keep in one place).
# ---------------------------------------------------------------------------

# Core / scheduling
declare(
    "worker_processes", max(2, min(8, (os.cpu_count() or 2) // 2)),
    "CPU-only tasks execute in this many spawned worker processes sharing a "
    "shm object arena (crash isolation, like the reference's worker pool); "
    "0 = execute on the node agent's threads. Device tasks always stay on "
    "threads in the device-owning process. Default derives from host CPUs.",
)
declare(
    "prestart_worker_processes", True,
    "Warm the worker-process pool in the background at node-agent creation "
    "(reference: worker_pool.cc prestart), so the forkserver cost overlaps "
    "driver setup instead of the first task submission.",
)
declare(
    "actor_processes", True,
    "CPU actors (num_tpus=0, max_concurrency=1) get a dedicated worker "
    "process with a mailbox RPC (crash isolation, the reference's actor "
    "model). Device actors and high-concurrency system actors stay in the "
    "device-owning process; unpicklable state falls back in-process.",
)
declare("task_max_retries", 3, "Default retries for tasks on worker/node death.")
declare("actor_max_restarts", 0, "Default actor restarts on failure.")
declare("scheduler_top_k_fraction", 0.2, "Top-k fraction for hybrid scheduling.")
declare("scheduler_spread_threshold", 0.5, "Utilization below which local wins.")
declare("health_check_period_ms", 1_000, "Control-plane health check interval.")
declare("health_check_timeout_ms", 10_000, "Misses before a node is declared dead.")

# Object store
declare("object_store_memory_bytes", 0, "Host shm store capacity; 0 = 30% of RAM.")
declare("object_store_fallback_dir", "/tmp/ray_tpu_spill", "Spill directory.")
declare("object_transfer_chunk_bytes", 1024 * 1024, "Inter-node chunk size.")
declare(
    "get_concurrency", 8,
    "Worker threads for batched Runtime.get: distinct refs fan out over "
    "this many parallel resolvers so pulls from different holders overlap "
    "(<=1 restores the serial path).",
)
declare(
    "object_transfer_pool_conns", 2,
    "Max pooled transfer connections per remote address; concurrent pulls "
    "from one holder ride separate sockets instead of serializing on one.",
)
declare(
    "object_transfer_chunk_window", 8,
    "Outstanding chunk requests pipelined per connection on the chunked "
    "pull path (1 = one synchronous round trip per chunk).",
)
declare(
    "object_transfer_stripe_min_bytes", 8 * 1024 * 1024,
    "Chunked pulls at or above this size stripe byte ranges across "
    "multiple advertised holders when at least two hold the object.",
)
declare(
    "object_pull_through_cache", True,
    "Seal remotely-pulled objects into the local store and register the "
    "location, so repeat gets are local hits and later pullers can fetch "
    "from this runtime (objects are immutable once sealed, so replicas "
    "never go stale).",
)
declare(
    "object_transfer_buffer_pool_bytes", 512 * 1024 * 1024,
    "Retained-bytes bound for the transfer receive-buffer pool. Large "
    "receive buffers are recycled across pulls (refcount-gated, so a "
    "buffer still referenced by zero-copy views is never reused) to "
    "avoid a full page-fault pass per large transfer; 0 disables "
    "pooling.",
)
declare(
    "object_transfer_max_stripes", 4,
    "Upper bound on concurrent stripe lanes a single chunked pull spreads "
    "across distinct sealed holders (diminishing returns past a few "
    "stripes on one NIC).",
)
declare(
    "object_transfer_shm_handoff", True,
    "Same-host pulls attach the holder's staging arena by name and map "
    "the blob zero-copy over /dev/shm instead of copying bytes through a "
    "loopback socket (detected via a boot-id host token).",
)
declare(
    "object_broadcast_relay", True,
    "Pullers of the same object self-organize into a chunk-pipelined "
    "relay tree: each claims a tree slot in the KV, pulls from its "
    "parent's committed prefix mid-transfer, and serves downstream "
    "pullers from its own partial. Off = every puller hits the sealed "
    "holders directly (flat fan-out).",
)
declare(
    "object_broadcast_fanout", 2,
    "Branching factor of the relay tree (out-degree per node, including "
    "the origin). Slot k's parent is slot (k - fanout) // fanout.",
)
declare(
    "object_relay_min_bytes", 4 * 1024 * 1024,
    "Objects below this size skip relay-tree formation; tree setup "
    "(claims + partial registration) costs more than a flat pull wins.",
)
declare(
    "object_relay_timeout_s", 30.0,
    "How long a chunk request parks on a relay holder's partial waiting "
    "for the byte range to land before the server fails the read and the "
    "puller falls back to another holder.",
)

# Object-plane observability (core/object_ledger.py)
declare(
    "object_ledger", True,
    "Maintain per-object ledger metadata (creator, pin reason, last "
    "access) and per-edge transfer-flow counters, shipped as bounded "
    "snapshots on heartbeat telemetry. Off = zero bookkeeping beyond the "
    "plain store entries (the bench overhead suite toggles this).",
)
declare(
    "object_ledger_max_objects", 256,
    "Max object records in one heartbeat ledger snapshot (largest-first; "
    "the snapshot carries total object/byte counts so truncation is "
    "visible on the head).",
)
declare(
    "object_leak_age_s", 60.0,
    "Head-side leak sweep: a pinned/escaped object with zero live driver "
    "refs older than this is flagged as leaked; a pull-through cache "
    "entry never re-hit for this long is flagged as cold.",
)
declare(
    "object_sweep_period_s", 5.0,
    "How often the head's monitor loop runs the object-plane leak/"
    "staleness sweep (dead-node directory entries, pinned-no-refs, cold "
    "cache bytes) and re-asserts its health alerts.",
)
declare(
    "object_flow_window_s", 10.0,
    "Sliding window for the per-edge object_flow_window_bps bandwidth "
    "gauges (per (src_node, dst_node, path) transfer link).",
)

# Gang / TPU
declare("gang_barrier_timeout_ms", 60_000, "SPMD gang entry barrier timeout.")
declare("device_prefetch_depth", 2, "Host->HBM double buffering depth.")

# Shared ingest service (data/ingest.py, data/tenant.py)
declare(
    "ingest_default_weight", 1.0,
    "Fair-share weight assigned to an ingest tenant that registers "
    "without an explicit one. Weights are relative: a weight-3 tenant "
    "is admitted ~3x the blocks of a weight-1 tenant under contention.",
)
declare(
    "ingest_inflight_bytes", 32 * 1024 * 1024,
    "Per-tenant in-flight byte budget for the ingest admission loop: "
    "once this many estimated output bytes are dispatched-but-"
    "unconsumed for one tenant, its further blocks wait regardless of "
    "deficit, so one fast-draining tenant cannot park the whole pool's "
    "output in the object plane.",
)
declare(
    "ingest_quantum_bytes", 4 * 1024 * 1024,
    "Deficit round-robin quantum: byte credit granted per admission "
    "round per unit of tenant weight. Larger quanta batch a tenant's "
    "dispatches; smaller quanta interleave tenants more finely.",
)
declare(
    "ingest_cache_ttl_s", 300.0,
    "Ephemeral block-cache TTL: a preprocessed block (PIN_INGEST) not "
    "re-served for this long is evicted by the service janitor. "
    "Deregistered tenants' blocks are condemned immediately and "
    "collected on the next janitor pass.",
)
declare("ingest_pool_min", 1, "Ingest worker-pool floor (autoscale lower bound).")
declare("ingest_pool_max", 4, "Ingest worker-pool ceiling (autoscale upper bound).")
declare(
    "ingest_eval_period_s", 0.5,
    "How often the ingest pool controller evaluates per-tenant "
    "data_stage_stall_seconds deltas for scale-up/scale-down decisions.",
)
declare(
    "ingest_stall_scale_threshold", 0.1,
    "Per-tenant stall-seconds accumulated within one controller eval "
    "period that counts as scale-up pressure on the ingest pool.",
)

# Serving (serve/engine.py, serve/spec_decode.py, serve/disagg.py)
declare(
    "spec_overlap", True,
    "Speculative decoding: overlap the draft-model propose for round N+1 "
    "with the host-side commit/bookkeeping of round N (the prefetched "
    "drafts are validated per slot by request/position stamps, so "
    "eviction or cancellation in between degrades to a plain token, "
    "never to a wrong one). Per-engine override: "
    "SpeculationConfig.overlap.",
)
declare(
    "kv_frame_layout", "layer",
    "Streamed KV-migration frame layout: 'layer' (wire v2 — frames carry "
    "a slab of consecutive layers per token range, so the stream starts "
    "during the first layers of the device->host pull and the importer "
    "stages slabs as they land) or 'token' (wire v1 — all layers per "
    "frame). Per-request override: Request.kv_frame_layout; disagg "
    "coordinators forward DisaggConfig.kv_frame_layout.",
)

# Observability
declare("log_to_driver", True, "Tail worker logs back to the driver process.")
declare("event_log_dir", "", "Structured event-log directory; empty = session dir.")
declare("task_events_max_buffer", 10_000, "Ring-buffer size for task events.")
declare(
    "trace_sample_rate", 0.0,
    "Fraction of serve requests that open a root trace span at the API "
    "entry point (util/tracing.py). 0 disables sampling entirely (the "
    "zero-overhead default); requests arriving under an already-active "
    "span are always traced regardless of this rate.",
)
declare(
    "telemetry_report_period_s", 5.0,
    "How often worker runtimes flush metrics snapshots, trace spans, and "
    "timeline events to the head (piggybacked on the heartbeat loop, so "
    "the effective period is at least one health_check_period_ms).",
)
declare(
    "telemetry_max_bytes", 1_000_000,
    "Byte budget for one heartbeat telemetry flush (spans + timeline "
    "events + metrics snapshot, pickled size). Overflow drops OLDEST "
    "spans/events first and counts them in telemetry_dropped_total{kind} "
    "so a span burst cannot bloat heartbeats. 0 = unlimited.",
)
declare(
    "telemetry_stale_factor", 3.0,
    "A node's federated telemetry snapshot is dropped from the merged "
    "dashboard/health view once it is older than this many "
    "telemetry_report_period_s (and purged outright on mark_node_dead), "
    "so killed nodes stop haunting /metrics.",
)

# SLO / health plane (core/health.py, util/slo.py)
declare(
    "slo_digests", True,
    "Update streaming latency digests (util/slo.py: TTFT, time-between-"
    "tokens, e2e, KV-migration) inline in the serve hot paths and ship "
    "them with heartbeat telemetry. Off = zero digest work.",
)
declare(
    "slo_digest_window_s", 60.0,
    "Sliding window the per-process latency digests answer quantile "
    "queries over (rotated in slo._SLICES sub-windows).",
)
declare(
    "slo_ttft_ms", 0.0,
    "p95-TTFT service-level objective in ms. >0 arms the default "
    "health-plane rule `p95(serve_ttft_seconds) > slo for 2 periods`; "
    "0 leaves TTFT alerting to user-supplied rules.",
)
declare(
    "health_eval_period_s", 2.0,
    "How often the head health plane (core/health.py) evaluates its "
    "alert rules against digests, federated metrics, and heartbeats.",
)
declare(
    "health_queue_depth_max", 64,
    "Default alert threshold for serve_disagg_queue_depth (sustained "
    "two evaluation periods).",
)
declare(
    "health_memory_fraction_max", 0.92,
    "Default alert threshold for host_memory_used_fraction (sustained "
    "two evaluation periods).",
)
declare(
    "health_quarantine_s", 5.0,
    "How long health-aware routing (core/health.py ReplicaHealth) "
    "quarantines a degraded replica before sending one probe request.",
)
declare(
    "autoscale_cooldown_s", 15.0,
    "Minimum gap between scale-up waves (autoscaler.py node launches and "
    "serve/fleet.py replica-target bumps). Demand arriving inside the "
    "cooldown is absorbed by the in-flight wave instead of launching "
    "more capacity, so one alert burst cannot flap the fleet.",
)
declare(
    "autoscale_step_max", 2,
    "Cap on how many scale-up actions one evaluation pass may take "
    "(node launches per Autoscaler.update, replica-target delta per "
    "FleetController period). Bounds the blast radius of a noisy "
    "demand signal.",
)
declare(
    "flight_recorder_entries", 256,
    "Per-process flight-recorder ring size (recent spans + log lines + "
    "events, util/flight_recorder.py) flushed into a postmortem artifact "
    "when a crashed worker is reaped.",
)
declare(
    "flight_recorder_bytes", 262_144,
    "Size cap for a worker's on-disk flight-recorder mirror file; the "
    "mirror is rewritten from the in-memory ring when it grows past "
    "this, so a chatty worker cannot fill the session dir.",
)

declare(
    "control_plane_rpc_host", "127.0.0.1",
    "Bind address for the control-plane RPC server; set 0.0.0.0 (or a "
    "specific interface) for cross-host attach.",
)
declare(
    "control_plane_rpc_port", -1,
    "Serve this runtime's control plane over TCP (core/rpc.py) so other "
    "processes/hosts and the CLI can attach: -1 = off, 0 = ephemeral port "
    "(logged), >0 = fixed port.",
)
declare(
    "node_host", "127.0.0.1",
    "This host's address for cross-host serving (worker dispatch + object "
    "transfer, core/cross_host.py): both the bind interface and the "
    "address ADVERTISED to the cluster, so it must be reachable from the "
    "head — set to this machine's cluster-facing IP when joining from "
    "another host.",
)

declare(
    "control_plane_reconnect_max_s", 5.0,
    "Cap on the exponential backoff between control-plane client "
    "reconnect attempts after a lost connection (rpc.RemoteControlPlane): "
    "attempts start at 50ms and double up to this bound, so a client "
    "rides out a head restart instead of poisoning itself.",
)
declare(
    "control_plane_call_deadline_s", 30.0,
    "Default per-call deadline for RemoteControlPlane requests. Every "
    "blocking call fails with the retryable ControlPlaneUnavailable "
    "within this window; idempotent methods (heartbeat, kv_get, dir_*, "
    "...) retry transparently across reconnects inside it, non-idempotent "
    "ones surface the error to the caller.",
)

declare(
    "control_plane_redial_rate", 16.0,
    "Process-wide cap on control-plane reconnect DIAL attempts per second "
    "(token bucket shared by every RemoteControlPlane in the process). "
    "Bounds the thundering herd when many clients re-dial a restarted or "
    "failed-over head/shard at once; <= 0 disables the cap.",
)
declare(
    "control_plane_shards", 0,
    "Federate the control plane: shard the KV store, object directory and "
    "pubsub fan-out across this many ControlPlaneShard subprocesses, each "
    "with a warm standby that is promoted on primary death "
    "(core/shard.py). 0 = off (single in-process head, the default).",
)
declare(
    "control_plane_shard_dir", "",
    "Directory for shard journals + snapshots when control_plane_shards "
    "> 0; empty = a per-session tmp directory.",
)
declare(
    "control_plane_gossip_ttl_s", 600.0,
    "TTL for gossip-namespace control-plane KV entries "
    "(object_transfer*/node_service/channel_service advertisements) whose "
    "owner is no longer ALIVE — reaps tombstones left by nodes that died "
    "without mark_node_dead.",
)
declare(
    "scheduler_local_admit", True,
    "Bottom-up scheduling: the driver-local node agent admits a task "
    "against its own resource view when it fits below the spread "
    "threshold, delegating to ClusterScheduler only on overflow "
    "(reference: Ray's two-level local-first scheduler).",
)

# Control-plane persistence (GCS-Redis analogue, file-backed)
declare(
    "control_plane_snapshot_path", "",
    "Snapshot the control-plane tables (KV/jobs/named actors/...) to this "
    "file on an interval; init(resume_from=path) rebuilds from it. "
    "Empty = persistence off.",
)
declare(
    "control_plane_snapshot_interval_s", 5.0,
    "Seconds between control-plane snapshots when persistence is on.",
)

# Online RL post-training (rl/online.py)
declare(
    "rl_staleness_max_versions", 1,
    "Online-RL staleness bound: a rollout trajectory whose stamped "
    "weights_version trails the trainer's current generation by more "
    "than this many versions is stale. What happens to it is "
    "rl_staleness_policy's call.",
)
declare(
    "rl_staleness_policy", "drop",
    "What the online-RL trainer does with stale trajectories: 'drop' "
    "discards them (counted in rl_stale_trajectories dropped), "
    "'correct' keeps them — the clipped importance ratio against the "
    "rollout-time logprobs (GRPO's logp_old) absorbs the off-policy "
    "gap.",
)
declare(
    "rl_trajectory_channel_capacity", 64,
    "Bound of the scored-trajectory DistChannel between the reward "
    "stage and the online-RL trainer: a slow trainer backpressures "
    "rollout generation instead of buffering unboundedly.",
)
declare(
    "rl_sync_stall_max_pct", 5.0,
    "Alert threshold for the rl goodput ledger's weight_sync share: the "
    "rl_sync_stall health rule fires when weight re-sync consumes more "
    "than this percent of loop wall time.",
)

# Correctness tooling (util/sanitizer.py, ray_tpu.tools.raylint)
declare(
    "sanitize", False,
    "RAY_TPU_SANITIZE=1 swaps threading.Lock/RLock for instrumented "
    "wrappers at import time: acquisition order feeds a per-process "
    "lock-order graph (cycles = potential deadlock) and long holds are "
    "flagged, both reported through the flight recorder. Off = the "
    "stock primitives, zero overhead.",
)
declare(
    "sanitize_hold_ms", 100.0,
    "Sanitizer lock-hold budget: releasing a lock held longer than this "
    "(blocking work under a lock) records a hold-time violation.",
)

# Pipeline-parallel trainer (train/pipeline.py)
declare(
    "pipeline_virtual_stages", 1,
    "Interleaved 1F1B: number of non-contiguous layer slices (model "
    "chunks) each pipeline stage worker owns. v>1 shrinks the "
    "warmup/drain bubble by ~v x at the cost of v x more cross-stage "
    "activation traffic. LMStageModule picks this up as its default "
    "when virtual_stages is not given explicitly; requires "
    "n_layers %% (num_stages * v) == 0 and microbatches %% stages == 0.",
)
declare(
    "stage_mesh_axes", "",
    "In-stage SPMD mesh for each pipeline stage gang, e.g. 'dp=2,tp=2' "
    "or 'fsdp=4'. Stage params are laid out by the regex partition "
    "rules in parallel/sharding.py (STAGE_PARTITION_RULES) onto a "
    "per-stage jax Mesh and forward/backward compile under it with "
    "activation sharding constraints. Empty = no in-stage sharding. "
    "Skipped with an info log when jax.device_count() is too small.",
)
declare(
    "pipeline_overlap_grad_exchange", True,
    "Overlap step N's dp grad exchange + optimizer update with step "
    "N+1's warmup forwards: apply_update runs on a background thread "
    "per worker and the next compute_grads fences on a per-leaf "
    "version check before touching params. Off = the synchronous "
    "update of PR 8.",
)


class Config:
    """Resolved configuration view. Thread-safe."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._overrides: Dict[str, Any] = {}

    def apply_overrides(self, system_config: Optional[Dict[str, Any]]) -> None:
        if not system_config:
            return
        with self._lock:
            for key, value in system_config.items():
                if key not in _REGISTRY:
                    raise KeyError(f"unknown config flag: {key}")
                self._overrides[key] = value

    def reset(self) -> None:
        with self._lock:
            self._overrides.clear()

    def get(self, name: str) -> Any:
        field = _REGISTRY.get(name)
        if field is None:
            raise KeyError(f"unknown config flag: {name}")
        with self._lock:
            if name in self._overrides:
                return self._overrides[name]
        env = os.environ.get(f"RAY_TPU_{name.upper()}")
        if env is not None:
            return field.parser(env)
        return field.default

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        return self.get(name)


def describe_flags() -> Dict[str, Dict[str, Any]]:
    return {
        f.name: {"default": f.default, "doc": f.doc, "value": config.get(f.name)}
        for f in _REGISTRY.values()
    }


config = Config()
