"""ctypes binding for the C++ shared-memory object store (_shm/shm_store.cc).

The native path for the host object plane (SURVEY.md N5): multi-process
workers map one /dev/shm arena and exchange sealed immutable buffers
zero-copy. The pure-Python in-process store remains the default for
thread-mode runtimes; this backend turns on for process-pool workers.

Build: `make -C ray_tpu/core/_shm` (auto-attempted on first use).
"""

from __future__ import annotations

import ctypes
import os
import socket
import subprocess
import threading
import weakref
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
# RAY_TPU_SHM_LIB: alternate build, e.g. the TSAN/ASAN .so from
# `make -C ray_tpu/core/_shm tsan` (see that Makefile, SURVEY §5.2)
_SO = os.environ.get(
    "RAY_TPU_SHM_LIB", os.path.join(_DIR, "_shm", "libshm_store.so")
)
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

ID_SIZE = 20


class ShmStoreError(RuntimeError):
    pass


class PullRejected(ShmStoreError):
    """Native pull could not land in the destination store (too large for
    the arena); the caller should fall back to the buffered path."""


def _load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        def build():
            try:
                subprocess.run(
                    ["make", "-C", os.path.join(_DIR, "_shm"), "-B"],
                    check=True, capture_output=True, timeout=120,
                )
            except (subprocess.CalledProcessError, OSError) as e:
                raise ShmStoreError(f"cannot build libshm_store.so: {e}") from e

        def rebuild_and_bind() -> ctypes.CDLL:
            build()
            lib = ctypes.CDLL(_SO)
            try:
                _bind(lib)
            except AttributeError as e:
                # dlopen caches by path: a fresh build that STILL lacks a
                # symbol in this process must fail with a clear error, not
                # an AttributeError that bricks every store construction
                raise ShmStoreError(
                    f"libshm_store.so rebuilt but still missing {e}; "
                    "restart the process to drop the stale dlopen mapping"
                ) from e
            return lib

        if not os.path.exists(_SO):
            build()
        try:
            lib = ctypes.CDLL(_SO)
            _bind(lib)
        except (OSError, AttributeError):
            # OSError: binary for another arch/libc. AttributeError: binary
            # predates a symbol this binding needs (e.g. built before the
            # transfer plane existed). Either way: rebuild from source.
            lib = rebuild_and_bind()
        _lib = lib
        return lib


def _bind(lib: ctypes.CDLL) -> None:
        lib.shm_store_create.restype = ctypes.c_void_p
        lib.shm_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
        lib.shm_store_open.restype = ctypes.c_void_p
        lib.shm_store_open.argtypes = [ctypes.c_char_p]
        lib.shm_obj_create.restype = ctypes.c_void_p
        lib.shm_obj_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.shm_obj_seal.restype = ctypes.c_int
        lib.shm_obj_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shm_obj_get.restype = ctypes.c_void_p
        lib.shm_obj_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64)
        ]
        for fn in ("shm_obj_release", "shm_obj_delete", "shm_obj_contains"):
            getattr(lib, fn).restype = ctypes.c_int
            getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shm_store_live_bytes.restype = ctypes.c_uint64
        lib.shm_store_live_bytes.argtypes = [ctypes.c_void_p]
        lib.shm_store_capacity.restype = ctypes.c_uint64
        lib.shm_store_capacity.argtypes = [ctypes.c_void_p]
        lib.shm_store_close.restype = None
        lib.shm_store_close.argtypes = [ctypes.c_void_p]
        # native transfer plane (_shm/transfer.cc)
        lib.shm_transfer_server_start.restype = ctypes.c_void_p
        lib.shm_transfer_server_start.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)
        ]
        lib.shm_transfer_server_port.restype = ctypes.c_int
        lib.shm_transfer_server_port.argtypes = [ctypes.c_void_p]
        lib.shm_transfer_server_stop.restype = None
        lib.shm_transfer_server_stop.argtypes = [ctypes.c_void_p]
        lib.shm_transfer_connect.restype = ctypes.c_int
        lib.shm_transfer_connect.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int
        ]
        lib.shm_transfer_pull_buf.restype = ctypes.c_int64
        lib.shm_transfer_pull_buf.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_uint64
        ]
        lib.shm_transfer_pull_store.restype = ctypes.c_int64
        lib.shm_transfer_pull_store.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p
        ]
        lib.shm_transfer_close_fd.restype = None
        lib.shm_transfer_close_fd.argtypes = [ctypes.c_int]


def _check_id(object_id: bytes) -> bytes:
    if len(object_id) != ID_SIZE:
        raise ValueError(f"object id must be {ID_SIZE} bytes, got {len(object_id)}")
    return object_id


class ShmObjectStore:
    """One mapped store handle (create for the node owner, open for clients)."""

    kind = "shm"

    def __init__(self, name: str, capacity: int = 1 << 30, max_objects: int = 4096,
                 create: bool = True):
        self._lib = _load()
        self.name = name if name.startswith("/") else f"/{name}"
        if create:
            self._h = self._lib.shm_store_create(
                self.name.encode(), capacity, max_objects
            )
        else:
            self._h = self._lib.shm_store_open(self.name.encode())
        if not self._h:
            raise ShmStoreError(
                f"cannot {'create' if create else 'open'} shm store {self.name}"
            )
        # parity with MemoryObjectStore.on_evict: fires on explicit delete
        # so directory locations can be deregistered. C-side LRU eviction
        # inside the arena is NOT observable from Python, so hook users
        # must tolerate stale advertisements (pullers fall through the
        # ranked holder list on a miss).
        self.on_evict = None
        # object-plane ledger (core/object_ledger.py): Python-side metadata
        # for entries THIS handle sealed or pulled (the C arena has no
        # enumeration API, so other processes' objects are invisible here —
        # each process's handle reports its own, and the head merges them).
        self.ledger_node = ""
        self._meta_lock = threading.Lock()
        self._meta: dict = {}  # object_id bytes -> ledger meta dict
        self._evictions = 0

    # -- raw byte API --------------------------------------------------------

    def _handle(self):
        """The C functions do no null check: calling through a closed handle
        is a segfault, not an error. Every entry point goes through here."""
        h = self._h
        if not h:
            raise ShmStoreError(f"shm store {self.name} is closed")
        return h

    def _create_write_seal(self, object_id: bytes, total: int, write) -> None:
        """Allocate, fill via write(ptr), seal. A failure after create must
        reclaim the slot: the creator pin (pins=1 until seal) blocks delete,
        so release it first — otherwise the unsealed entry is a permanent
        compaction barrier the LRU can never evict."""
        h = self._handle()
        ptr = self._lib.shm_obj_create(h, object_id, total)
        if not ptr:
            raise ShmStoreError(
                f"create failed for {object_id.hex()[:8]} ({total}B): "
                f"duplicate, table full, or arena exhausted"
            )
        try:
            write(ptr)
            if self._lib.shm_obj_seal(h, object_id) != 0:
                raise ShmStoreError("seal failed")
        except Exception:
            self._lib.shm_obj_release(h, object_id)  # drop creator pin
            self._lib.shm_obj_delete(h, object_id)
            raise
        self._note_put(object_id, total)

    def _note_put(self, object_id: bytes, nbytes: int,
                  pin_reason: str = "") -> None:
        """Record ledger metadata for an object this handle landed."""
        import time as _time

        now = _time.monotonic()
        with self._meta_lock:
            if len(self._meta) > 65536:  # runaway guard for long-lived handles
                self._meta.clear()
            self._meta[bytes(object_id)] = {
                "size_bytes": int(nbytes),
                "created_at": now,
                "last_access": now,
                "pin_reason": pin_reason,
                "creator_node": self.ledger_node,
                "creator_pid": os.getpid(),
                "creator_task": "",
            }

    def annotate(self, object_id: bytes, pin_reason: Optional[str] = None,
                 creator_task: Optional[str] = None,
                 creator_node: Optional[str] = None) -> None:
        """Ledger-metadata parity with MemoryObjectStore.annotate (the
        serialized_escape reason is sticky there too)."""
        with self._meta_lock:
            meta = self._meta.get(bytes(object_id))
            if meta is None:
                return
            if (pin_reason is not None
                    and meta["pin_reason"] != "serialized_escape"):
                meta["pin_reason"] = pin_reason
            if creator_task is not None:
                meta["creator_task"] = creator_task
            if creator_node is not None:
                meta["creator_node"] = creator_node

    def put(self, object_id: bytes, data) -> None:
        """data: bytes or any C-contiguous buffer (memoryview, pickle5 raw)."""
        _check_id(object_id)
        if not isinstance(data, (bytes, bytearray)):
            data = np.frombuffer(data, np.uint8)  # zero-copy address handle
        n = data.nbytes if isinstance(data, np.ndarray) else len(data)
        src = data.ctypes.data if isinstance(data, np.ndarray) else bytes(data)
        self._create_write_seal(object_id, n, lambda ptr: ctypes.memmove(ptr, src, n))

    def get_view(self, object_id: bytes) -> Optional[memoryview]:
        """Zero-copy pinned view; call release(id) when done."""
        _check_id(object_id)
        size = ctypes.c_uint64()
        h = self._h
        if not h:  # closed mid-flight: report missing, don't segfault
            return None
        ptr = self._lib.shm_obj_get(h, object_id, ctypes.byref(size))
        if not ptr:
            return None
        with self._meta_lock:
            meta = self._meta.get(bytes(object_id))
            if meta is not None:
                import time as _time

                meta["last_access"] = _time.monotonic()
        arr = (ctypes.c_uint8 * size.value).from_address(ptr)
        return memoryview(arr)

    def get_bytes(self, object_id: bytes) -> Optional[bytes]:
        view = self.get_view(object_id)
        if view is None:
            return None
        try:
            return bytes(view)
        finally:
            self.release(object_id)

    def release(self, object_id: bytes) -> None:
        if not self._h:  # store already closed (e.g. interpreter shutdown)
            return
        self._lib.shm_obj_release(self._h, _check_id(object_id))

    def delete(self, object_id: bytes) -> bool:
        h = self._h
        if not h:
            return False
        deleted = self._lib.shm_obj_delete(h, _check_id(object_id)) == 0
        if deleted:
            with self._meta_lock:
                self._meta.pop(bytes(object_id), None)
                self._evictions += 1
        on_evict = self.on_evict
        if deleted and on_evict is not None:
            try:
                on_evict(object_id)
            except Exception:  # noqa: BLE001 — hooks never fail a delete
                pass
        return deleted

    def contains(self, object_id: bytes) -> bool:
        h = self._h
        if not h:
            return False
        return self._lib.shm_obj_contains(h, _check_id(object_id)) == 1

    # -- numpy zero-copy -----------------------------------------------------

    def put_array(self, object_id: bytes, arr: np.ndarray) -> None:
        arr = np.ascontiguousarray(arr)
        header = f"{arr.dtype.str}|{','.join(map(str, arr.shape))}|".encode()
        total = len(header) + arr.nbytes

        def write(ptr):
            ctypes.memmove(ptr, header, len(header))
            ctypes.memmove(ptr + len(header), arr.ctypes.data, arr.nbytes)

        self._create_write_seal(_check_id(object_id), total, write)

    def get_array(self, object_id: bytes) -> Optional[np.ndarray]:
        """Zero-copy read: the returned array aliases shared memory. The pin
        is released when the last numpy view dies (finalizer on the buffer
        owner every view chains to); do NOT also call release(id)."""
        view = self.get_view(object_id)
        if view is None:
            return None
        # view.obj is the ctypes buffer at the bottom of every numpy view's
        # .base chain; when it is collected, no aliasing array remains.
        weakref.finalize(view.obj, self.release, bytes(object_id))
        raw = np.frombuffer(view, np.uint8)
        # parse tiny header: dtype|shape|
        first = bytes(raw[:64])
        d1 = first.index(b"|")
        d2 = first.index(b"|", d1 + 1)
        dtype = np.dtype(first[:d1].decode())
        shape_s = first[d1 + 1: d2].decode()
        shape = tuple(int(x) for x in shape_s.split(",")) if shape_s else ()
        data = raw[d2 + 1:]
        return data.view(dtype).reshape(shape)

    def live_bytes(self) -> int:
        return self._lib.shm_store_live_bytes(self._handle())

    def capacity(self) -> int:
        return self._lib.shm_store_capacity(self._handle())

    def stats(self) -> dict:
        """Same dict shape as MemoryObjectStore.stats so the ledger and
        /metrics report both backends uniformly. Bytes/capacity come from
        the arena (authoritative, cross-process); the object count is
        this handle's tracked entries (the arena has no enumeration API)."""
        try:
            used, cap = self.live_bytes(), self.capacity()
        except ShmStoreError:
            used = cap = 0
        with self._meta_lock:
            n = len(self._meta)
        return {
            "num_objects": n,
            "used_bytes": used,
            "capacity_bytes": cap,
            "num_spilled": 0,  # the arena never spills; creates fail instead
            "num_evictions": self._evictions,
        }

    def list_objects(self):
        """[(object_id bytes, nbytes)] for this handle's tracked entries
        (MemoryObjectStore.list_objects parity for introspection)."""
        with self._meta_lock:
            items = list(self._meta.items())
        return [(oid, m["size_bytes"]) for oid, m in items
                if self.contains(oid)]

    def ledger_records(self) -> list:
        """Ledger rows in object_ledger wire shape; entries deleted by
        another process (or LRU-evicted in the arena) are pruned here."""
        import time as _time

        now = _time.monotonic()
        with self._meta_lock:
            items = list(self._meta.items())
        out, stale = [], []
        for oid, m in items:
            if not self.contains(oid):
                stale.append(oid)
                continue
            out.append({
                "object_id": oid.hex(),
                "size_bytes": m["size_bytes"],
                "age_s": round(now - m["created_at"], 3),
                "idle_s": round(now - m["last_access"], 3),
                "pin_count": 0,  # C-side pins are view refcounts, not holds
                "pin_reason": m["pin_reason"],
                "creator_node": m["creator_node"][:12],
                "creator_pid": m["creator_pid"],
                "creator_task": m["creator_task"],
                "spilled": False,
            })
        if stale:
            with self._meta_lock:
                for oid in stale:
                    self._meta.pop(oid, None)
        return out

    def close(self) -> None:
        if self._h:
            self._lib.shm_store_close(self._h)
            self._h = None

    def unlink_name(self) -> None:
        """Remove the /dev/shm name WITHOUT closing the mapping: live
        pointers stay valid, but no new process can open the store and
        the segment is reclaimed once the last mapping drops. For
        teardown paths that must not munmap under in-flight users but
        also must not leak the name past process exit."""
        try:
            os.unlink(os.path.join("/dev/shm", self.name.lstrip("/")))
        except OSError:
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeTransferServer:
    """C++ serving thread streaming sealed objects from `store`'s arena
    (_shm/transfer.cc). The store must stay open for the server's life —
    the server holds a raw handle into it."""

    def __init__(self, store: ShmObjectStore, host: str = "127.0.0.1",
                 port: int = 0):
        self._lib = _load()
        self._store = store  # keep the mapping alive
        host = socket.gethostbyname(host)  # the C side wants a dotted quad
        port_out = ctypes.c_int()
        self._h = self._lib.shm_transfer_server_start(
            store._handle(), host.encode(), port, ctypes.byref(port_out)
        )
        if not self._h:
            raise ShmStoreError("cannot start native transfer server")
        self.port = port_out.value

    def stop(self) -> None:
        if self._h:
            self._lib.shm_transfer_server_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class NativeTransferClient:
    """Pulls whole objects over one pooled connection per holder. The recv
    loop runs in C with the GIL released; -2 (missing) and -3 (exceeds the
    caller's buffer) are soft failures the caller can fall back from.
    Connect and every send/recv are bounded by `timeout_s` (enforced in C
    via SO_RCVTIMEO/SO_SNDTIMEO) so a blackholed holder fails fast instead
    of wedging the puller."""

    MISSING = -2
    TOO_LARGE = -3

    def __init__(self, timeout_s: float = 30.0):
        self._lib = _load()
        self._timeout_ms = max(1, int(timeout_s * 1000))
        self._fds: dict = {}
        self._lock = threading.Lock()

    def _conn(self, host: str, port: int):
        key = (host, port)
        with self._lock:
            conn = self._fds.get(key)
        if conn is not None:
            return conn
        # connect OUTSIDE the registry lock: a slow/unreachable holder
        # must not block pulls from other holders
        fd = self._lib.shm_transfer_connect(
            socket.gethostbyname(host).encode(), port, self._timeout_ms)
        if fd < 0:
            raise ShmStoreError(f"cannot connect to {host}:{port}")
        with self._lock:
            existing = self._fds.get(key)
            if existing is not None:  # lost the race: keep the first conn
                self._lib.shm_transfer_close_fd(fd)
                return existing
            conn = (fd, threading.Lock())
            self._fds[key] = conn
            return conn

    def pull(self, host: str, port: int, object_id: bytes,
             size: int) -> Optional[bytearray]:
        """Pull `object_id` (known `size` from the control path) into a
        fresh buffer. Returns None when the holder no longer has it."""
        _check_id(object_id)
        fd, fd_lock = self._conn(host, port)
        buf = bytearray(size)
        c_buf = (ctypes.c_uint8 * size).from_buffer(buf) if size else None
        with fd_lock:  # request/response pairs must not interleave on one fd
            rc = self._lib.shm_transfer_pull_buf(fd, object_id, c_buf, size)
        if rc == self.MISSING:
            return None
        if rc == self.TOO_LARGE:
            # soft failure by contract: the C side drained the payload, so
            # the pooled connection stays healthy — do NOT drop it
            raise PullRejected(
                f"object {object_id.hex()[:8]} is larger than the "
                f"{size}B buffer the control path promised"
            )
        if rc < 0 or rc != size:
            self._drop(host, port)
            raise ShmStoreError(
                f"native pull of {object_id.hex()[:8]} from {host}:{port} "
                f"failed (rc={rc}, expected {size}B)"
            )
        return buf

    def pull_into(self, host: str, port: int, object_id: bytes,
                  store: ShmObjectStore) -> Optional[int]:
        """Pull `object_id` straight into `store`'s arena (no Python-side
        allocation). Returns the size, None when the holder lacks the
        object, or raises PullRejected when the local create failed and
        the object is not already present (caller falls back)."""
        _check_id(object_id)
        fd, fd_lock = self._conn(host, port)
        with fd_lock:  # request/response pairs must not interleave on one fd
            rc = self._lib.shm_transfer_pull_store(fd, object_id,
                                                   store._handle())
        if rc == self.MISSING:
            return None
        if rc == self.TOO_LARGE:
            # create failed: either a concurrent pull landed it (reuse) or
            # it genuinely does not fit this store. get_view (not contains)
            # pins it — a concurrent delete between the two would otherwise
            # turn the reuse branch into a crash.
            view = store.get_view(object_id)
            if view is not None:
                try:
                    return len(view)
                finally:
                    store.release(object_id)
            raise PullRejected(
                f"object {object_id.hex()[:8]} does not fit store {store.name}"
            )
        if rc < 0:
            self._drop(host, port)
            raise ShmStoreError(
                f"native pull of {object_id.hex()[:8]} from {host}:{port} "
                f"failed (rc={rc})"
            )
        # the pull landed the object via C without a Python put: record it
        # in the destination handle's ledger as a pull-through replica
        store._note_put(object_id, int(rc), pin_reason="cache")
        return int(rc)

    def _drop(self, host: str, port: int) -> None:
        with self._lock:
            conn = self._fds.pop((host, port), None)
        if conn is not None:
            self._lib.shm_transfer_close_fd(conn[0])

    def close(self) -> None:
        with self._lock:
            conns = list(self._fds.values())
            self._fds.clear()
        for fd, _ in conns:
            self._lib.shm_transfer_close_fd(fd)
