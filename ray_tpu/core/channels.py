"""Distributed SPSC channels for compiled graphs.

Reference analogue: `python/ray/experimental/channel/` — the compiled
DAG's transport (`shared_memory_channel.py` intra-node,
`torch_tensor_nccl_channel.py` cross-node). TPU-native shape: a channel
is HOMED in its consumer's process as a plain bounded queue (the hot
read path is a local dequeue, no syscall); remote producers push frames
over a persistent TCP connection to the owner process's ChannelService.
Device arrays do NOT ride these channels — compiled-graph values are
host objects; intra-slice tensors move as jax arrays over ICI inside the
actors themselves (SURVEY §7.4.5).

Why consumer-homed: the consumer blocks in get() at pipeline cadence —
that must never pay a round trip. The producer's put() pays the hop, and
its blocking-put backpressure travels as a delayed RPC reply, so a full
downstream queue stalls exactly the producer lane that feeds it (the
reference's bounded-channel semantics).

A `DistChannel` pickles as (owner_addr, chan_id, maxsize) and
reconstructs anywhere: in the owner process it resolves to the local
registry queue; elsewhere to a pooled writer connection.
"""

from __future__ import annotations

import pickle
import queue
import socket
import socketserver
import threading
import uuid
from typing import Any, Dict, Optional, Tuple

from . import object_ledger
from .logging import get_logger
from .metrics import MICRO_BUCKETS, Counter, Histogram
from .wire import MSG_REQUEST, MSG_RESPONSE, WireError, recv_msg, send_msg

logger = get_logger("channels")

KV_CHANNEL_PREFIX = "channel_service/"  # node_id hex -> service address

_PUT_TIMEOUT_S = 300.0

# Backpressure observability (pipeline training streams activations and
# gradients through here at step cadence): bytes pushed per path, how long
# consumers sit in get(), and how often a put found the queue already at
# capacity — the "backpressure engaged" signal.
_send_bytes = Counter(
    "channel_send_bytes",
    "Bytes pushed into DistChannels (path=local: same-process enqueue, "
    "estimated size; path=remote: pickled frame bytes on the wire).",
)
_recv_wait = Histogram(
    "channel_recv_wait_seconds",
    "Time a consumer spent blocked in DistChannel.get().",
    buckets=MICRO_BUCKETS,
)
_capacity_reached = Counter(
    "channel_capacity_reached_total",
    "Puts that found the channel at capacity (local/service: queue full at "
    "arrival; remote: put refused after the owner-side timeout).",
)


def _approx_nbytes(value: Any) -> int:
    """Cheap size estimate for the local put fast path, which never
    serializes: sum nbytes of array/bytes leaves in (nested) tuples,
    lists, and dicts; other leaves count 0 rather than paying a pickle."""
    n = getattr(value, "nbytes", None)
    if n is not None:
        return int(n)
    if isinstance(value, (bytes, bytearray, memoryview)):
        return len(value)
    if isinstance(value, dict):
        return sum(_approx_nbytes(v) for v in value.values())
    if isinstance(value, (tuple, list)):
        return sum(_approx_nbytes(v) for v in value)
    return 0


def channel_stats() -> Dict[str, float]:
    """This process's channel-metric totals (summed over tag sets) — the
    cheap assertion surface for tests and bench, and the per-node record
    federated to the head on heartbeat telemetry."""
    with _registry._lock:
        depth = sum(q.qsize() for q in _registry._chans.values())
        channels = len(_registry._chans)
    return {
        "send_bytes": sum(v for _, _, v in _send_bytes.samples()),
        "recv_count": sum(
            v for name, _, v in _recv_wait.samples() if name.endswith("_count")
        ),
        "recv_wait_seconds": sum(
            v for name, _, v in _recv_wait.samples() if name.endswith("_sum")
        ),
        "capacity_reached": sum(v for _, _, v in _capacity_reached.samples()),
        "channels": float(channels),
        "depth": float(depth),
    }


class _Registry:
    """Per-process channel table: chan_id -> bounded queue. Channels
    materialize lazily on first touch (producer frame or consumer get),
    so creation order between the two sides never matters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._chans: Dict[str, queue.Queue] = {}

    def get_or_create(self, chan_id: str, maxsize: int) -> queue.Queue:
        with self._lock:
            q = self._chans.get(chan_id)
            if q is None:
                q = self._chans[chan_id] = queue.Queue(maxsize)
            return q

    def drop(self, chan_id: str) -> None:
        with self._lock:
            self._chans.pop(chan_id, None)


class _ServiceHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: "ChannelService" = self.server  # type: ignore[assignment]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        server._track(sock)
        try:
            while True:
                msg_type, req = recv_msg(sock)
                if msg_type != MSG_REQUEST:
                    raise WireError(f"unexpected message type {msg_type}")
                op = req.get("op")
                if op == "put":
                    q = server.registry.get_or_create(
                        req["chan"], req.get("maxsize", 8))
                    if q.full():
                        _capacity_reached.inc(tags={"path": "service"})
                    try:
                        # blocking put: the delayed ok IS the backpressure
                        # signal to the remote producer (SPSC edges, so
                        # this per-connection thread stalls only the lane
                        # that overfilled its downstream)
                        q.put(pickle.loads(req["blob"]),
                              timeout=req.get("timeout", _PUT_TIMEOUT_S))
                        resp = {"ok": True}
                    except queue.Full:
                        resp = {"ok": False, "error": "channel full"}
                elif op == "put_many":
                    # one wire frame, N enqueues (coalesced small-value
                    # batch, e.g. streamed KV frames): unrolled here so
                    # the consumer still sees individual items, each put
                    # carrying the same blocking-backpressure semantics
                    q = server.registry.get_or_create(
                        req["chan"], req.get("maxsize", 8))
                    if q.full():
                        _capacity_reached.inc(tags={"path": "service"})
                    try:
                        for item in pickle.loads(req["blob"]):
                            q.put(item,
                                  timeout=req.get("timeout", _PUT_TIMEOUT_S))
                        resp = {"ok": True}
                    except queue.Full:
                        resp = {"ok": False, "error": "channel full"}
                elif op == "ping":
                    resp = {"ok": True}
                else:
                    resp = {"ok": False, "error": f"unknown op {op!r}"}
                send_msg(sock, MSG_RESPONSE, resp)
        except (WireError, OSError):
            pass  # producer disconnected
        finally:
            server._untrack(sock)


class ChannelService(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, registry: _Registry, host: str = "127.0.0.1",
                 port: int = 0):
        super().__init__((host, port), _ServiceHandler)
        self.registry = registry
        # established producer connections, severed on stop() so a stopped
        # service looks DEAD to pooled writers (mirrors ControlPlaneServer)
        self._conn_lock = threading.Lock()
        self._conns: set = set()
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="channel-service"
        )
        self._thread.start()

    def _track(self, sock) -> None:
        with self._conn_lock:
            self._conns.add(sock)

    def _untrack(self, sock) -> None:
        with self._conn_lock:
            self._conns.discard(sock)

    @property
    def address(self) -> str:
        host, port = self.server_address
        return f"{host}:{port}"

    def stop(self) -> None:
        self.shutdown()
        self.server_close()
        # closing the listener leaves established handler conns alive:
        # sever them too, or a producer's pooled writer keeps a half-open
        # socket whose next put blocks instead of failing fast
        with self._conn_lock:
            conns = list(self._conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


# --------------------------------------------------------------------------
# process-global service + writer pool
# --------------------------------------------------------------------------

_state_lock = threading.Lock()
_registry = _Registry()
_service: Optional[ChannelService] = None
_writers: Dict[Tuple[str, str], "_Writer"] = {}  # (addr, chan_id) -> writer


def ensure_service(host: str = "127.0.0.1") -> str:
    """Start (once) and return this process's channel-service address.
    Pass the CLUSTER-FACING host (config.node_host) — a loopback bind
    advertises an address remote producers resolve to themselves."""
    global _service
    with _state_lock:
        if _service is None:
            _service = ChannelService(_registry, host=host)
            logger.info("channel service on %s", _service.address)
        return _service.address


def service_address() -> Optional[str]:
    with _state_lock:
        return _service.address if _service is not None else None


class _Writer:
    """One persistent producer connection PER CHANNEL: a wedged lane
    (downstream full, server blocking in put) stalls only its own
    connection — never another edge's puts to the same host."""

    def __init__(self, addr: str):
        self.addr = addr
        self._sock = self._dial()
        self._lock = threading.Lock()

    def _dial(self) -> socket.socket:
        host, _, port = self.addr.rpartition(":")
        sock = socket.create_connection((host, int(port)), timeout=10.0)
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def put(self, chan_id: str, value: Any, maxsize: int,
            timeout: float) -> None:
        """Transport-vs-app split (mirrors object_transfer): a dead pooled
        socket (owner restarted / transient drop) reconnects ONCE in place
        and replays the frame; a second transport failure propagates. An
        application-level refusal ("channel full") is the backpressure
        signal — it never retries and raises queue.Full."""
        blob = _dumps(value)
        _send_bytes.inc(len(blob), tags={"path": "remote"})
        object_ledger.record_flow(object_ledger.local_node(),
                                  object_ledger.peer_node(self.addr),
                                  "channel", len(blob), transfers=1)
        frame = {
            "op": "put", "chan": chan_id, "blob": blob,
            "maxsize": maxsize, "timeout": timeout,
        }
        # The lock IS the request/reply framing: replies carry no ids and
        # match by position on this one socket, so send+recv must be one
        # critical section. Contention = serialized puts, by design.
        with self._lock:
            try:
                send_msg(self._sock, MSG_REQUEST, frame)
                _msg_type, resp = recv_msg(self._sock)  # raylint: disable=R2
            except (WireError, OSError):
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = self._dial()  # raises if the owner is gone
                send_msg(self._sock, MSG_REQUEST, frame)
                _msg_type, resp = recv_msg(self._sock)  # raylint: disable=R2
        if not resp.get("ok"):
            _capacity_reached.inc(tags={"path": "remote"})
            raise queue.Full(resp.get("error", "remote channel put failed"))

    def put_many(self, chan_id: str, values: list, maxsize: int,
                 timeout: float) -> None:
        """Coalesced put: N values in ONE wire frame (and one ledger
        flow record), unrolled into N queue items owner-side. Same
        reconnect-once-and-replay / queue.Full semantics as put()."""
        blob = _dumps(list(values))
        _send_bytes.inc(len(blob), tags={"path": "remote"})
        object_ledger.record_flow(object_ledger.local_node(),
                                  object_ledger.peer_node(self.addr),
                                  "channel", len(blob), transfers=1)
        frame = {
            "op": "put_many", "chan": chan_id, "blob": blob,
            "maxsize": maxsize, "timeout": timeout,
        }
        # send+recv under the lock: same positional request/reply framing
        # as put() above
        with self._lock:
            try:
                send_msg(self._sock, MSG_REQUEST, frame)
                _msg_type, resp = recv_msg(self._sock)  # raylint: disable=R2
            except (WireError, OSError):
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = self._dial()  # raises if the owner is gone
                send_msg(self._sock, MSG_REQUEST, frame)
                _msg_type, resp = recv_msg(self._sock)  # raylint: disable=R2
        if not resp.get("ok"):
            _capacity_reached.inc(tags={"path": "remote"})
            raise queue.Full(resp.get("error", "remote channel put failed"))

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _writer_for(addr: str, chan_id: str, fresh: bool = False) -> _Writer:
    """Connect OUTSIDE the global lock (a slow/unreachable owner must not
    freeze unrelated channels); fresh=True evicts a dead cached writer."""
    key = (addr, chan_id)
    with _state_lock:
        w = _writers.get(key)
        if w is not None and not fresh:
            return w
        if w is not None:
            _writers.pop(key, None)
    neww = _Writer(addr)
    with _state_lock:
        race = _writers.get(key)
        if race is not None and not fresh:
            neww.close()
            return race
        if w is not None:
            w.close()
        _writers[key] = neww
    return neww


def _dumps(obj: Any) -> bytes:
    try:
        return pickle.dumps(obj, protocol=5)
    except Exception:
        import cloudpickle

        return cloudpickle.dumps(obj, protocol=5)


# --------------------------------------------------------------------------
# the channel handle
# --------------------------------------------------------------------------


class DistChannel:
    """Bounded SPSC channel homed at `owner_addr`'s process. get() only in
    the owner process (local dequeue); put() from anywhere."""

    def __init__(self, owner_addr: str, chan_id: Optional[str] = None,
                 maxsize: int = 8):
        self.owner_addr = owner_addr
        self.chan_id = chan_id or uuid.uuid4().hex
        self.maxsize = maxsize

    def _local(self) -> Optional[queue.Queue]:
        if service_address() == self.owner_addr:
            return _registry.get_or_create(self.chan_id, self.maxsize)
        return None

    def put(self, value: Any, timeout: Optional[float] = None) -> None:
        from ..util import tracing

        t = _PUT_TIMEOUT_S if timeout is None else timeout
        with tracing.span_if_traced(
                "channel_send", {"channel": self.chan_id[:8]}):
            q = self._local()
            if q is not None:
                if q.full():
                    _capacity_reached.inc(tags={"path": "local"})
                q.put(value, timeout=t)
                _send_bytes.inc(_approx_nbytes(value), tags={"path": "local"})
                return
            # _Writer.put self-heals a stale socket (one reconnect +
            # replay), so no fresh-writer fallback is needed here
            _writer_for(self.owner_addr, self.chan_id).put(
                self.chan_id, value, self.maxsize, t)

    def put_many(self, values: list, timeout: Optional[float] = None) -> None:
        """Batched put: locally a plain loop of enqueues; remotely ONE
        wire frame unrolled owner-side — the coalescing primitive the
        streamed KV sender batches small frames with."""
        from ..util import tracing

        if not values:
            return
        t = _PUT_TIMEOUT_S if timeout is None else timeout
        with tracing.span_if_traced(
                "channel_send", {"channel": self.chan_id[:8],
                                 "batch": len(values)}):
            q = self._local()
            if q is not None:
                for value in values:
                    if q.full():
                        _capacity_reached.inc(tags={"path": "local"})
                    q.put(value, timeout=t)
                    _send_bytes.inc(_approx_nbytes(value),
                                    tags={"path": "local"})
                return
            _writer_for(self.owner_addr, self.chan_id).put_many(
                self.chan_id, list(values), self.maxsize, t)

    def get(self, timeout: Optional[float] = None) -> Any:
        import time

        from ..util import tracing

        q = self._local()
        if q is None:
            raise RuntimeError(
                "DistChannel.get() outside the owner process (SPSC: the "
                f"consumer owns {self.chan_id[:8]} at {self.owner_addr})"
            )
        with tracing.span_if_traced(
                "channel_recv", {"channel": self.chan_id[:8]}):
            t0 = time.perf_counter()
            try:
                return q.get(timeout=timeout)
            finally:
                # waits are recorded even when the get times out — an
                # Empty after a full timeout IS the stall being measured
                _recv_wait.observe(time.perf_counter() - t0)

    def close(self) -> None:
        """Owner side: drop the registry queue (one-shot result channels
        call this after their single read, or executions would leak one
        queue each)."""
        if service_address() == self.owner_addr:
            _registry.drop(self.chan_id)
        with _state_lock:
            w = _writers.pop((self.owner_addr, self.chan_id), None)
        if w is not None:
            w.close()

    def __reduce__(self):
        return (DistChannel, (self.owner_addr, self.chan_id, self.maxsize))
