"""Typed identifiers for jobs, tasks, actors, objects, nodes and slices.

Design follows the reference's ID family (upstream ray `src/ray/common/id.h ::
BaseID/JobID/ActorID/TaskID/ObjectID`): fixed-width binary IDs with ownership
information embedded so that, given an ObjectID, the runtime can recover the
task that produced it and the job it belongs to without a directory lookup.

Layout (bytes):
    JobID    4   random
    NodeID   16  random
    SliceID  8   random          (TPU-native addition: a gang/slice identity)
    ActorID  16  = 12 random | 4 job
    TaskID   24  = 8 random  | 16 actor (nil actor for normal tasks)
    ObjectID 28  = 24 task   | 4 big-endian put/return index
"""

from __future__ import annotations

import os
import threading

__all__ = [
    "BaseID",
    "JobID",
    "NodeID",
    "SliceID",
    "ActorID",
    "TaskID",
    "ObjectID",
    "WorkerID",
    "PlacementGroupID",
]


class BaseID:
    """Immutable fixed-width binary identifier."""

    SIZE = 0
    __slots__ = ("_bytes", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._bytes = binary
        self._hash = hash((type(self).__name__, binary))

    @classmethod
    def generate(cls) -> "BaseID":
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\x00" * cls.SIZE)

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other) -> bool:
        return self._bytes < other._bytes

    def __hash__(self) -> int:
        return self._hash

    def __reduce__(self):
        # Rebuild through the constructor: the cached _hash is salted by
        # THIS process's PYTHONHASHSEED and must never cross a process
        # boundary (an unpickled id with a foreign hash silently misses
        # every dict lookup against locally-built ids).
        return (type(self), (self._bytes,))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._bytes.hex()})"


class JobID(BaseID):
    SIZE = 4
    _counter = 0
    _lock = threading.Lock()

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(cls.SIZE, "big"))

    @classmethod
    def next(cls) -> "JobID":
        with cls._lock:
            cls._counter += 1
            return cls.from_int(cls._counter)

    def to_int(self) -> int:
        return int.from_bytes(self._bytes, "big")


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class SliceID(BaseID):
    """Identity of a TPU slice / gang failure domain."""

    SIZE = 8


class PlacementGroupID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    SIZE = 16
    _RANDOM = 12

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(os.urandom(cls._RANDOM) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[self._RANDOM :])


class TaskID(BaseID):
    SIZE = 24
    _RANDOM = 8

    @classmethod
    def of(cls, actor_id: "ActorID | None" = None) -> "TaskID":
        actor = actor_id if actor_id is not None else ActorID.nil()
        return cls(os.urandom(cls._RANDOM) + actor.binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[self._RANDOM :])

    def is_actor_task(self) -> bool:
        return not self.actor_id().is_nil()


class ObjectID(BaseID):
    SIZE = 28
    _INDEX_BYTES = 4
    MAX_INDEX = 2**32 - 1

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        if not 0 <= index <= cls.MAX_INDEX:
            raise ValueError(f"return index out of range: {index}")
        return cls(task_id.binary() + index.to_bytes(cls._INDEX_BYTES, "big"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Puts share the index space with returns; the high bit marks a put.
        return cls.for_task_return(task_id, put_index | 0x80000000)

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def index(self) -> int:
        return int.from_bytes(self._bytes[TaskID.SIZE :], "big") & 0x7FFFFFFF

    def is_put(self) -> bool:
        return bool(self._bytes[TaskID.SIZE] & 0x80)
