"""Cluster scheduling policies.

Equivalent of the reference's scheduling policy stack (upstream ray
`src/ray/raylet/scheduling/cluster_resource_scheduler.cc`,
`policy/hybrid_scheduling_policy.cc`, `spread_scheduling_policy.cc`,
`node_affinity_scheduling_policy.cc`, bundle packing in
`policy/bundle_scheduling_policy.cc`): resource-shape feasibility + node
selection over the eventually-consistent cluster view.

TPU-native difference: nodes can carry ICI topology coordinates, and demands
can be ``TopologyRequest`` shapes; sub-slice packing is delegated to
``ray_tpu.sched.topology`` which understands torus geometry.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from .config import config
from .control_plane import ControlPlane, NodeInfo, NodeState
from .ids import NodeID
from .task_spec import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SchedulingStrategy,
    SpreadSchedulingStrategy,
    TaskSpec,
)


def _feasible(node: NodeInfo, demand: Dict[str, float]) -> bool:
    return all(node.resources_total.get(k, 0.0) >= v for k, v in demand.items())


def _available(node: NodeInfo, demand: Dict[str, float]) -> bool:
    return all(node.resources_available.get(k, 0.0) >= v for k, v in demand.items())


def _utilization(node: NodeInfo) -> float:
    scores = []
    for key, total in node.resources_total.items():
        if total > 0:
            used = total - node.resources_available.get(key, 0.0)
            scores.append(used / total)
    return max(scores) if scores else 0.0


class ClusterScheduler:
    """Select a node for a task spec. Stateless over the control-plane view."""

    def __init__(self, control_plane: ControlPlane, spread_threshold: float = 0.5):
        self._cp = control_plane
        self._spread_threshold = spread_threshold
        self._rr_counter = 0

    def select_node(
        self,
        spec: TaskSpec,
        preferred_node: Optional[NodeID] = None,
        pg_table: Optional[Dict] = None,
    ) -> Optional[NodeID]:
        """Return a node for this task, or None if infeasible/unavailable now.

        Raises ValueError for permanently infeasible demands (no ALIVE node
        could ever satisfy the shape) so callers can fail fast instead of
        queueing forever — matching the reference's infeasible-task warning.
        Exception: a hard-label constraint NO alive node carries returns
        None (stays pending) rather than raising — a labeled node may join
        or be autoscaled moments later, and labels (unlike resource shapes)
        carry no capacity bound to prove infeasibility against. Once
        label-matching nodes exist, an oversized demand fails fast as usual.
        """
        demand = spec.options.resource_demand()
        strategy = spec.options.scheduling_strategy
        nodes = self._cp.alive_nodes()
        if not nodes:
            return None

        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            if pg_table is None:
                return None
            node_id = pg_table.get((strategy.placement_group_id, strategy.bundle_index))
            return node_id

        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            node = self._cp.get_node(strategy.node_id)
            alive = node is not None and node.state is NodeState.ALIVE
            if alive and _feasible(node, demand) and _available(node, demand):
                return node.node_id
            if strategy.soft:
                return self._hybrid(nodes, demand, preferred_node)
            if not alive:
                raise ValueError(
                    f"task {spec.name} requires node "
                    f"{strategy.node_id.hex()[:8]} which is not alive"
                )
            return None

        if isinstance(strategy, NodeLabelSchedulingStrategy):
            labeled = [
                n for n in nodes if strategy._matches(strategy.hard, n.labels)
            ]
            if not labeled:
                # Stay pending: a matching node may join (worker host,
                # autoscaled provider node carrying labels) moments later —
                # the reference keeps label-gated tasks as pending demand
                # rather than failing them.
                return None
            hard = [n for n in labeled if _feasible(n, demand)]
            if not hard:
                # labeled nodes exist but none can EVER fit the demand:
                # same fail-fast contract as the unlabeled infeasible path
                raise ValueError(
                    f"task {spec.name} demand {demand} is infeasible on "
                    f"every node matching hard labels {strategy.hard}"
                )
            preferred = [
                n for n in hard if strategy._matches(strategy.soft, n.labels)
            ]
            for pool in (preferred, hard):
                avail = [n for n in pool if _available(n, demand)]
                if avail:
                    return min(avail, key=_utilization).node_id
            return None  # feasible but busy: wait

        feasible = [n for n in nodes if _feasible(n, demand)]
        if not feasible:
            raise ValueError(
                f"task {spec.name} demand {demand} is infeasible on every alive node"
            )

        if isinstance(strategy, SpreadSchedulingStrategy):
            return self._spread(feasible, demand)
        return self._hybrid(nodes, demand, preferred_node)

    # -- policies -----------------------------------------------------------
    def _hybrid(
        self,
        nodes: List[NodeInfo],
        demand: Dict[str, float],
        preferred_node: Optional[NodeID],
    ) -> Optional[NodeID]:
        """Local-first below the utilization threshold, else best (least
        utilized) available node — the reference's hybrid policy shape."""
        if preferred_node is not None:
            local = self._cp.get_node(preferred_node)
            if (
                local is not None
                and local.state is NodeState.ALIVE
                and _feasible(local, demand)
                and _available(local, demand)
                and _utilization(local) < self._spread_threshold
            ):
                return local.node_id
        candidates = [n for n in nodes if _feasible(n, demand) and _available(n, demand)]
        if not candidates:
            return None
        candidates.sort(key=_utilization)
        # top-k random among least-utilized to avoid herd behavior
        k = max(1, int(len(candidates) * config.scheduler_top_k_fraction))
        return random.choice(candidates[:k]).node_id

    def _spread(self, feasible: List[NodeInfo], demand: Dict[str, float]) -> Optional[NodeID]:
        available = [n for n in feasible if _available(n, demand)]
        if not available:
            return None
        self._rr_counter += 1
        ordered = sorted(available, key=lambda n: (_utilization(n), n.node_id.binary()))
        return ordered[self._rr_counter % len(ordered)].node_id
