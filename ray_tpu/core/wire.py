"""Wire protocol: framed, versioned messages for cross-process control RPC.

Reference analogue: `src/ray/protobuf/*.proto` + the gRPC plumbing in
`src/ray/rpc/` — the reference serializes every control-plane surface so
daemons on different hosts interoperate. TPU-native scope: the DATA plane
here is XLA collectives over ICI (jax.distributed), which needs no runtime
wire format; what must serialize is the CONTROL plane (node/actor/job/KV
tables, object locations). This module is that wire format.

Frame layout (all integers big-endian):

    [4B length] [1B version] [1B msg type] [length-6 bytes payload]

Payload is pickle protocol 5 of a plain dict (schema per message type
below). Pickle-over-TCP is acceptable here for the same reason the
reference trusts protobuf-over-gRPC: the control plane is an internal,
mutually-trusted surface, never exposed to user traffic.

Message types:
    REQUEST  {"id": int, "method": str, "args": tuple, "kwargs": dict}
    RESPONSE {"id": int, "ok": bool, "value": Any} |
             {"id": int, "ok": False, "error": str, "exc": Exception}
    EVENT    {"channel": str, "message": Any}   (server -> client push)
    BLOB     [8B req id][8B offset][raw bytes]  (zero-copy chunk lane:
             the payload is NOT pickled — the sender scatter-gathers a
             header plus a memoryview, the receiver recv_intos straight
             into a caller-provided buffer at the carried offset)
"""

from __future__ import annotations

import pickle
import socket
import struct
from typing import Any, Callable, Optional, Tuple

WIRE_VERSION = 1

MSG_REQUEST = 1
MSG_RESPONSE = 2
MSG_EVENT = 3
MSG_BLOB = 4

_HEADER = struct.Struct(">IBB")  # length, version, type
_BLOB_PREFIX = struct.Struct(">QQ")  # request id, byte offset
_MAX_FRAME = 256 << 20  # 256 MB control message ceiling


class WireError(ConnectionError):
    pass


# Chaos fault-injection hook (ray_tpu.util.chaos): when set, consulted
# before every frame send/recv in THIS process. Raising OSError simulates
# a partition at the RPC socket layer; sleeping simulates link delay.
_fault_injector: Optional[Callable[[socket.socket, str], None]] = None


def set_fault_injector(fn: Optional[Callable[[socket.socket, str], None]]) -> None:
    """Install (or clear, with None) the process-wide wire fault hook."""
    global _fault_injector
    _fault_injector = fn


def send_msg(sock: socket.socket, msg_type: int, payload: Any) -> None:
    inj = _fault_injector
    if inj is not None:
        inj(sock, "send")
    body = pickle.dumps(payload, protocol=5)
    if len(body) + 2 > _MAX_FRAME:
        raise WireError(f"frame too large: {len(body)} bytes")
    sock.sendall(_HEADER.pack(len(body) + 2, WIRE_VERSION, msg_type) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise WireError("connection closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> Tuple[int, Any]:
    """-> (msg_type, payload). Raises WireError on close/corruption."""
    inj = _fault_injector
    if inj is not None:
        inj(sock, "recv")
    header = _recv_exact(sock, _HEADER.size)
    length, version, msg_type = _HEADER.unpack(header)
    if version != WIRE_VERSION:
        raise WireError(f"wire version {version} != {WIRE_VERSION}")
    if length < 2 or length > _MAX_FRAME:
        raise WireError(f"bad frame length {length}")
    body = _recv_exact(sock, length - 2)
    return msg_type, pickle.loads(body)


def send_blob(sock: socket.socket, req_id: int, offset: int,
              view: "memoryview | bytes | bytearray") -> None:
    """Send a MSG_BLOB frame without copying or pickling the payload.

    The kernel gathers the 22-byte header and the data view in one
    sendmsg; on a short write the remainder is completed with sendall
    over sub-views, still copy-free on the Python side.
    """
    inj = _fault_injector
    if inj is not None:
        inj(sock, "send")
    data = memoryview(view)
    if data.ndim != 1 or data.format != "B":
        data = data.cast("B")
    n = len(data)
    if n + 2 + _BLOB_PREFIX.size > _MAX_FRAME:
        raise WireError(f"blob frame too large: {n} bytes")
    hdr = _HEADER.pack(n + 2 + _BLOB_PREFIX.size, WIRE_VERSION, MSG_BLOB)
    prefix = _BLOB_PREFIX.pack(req_id, offset)
    sent = sock.sendmsg([hdr, prefix, data])
    skip = len(hdr) + len(prefix)
    if sent < skip:
        sock.sendall((hdr + prefix)[sent:])
        sent = skip
    if sent - skip < n:
        sock.sendall(data[sent - skip:])


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    while view.nbytes:
        k = sock.recv_into(view, view.nbytes)
        if not k:
            raise WireError("connection closed mid-blob")
        view = view[k:]


def recv_frame_into(
    sock: socket.socket,
    sink_for: Callable[[int, int, int], memoryview],
) -> Tuple[int, Any]:
    """recv_msg variant that lands MSG_BLOB payloads in caller memory.

    Non-blob frames behave exactly like recv_msg. For a blob frame the
    caller's `sink_for(req_id, offset, nbytes)` must return a writable
    memoryview of exactly `nbytes`; the payload is recv_into'd there and
    the return value is (MSG_BLOB, (req_id, offset, nbytes)).
    """
    inj = _fault_injector
    if inj is not None:
        inj(sock, "recv")
    header = _recv_exact(sock, _HEADER.size)
    length, version, msg_type = _HEADER.unpack(header)
    if version != WIRE_VERSION:
        raise WireError(f"wire version {version} != {WIRE_VERSION}")
    if length < 2 or length > _MAX_FRAME:
        raise WireError(f"bad frame length {length}")
    if msg_type != MSG_BLOB:
        body = _recv_exact(sock, length - 2)
        return msg_type, pickle.loads(body)
    if length < 2 + _BLOB_PREFIX.size:
        raise WireError(f"short blob frame: {length}")
    req_id, offset = _BLOB_PREFIX.unpack(_recv_exact(sock, _BLOB_PREFIX.size))
    nbytes = length - 2 - _BLOB_PREFIX.size
    sink = sink_for(req_id, offset, nbytes)
    if sink.nbytes != nbytes:
        raise WireError(f"blob sink mismatch: {sink.nbytes} != {nbytes}")
    _recv_exact_into(sock, sink)
    return MSG_BLOB, (req_id, offset, nbytes)
