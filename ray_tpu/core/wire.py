"""Wire protocol: framed, versioned messages for cross-process control RPC.

Reference analogue: `src/ray/protobuf/*.proto` + the gRPC plumbing in
`src/ray/rpc/` — the reference serializes every control-plane surface so
daemons on different hosts interoperate. TPU-native scope: the DATA plane
here is XLA collectives over ICI (jax.distributed), which needs no runtime
wire format; what must serialize is the CONTROL plane (node/actor/job/KV
tables, object locations). This module is that wire format.

Frame layout (all integers big-endian):

    [4B length] [1B version] [1B msg type] [length-6 bytes payload]

Payload is pickle protocol 5 of a plain dict (schema per message type
below). Pickle-over-TCP is acceptable here for the same reason the
reference trusts protobuf-over-gRPC: the control plane is an internal,
mutually-trusted surface, never exposed to user traffic.

Message types:
    REQUEST  {"id": int, "method": str, "args": tuple, "kwargs": dict}
    RESPONSE {"id": int, "ok": bool, "value": Any} |
             {"id": int, "ok": False, "error": str, "exc": Exception}
    EVENT    {"channel": str, "message": Any}   (server -> client push)
"""

from __future__ import annotations

import pickle
import socket
import struct
from typing import Any, Callable, Optional, Tuple

WIRE_VERSION = 1

MSG_REQUEST = 1
MSG_RESPONSE = 2
MSG_EVENT = 3

_HEADER = struct.Struct(">IBB")  # length, version, type
_MAX_FRAME = 256 << 20  # 256 MB control message ceiling


class WireError(ConnectionError):
    pass


# Chaos fault-injection hook (ray_tpu.util.chaos): when set, consulted
# before every frame send/recv in THIS process. Raising OSError simulates
# a partition at the RPC socket layer; sleeping simulates link delay.
_fault_injector: Optional[Callable[[socket.socket, str], None]] = None


def set_fault_injector(fn: Optional[Callable[[socket.socket, str], None]]) -> None:
    """Install (or clear, with None) the process-wide wire fault hook."""
    global _fault_injector
    _fault_injector = fn


def send_msg(sock: socket.socket, msg_type: int, payload: Any) -> None:
    inj = _fault_injector
    if inj is not None:
        inj(sock, "send")
    body = pickle.dumps(payload, protocol=5)
    if len(body) + 2 > _MAX_FRAME:
        raise WireError(f"frame too large: {len(body)} bytes")
    sock.sendall(_HEADER.pack(len(body) + 2, WIRE_VERSION, msg_type) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise WireError("connection closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> Tuple[int, Any]:
    """-> (msg_type, payload). Raises WireError on close/corruption."""
    inj = _fault_injector
    if inj is not None:
        inj(sock, "recv")
    header = _recv_exact(sock, _HEADER.size)
    length, version, msg_type = _HEADER.unpack(header)
    if version != WIRE_VERSION:
        raise WireError(f"wire version {version} != {WIRE_VERSION}")
    if length < 2 or length > _MAX_FRAME:
        raise WireError(f"bad frame length {length}")
    body = _recv_exact(sock, length - 2)
    return msg_type, pickle.loads(body)
