"""Per-node agent: worker pool, local dispatch, resource accounting, actors.

Equivalent of the reference's raylet (upstream ray `src/ray/raylet/
node_manager.cc :: NodeManager`, `worker_pool.cc`, `local_task_manager.cc`,
`dependency_manager.cc`): grants execution to tasks once their dependencies
are local and resources are acquired, runs them on its worker pool, seals
returns into the node object store and reports completion to the owner.

TPU-native design decision (deliberate divergence from the reference): on a
TPU host the device is owned by ONE process, so device-tasks execute on a
*thread* pool inside the device-owning process — JAX/XLA dispatch releases
the GIL, so threads give parallelism where it matters while keeping every
task in the device process. A separate *process* pool (see process_pool.py)
handles CPU-heavy Python data tasks, mirroring the reference's worker
processes, with the shared-memory store as the object plane.
"""

from __future__ import annotations

import queue
import threading
import traceback
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from .actor_process import ActorProcessCrash
from .config import config
from .control_plane import ControlPlane, NodeInfo
from .ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from .logging import get_logger
from .metrics import Counter, Gauge
from .object_store import MemoryObjectStore, ObjectLostError, seal_value
from .task_spec import TaskKind, TaskSpec

logger = get_logger("node_agent")

_tasks_counter = Counter("ray_tpu_tasks_finished", "Tasks finished by outcome")
_running_gauge = Gauge("ray_tpu_tasks_running", "Tasks currently executing")
_actors_isolated_counter = Counter(
    "ray_tpu_actors_isolated",
    "Actor creations by isolation outcome (process / in_process / fallback).",
)
_pool_fallback_counter = Counter(
    "ray_tpu_pool_fallbacks",
    "CPU tasks that bypassed process isolation (unpicklable args/closure)",
)


class WorkerCrashedError(RuntimeError):
    """The worker executing the task died (killed, OOM, node failure)."""


class TaskCancelledError(RuntimeError):
    pass


@dataclass
class TaskResult:
    task_id: TaskID
    ok: bool
    values: Optional[List[Any]] = None  # one per return id
    error: Optional[BaseException] = None
    is_application_error: bool = False  # user exception vs system failure


DoneCallback = Callable[[TaskResult], None]


def _preboot_forkserver() -> None:
    """Boot the multiprocessing forkserver without spawning any worker:
    the server process launches via `-c` and never reads the driver's
    __main__, so this is safe to run concurrently with driver code. The
    first real worker spawn then skips the ~multi-second server boot."""
    try:
        from .process_pool import _mp_context

        ctx = _mp_context()
        if ctx.get_start_method() != "forkserver":
            return
        from multiprocessing import forkserver

        forkserver.ensure_running()
    except Exception:  # noqa: BLE001 — warmup is best-effort
        logger.debug("forkserver preboot failed", exc_info=True)


def admits(total: Dict[str, float], available: Dict[str, float],
           demand: Dict[str, float], spread_threshold: float) -> bool:
    """The bottom-up local-admission rule (shared by NodeAgent.try_admit
    and the scale harness's simulated agents): feasible against totals,
    available right now, and current utilization under the spread
    threshold — exactly ClusterScheduler._hybrid's local-first gate, so a
    local admission matches the global policy's choice."""
    if not all(total.get(k, 0.0) >= v for k, v in demand.items()):
        return False
    if not all(available.get(k, 0.0) >= v - 1e-9 for k, v in demand.items()):
        return False
    util = max((1.0 - available.get(k, 0.0) / t
                for k, t in total.items() if t > 0), default=0.0)
    return util < spread_threshold


class ResourceTracker:
    """Node-local resource ledger with blocking acquire semantics."""

    def __init__(self, total: Dict[str, float]):
        self.total = dict(total)
        self._available = dict(total)
        self._lock = threading.Lock()

    def try_acquire(self, demand: Dict[str, float]) -> bool:
        with self._lock:
            if all(self._available.get(k, 0.0) >= v - 1e-9 for k, v in demand.items()):
                for k, v in demand.items():
                    self._available[k] = self._available.get(k, 0.0) - v
                return True
            return False

    def release(self, demand: Dict[str, float]) -> None:
        with self._lock:
            for k, v in demand.items():
                self._available[k] = min(
                    self.total.get(k, 0.0), self._available.get(k, 0.0) + v
                )

    def available(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._available)


def _is_async_actor(cls) -> bool:
    """An actor class with ANY async method runs on an asyncio event loop
    (reference: async actors in `core_worker.cc` / `actor.py` — the
    presence of coroutine methods selects the event-loop execution mode).
    getmembers walks the MRO, so inherited async methods count too."""
    import inspect

    if not inspect.isclass(cls):
        return False
    return any(
        inspect.iscoroutinefunction(m)
        for _, m in inspect.getmembers(cls, callable)
    )


class _ActorRunner:
    """Dedicated execution lane for one actor: FIFO mailbox + instance state.

    Reference analogue: the actor worker's task queue with in-order execution
    (`src/ray/core_worker/transport/task_receiver.cc` ordered scheduling).
    """

    def __init__(self, actor_id: ActorID, max_concurrency: int = 1):
        self.actor_id = actor_id
        self.instance: Any = None
        self.process = None  # ActorProcess when isolated (actor_process.py)
        self.held_resources: Dict[str, float] = {}
        self.mailbox: "queue.Queue[Optional[Tuple[TaskSpec, Callable[[], None]]]]" = queue.Queue()
        self.dead = False
        self.death_cause: Optional[BaseException] = None
        self.threads: List[threading.Thread] = []
        self.max_concurrency = max(1, max_concurrency)
        # task ids whose done callbacks are registered but not yet claimed
        # by a runner lane — swept on kill so no caller hangs
        self.pending_ids: set = set()

    def start(self, run_one: Callable[["_ActorRunner", TaskSpec, Callable[[], None]], None]) -> None:
        for i in range(self.max_concurrency):
            t = threading.Thread(
                target=self._loop, args=(run_one,), daemon=True,
                name=f"actor-{self.actor_id.hex()[:8]}-{i}",
            )
            t.start()
            self.threads.append(t)

    def _loop(self, run_one):
        while True:
            item = self.mailbox.get()
            if item is None:
                return
            if item[0] == "__direct__":
                # compiled-graph fast path (ray_tpu.dag): a pre-bound
                # closure runs on the actor's lane with its instance,
                # skipping spec/scheduling/store — actor-serial semantics
                # are preserved because it's the same mailbox.
                try:
                    item[1](self.instance)
                except Exception:  # noqa: BLE001 — closure handles user errors
                    logger.exception("direct actor submit failed")
                continue
            spec, release = item
            run_one(self, spec, release)

    def stop(self) -> None:
        for _ in self.threads:
            self.mailbox.put(None)


class _AsyncActorRunner(_ActorRunner):
    """Event-loop lane for an async actor: tasks run as coroutines on ONE
    asyncio loop; max_concurrency bounds concurrent AWAITS (a semaphore),
    so a replica overlaps slow requests wherever they await instead of
    burning a thread per slot (reference: the async actor event loop in
    `core_worker.cc`; concurrency groups collapse to the semaphore)."""

    def start(self, run_one) -> None:
        import asyncio

        self.loop = asyncio.new_event_loop()
        self._run_one = run_one

        def loop_main():
            asyncio.set_event_loop(self.loop)
            self.loop.run_forever()

        loop_thread = threading.Thread(
            target=loop_main, daemon=True,
            name=f"actor-loop-{self.actor_id.hex()[:8]}",
        )
        loop_thread.start()
        # the semaphore must be created ON the loop
        fut = asyncio.run_coroutine_threadsafe(self._make_sem(), self.loop)
        fut.result(timeout=10)
        dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name=f"actor-dispatch-{self.actor_id.hex()[:8]}",
        )
        dispatcher.start()
        self.threads = [loop_thread, dispatcher]

    async def _make_sem(self):
        import asyncio

        self._sem = asyncio.Semaphore(self.max_concurrency)

    def _dispatch_loop(self) -> None:
        import asyncio

        while True:
            item = self.mailbox.get()
            if item is None:
                # cancel in-flight awaits so callers get actor-death errors
                # instead of hanging, then stop the loop
                def _cancel_and_stop():
                    for t in asyncio.all_tasks(self.loop):
                        t.cancel()
                    self.loop.call_soon(self.loop.stop)

                self.loop.call_soon_threadsafe(_cancel_and_stop)
                return
            asyncio.run_coroutine_threadsafe(self._handle(item), self.loop)

    async def _handle(self, item) -> None:
        import inspect

        async with self._sem:
            if item[0] == "__direct__":
                try:
                    res = item[1](self.instance)
                    if inspect.isawaitable(res):
                        await res
                except Exception:  # noqa: BLE001
                    logger.exception("direct async actor submit failed")
                return
            spec, _release = item
            await self._run_one(self, spec)


class NodeAgent:
    """One per (virtual or real) node."""

    def __init__(
        self,
        info: NodeInfo,
        control_plane: ControlPlane,
        object_directory: "ObjectDirectory",
        num_task_threads: Optional[int] = None,
    ):
        self.info = info
        self.node_id = info.node_id
        self._cp = control_plane
        self._directory = object_directory
        self.store = MemoryObjectStore()
        self.store.ledger_node = info.node_id.hex()
        # an object leaving this store must leave the directory too, or a
        # pull-through replica's advertisement outlives the replica and
        # sends pullers to a holder that no longer has the bytes
        self.store.on_evict = (
            lambda oid: object_directory.remove_location(oid, info.node_id))
        self.resources = ResourceTracker(info.resources_total)
        self._actors: Dict[ActorID, _ActorRunner] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        n_threads = num_task_threads or max(2, int(info.resources_total.get("CPU", 2)))
        self._task_queue: "queue.Queue[Optional[Tuple[TaskSpec, DoneCallback]]]" = queue.Queue()
        self._threads = [
            threading.Thread(target=self._worker_loop, daemon=True, name=f"worker-{i}")
            for i in range(n_threads)
        ]
        for t in self._threads:
            t.start()
        # tasks currently running, for cancellation/failure injection
        self._running: Dict[TaskID, threading.Event] = {}
        self._pending_actor_dones: Dict[TaskID, DoneCallback] = {}
        # per-item callbacks for streaming tasks, keyed by task id
        self._stream_cbs: Dict[TaskID, Callable[[int, ObjectID], None]] = {}
        # CPU-task process pool (config.worker_processes > 0): created lazily
        # on the first eligible task so thread-mode runtimes pay nothing —
        # but the forkserver itself pre-boots in the background at agent
        # creation (the reference PRESTARTS workers, worker_pool.cc), so
        # most of the spawn cost overlaps driver setup. Only the server
        # boots here: actually spawning workers would run the __main__
        # suppression window concurrently with arbitrary driver top-level
        # code (see process_pool._suppress_main_reimport) — worker spawns
        # stay inside explicit submission calls.
        self._pool = None
        self._pool_lock = threading.Lock()
        if config.worker_processes > 0 and config.prestart_worker_processes:
            threading.Thread(
                target=_preboot_forkserver, daemon=True, name="pool-warmup"
            ).start()
        # test hook: simulate a hung host (stops heartbeating, keeps running)
        self.suspend_heartbeat = False
        # remote control plane: bound each monitor-sweep heartbeat tightly
        # instead of the default call deadline (see _sync_load)
        from .rpc import RemoteControlPlane

        self._hb_kwargs = (
            {"_deadline_s": max(2.0, config.health_check_period_ms / 1000.0)}
            if isinstance(control_plane, RemoteControlPlane) else {}
        )

    # ------------------------------------------------------------------ api
    def try_admit(self, demand: Dict[str, float],
                  spread_threshold: Optional[float] = None) -> bool:
        """Bottom-up scheduling probe (reference: Ray's two-level local-
        first scheduler, arXiv:1712.05889 §4.2): would this node admit the
        demand right now, judged against the agent's OWN resource tracker
        — fresher than the control plane's eventually-consistent view.
        Mirrors ClusterScheduler._hybrid's local-first rule (feasible +
        available + utilization under the spread threshold), so a local
        admission is exactly the placement the global policy would have
        picked; anything else overflows to the ClusterScheduler. View-only:
        resources are still acquired by the executing worker, the same
        admission-vs-execution race the global path has."""
        if self._stopped.is_set():
            return False
        if spread_threshold is None:
            spread_threshold = float(config.scheduler_spread_threshold)
        return admits(self.resources.total, self.resources.available(),
                      demand, spread_threshold)

    def submit(self, spec: TaskSpec, done: DoneCallback,
               stream: Optional[Callable[[int, ObjectID], None]] = None) -> None:
        """Dispatch once dependencies are local. Resources are acquired by the
        executing worker thread (dependency-first, like the reference's
        dispatch order: args ready -> acquire -> pop worker).

        stream: per-item callback for num_returns="streaming" tasks,
        invoked as each yielded value seals into the store."""
        if self._stopped.is_set():
            done(TaskResult(spec.task_id, ok=False, error=WorkerCrashedError("node stopped")))
            return
        if stream is not None:
            with self._lock:
                self._stream_cbs[spec.task_id] = stream
        missing = [d for d in spec.dependencies if not self.store.contains(d)]
        if not missing:
            self._enqueue(spec, done)
            return
        remaining = {"n": len(missing)}
        lock = threading.Lock()

        def on_dep_ready() -> None:
            with lock:
                remaining["n"] -= 1
                if remaining["n"] != 0:
                    return
            self._enqueue(spec, done)

        for dep in missing:
            self._fetch_async(dep, on_dep_ready)

    def _enqueue(self, spec: TaskSpec, done: DoneCallback) -> None:
        if spec.kind is TaskKind.ACTOR_TASK:
            self._submit_actor_task(spec, done)
        else:
            self._task_queue.put((spec, done))

    # --------------------------------------------------------- normal tasks
    def _worker_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                item = self._task_queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is None:
                return
            spec, done = item
            demand = {} if spec.skip_node_resources else spec.options.resource_demand()
            # Block-wait for resources on this worker lane; the cluster
            # scheduler already sized placement to the node's view.
            while not self.resources.try_acquire(demand):
                if self._stopped.is_set():
                    done(TaskResult(spec.task_id, ok=False,
                                    error=WorkerCrashedError("node stopped")))
                    return
                threading.Event().wait(0.002)
            self._sync_load()
            try:
                result = self._execute(spec)
            finally:
                # Actor placement resources stay held for the actor's lifetime
                # (released by kill_actor / node stop), like a leased worker.
                hold = (
                    spec.kind is TaskKind.ACTOR_CREATION
                    and self.has_actor(spec.actor_id)
                )
                if hold:
                    with self._lock:
                        self._actors[spec.actor_id].held_resources = demand
                else:
                    self.resources.release(demand)
                self._sync_load()
            done(result)

    def _execute(self, spec: TaskSpec) -> TaskResult:
        if spec.kind is TaskKind.ACTOR_CREATION:
            return self._execute_actor_creation(spec)
        if spec.options.num_returns == "streaming":
            return self._execute_streaming(spec)
        kill_event = threading.Event()
        with self._lock:
            self._running[spec.task_id] = kill_event
        _running_gauge.add(1, {"node": self.node_id.hex()[:8]})
        try:
            args, kwargs = self._materialize_args(spec)
            values = self._call_user_function(spec, None, args, kwargs, kill_event)
            self._seal_returns(spec, values)
            _tasks_counter.inc(tags={"outcome": "ok"})
            return TaskResult(spec.task_id, ok=True, values=values)
        except WorkerCrashedError as e:
            _tasks_counter.inc(tags={"outcome": "crashed"})
            return TaskResult(spec.task_id, ok=False, error=e)
        except BaseException as e:  # noqa: BLE001 - user code may raise anything
            _tasks_counter.inc(tags={"outcome": "error"})
            return TaskResult(
                spec.task_id, ok=False, error=e, is_application_error=True
            )
        finally:
            _running_gauge.add(-1, {"node": self.node_id.hex()[:8]})
            with self._lock:
                self._running.pop(spec.task_id, None)

    def _execute_streaming(self, spec: TaskSpec) -> TaskResult:
        """Generator task: each yielded value seals into the store under
        ObjectID.for_task_return(task_id, i) and the owner's stream
        callback fires immediately — the consumer iterates while this
        loop still runs. Runs in-process (never on the worker-process
        pool: a generator cannot cross that boundary incrementally).
        On a mid-stream exception the already-sealed prefix stays valid;
        the owner surfaces the error after it."""
        kill_event = threading.Event()
        with self._lock:
            self._running[spec.task_id] = kill_event
            stream_cb = self._stream_cbs.pop(spec.task_id, None)
        _running_gauge.add(1, {"node": self.node_id.hex()[:8]})
        try:
            from .runtime_env import applied, resolve, validate

            renv = resolve(validate(spec.options.runtime_env), self._cp)
            args, kwargs = self._materialize_args(spec)
            # Streaming runs in-process (a generator can't cross the
            # worker-pool boundary incrementally), so the env applies to
            # this process for the stream's duration — same contract as
            # the pool worker, scoped to the generator's lifetime.
            with applied(renv):
                gen = spec.func(*args, **kwargs)
                if not hasattr(gen, "__next__"):
                    raise TypeError(
                        f"num_returns='streaming' task {spec.name} must be a "
                        f"generator; got {type(gen).__name__}"
                    )
                for i, value in enumerate(gen):
                    if kill_event.is_set():
                        raise WorkerCrashedError(
                            "worker killed during streaming")
                    oid = ObjectID.for_task_return(spec.task_id, i)
                    self.store.put(oid, seal_value(value, spec.name))
                    self.store.annotate(oid, creator_task=spec.name)
                    self._directory.add_location(oid, self.node_id)
                    if stream_cb is not None:
                        stream_cb(i, oid)
            _tasks_counter.inc(tags={"outcome": "ok"})
            return TaskResult(spec.task_id, ok=True, values=None)
        except WorkerCrashedError as e:
            _tasks_counter.inc(tags={"outcome": "crashed"})
            return TaskResult(spec.task_id, ok=False, error=e)
        except BaseException as e:  # noqa: BLE001 — user generators raise anything
            _tasks_counter.inc(tags={"outcome": "error"})
            return TaskResult(spec.task_id, ok=False, error=e,
                              is_application_error=True)
        finally:
            _running_gauge.add(-1, {"node": self.node_id.hex()[:8]})
            with self._lock:
                self._running.pop(spec.task_id, None)

    def _call_user_function(self, spec, instance, args, kwargs, kill_event):
        if kill_event.is_set():
            raise WorkerCrashedError("worker killed before execution")
        if spec.kind is TaskKind.ACTOR_TASK:
            func = getattr(instance, spec.method_name)
        else:
            func = spec.func
        ctx = getattr(spec, "trace_ctx", None)
        if ctx:
            # distributed tracing (util/tracing; reference:
            # tracing_helper's execute-side span): the execute span
            # parents under the submitter's span, and while it is
            # current, tasks THIS task submits chain into the same trace
            from ..util import tracing

            with tracing.start_span(
                f"execute:{spec.name}",
                {"task_id": spec.task_id.hex()[:16],
                 "node": self.node_id.hex()[:8],
                 "kind": spec.kind.value,
                 "attempt": spec.attempt},
                context=ctx,
            ):
                out = self._invoke(spec, func, args, kwargs)
        else:
            out = self._invoke(spec, func, args, kwargs)
        if kill_event.is_set():
            raise WorkerCrashedError("worker killed during execution")
        return self._shape_returns(spec, out)

    def _invoke(self, spec: TaskSpec, func, args, kwargs):
        """Route execution: stateless CPU-only tasks go to the worker-process
        pool when enabled (crash isolation, the reference's worker-process
        model); device tasks and actors stay on threads in the device-owning
        process (node_agent docstring). Tasks that can't cross the process
        boundary (unpicklable closures) fall back to in-process execution."""
        from .runtime_env import resolve, validate

        renv = validate(spec.options.runtime_env)
        # kv:// working_dir (shipped by a possibly-remote driver) becomes a
        # local cached extraction before the worker sees it
        renv = resolve(renv, self._cp)
        if (
            spec.kind is TaskKind.NORMAL
            and config.worker_processes > 0
            and spec.options.resource_demand().get("TPU", 0.0) <= 0.0
        ):
            from .process_pool import (
                TaskNotSerializableError,
                WorkerProcessCrash,
            )

            pool = self._ensure_pool()
            if pool is not None:
                try:
                    # sealed=True hands back the worker's pickled payload as
                    # SealedBytes without deserializing in this process —
                    # _seal_returns stores it as-is (single-return tasks;
                    # multi-return needs the tuple split, so it deserializes)
                    return pool.run(
                        func, tuple(args), dict(kwargs),
                        sealed=spec.options.num_returns == 1,
                        runtime_env=renv,
                    )
                except TaskNotSerializableError:
                    if renv:
                        # isolation was REQUESTED: never silently run without
                        raise
                    _pool_fallback_counter.inc(tags={"task": spec.name[:40]})
                    logger.debug(
                        "task %s not serializable; executing in-process",
                        spec.name,
                    )
                except WorkerProcessCrash as e:
                    raise WorkerCrashedError(str(e)) from e
        if renv:
            from .runtime_env import RuntimeEnvError

            raise RuntimeEnvError(
                f"task {spec.name} has a runtime_env but would execute "
                "in-process (device task, actor, or worker_processes=0): "
                "env isolation requires a worker process. Use job-level "
                "runtime_env for device work, or drop the constraint."
            )
        return func(*args, **kwargs)

    def _ensure_pool(self):
        with self._pool_lock:
            if self._pool is None and not self._stopped.is_set():
                from .process_pool import (
                    acquire_shared_pool,
                    register_inline_only_types,
                )

                try:
                    from ..api import ActorHandle
                    from .core_worker import ObjectRef

                    register_inline_only_types(ObjectRef, ActorHandle)
                except Exception:
                    pass
                try:
                    # refcounted process-wide singleton: virtual nodes share
                    # one OS process, so one pool serves them all
                    self._pool = acquire_shared_pool(config.worker_processes)
                except Exception as e:  # shm unavailable: stay on threads
                    logger.warning("process pool unavailable (%s); using threads", e)
                    self._pool = False
                if self._pool:
                    try:
                        # host-OOM guard (reference memory_monitor.cc):
                        # kills the newest pool task under memory pressure;
                        # it retries via the worker-crash path. The monitor
                        # is OPTIONAL — its failure must not disable the
                        # pool (or leak the acquire ref above).
                        self._pool.ensure_memory_monitor()
                    except Exception:  # noqa: BLE001
                        logger.warning("memory monitor unavailable",
                                       exc_info=True)
            return self._pool or None

    def _materialize_args(self, spec: TaskSpec) -> Tuple[tuple, dict]:
        from .core_worker import ObjectRef  # cycle: resolved at call time

        def resolve(v: Any) -> Any:
            if isinstance(v, ObjectRef):
                return self.store.get(v.object_id, timeout=30.0)
            return v

        args = tuple(resolve(a) for a in spec.args)
        kwargs = {k: resolve(v) for k, v in spec.kwargs.items()}
        return args, kwargs

    def _seal_returns(self, spec: TaskSpec, values: List[Any]) -> None:
        """Publish return values to the object plane, sealed.

        seal_value pickles host objects (SealedBytes) so the stored form can
        never alias live state the producer keeps mutating, and every get()
        deserializes a private copy — the serialization boundary the
        reference gets by construction from worker processes + plasma.
        jax.Array trees and already-sealed pool payloads pass through."""
        for oid, value in zip(spec.return_ids, values):
            self.store.put(oid, seal_value(value, spec.name))
            self.store.annotate(oid, creator_task=spec.name)
            self._directory.add_location(oid, self.node_id)

    # ---------------------------------------------------------------- actors
    def _should_isolate(self, spec: TaskSpec) -> bool:
        """Actor-isolation policy (reference: every actor IS a worker
        process). CPU actors with serial mailboxes isolate; device actors
        are exempt by contract (a child importing jax races the parent for
        the TPU client), and high-concurrency actors (serve replicas, trial
        runners — streaming returns, shared batchers) stay in-process."""
        if _is_async_actor(spec.func):
            # the event loop and its coroutines cannot cross an
            # ActorProcess boundary; async actors are in-process by mode
            return False
        if spec.options.in_process is not None:
            return not spec.options.in_process
        return (
            config.actor_processes
            and spec.options.resource_demand().get("TPU", 0.0) <= 0.0
            and spec.options.max_concurrency <= 1
        )

    def _build_actor_instance(self, spec: TaskSpec, args, kwargs):
        """-> (instance, actor_process_or_None), honoring the isolation
        policy with in-process fallback for unpicklable state."""
        if self._should_isolate(spec):
            from .actor_process import (
                ActorNotSerializableError,
                ActorProcess,
                _InstanceProxy,
            )
            from .runtime_env import resolve, validate

            try:
                proc = ActorProcess(
                    spec.func, args, kwargs,
                    max_concurrency=spec.options.max_concurrency,
                    runtime_env=resolve(
                        validate(spec.options.runtime_env), self._cp),
                )
                _actors_isolated_counter.inc(tags={"mode": "process"})
                return _InstanceProxy(
                    proc, getattr(spec.func, "__name__", "Actor")
                ), proc
            except ActorNotSerializableError as e:
                if spec.options.runtime_env or spec.options.in_process is False:
                    # isolation was explicitly REQUIRED (env isolation, or
                    # in_process=False for crash containment): silently
                    # running in the driver would defeat the request
                    raise
                _actors_isolated_counter.inc(tags={"mode": "fallback"})
                logger.debug(
                    "actor %s state can't cross a process boundary (%s); "
                    "running in-process", spec.name, e,
                )
        else:
            if spec.options.runtime_env:
                from .runtime_env import RuntimeEnvError

                # same strictness as the task path (node_agent._invoke):
                # an env that cannot be applied must not be silently dropped
                raise RuntimeEnvError(
                    f"actor {spec.name} has a runtime_env but would run "
                    "in-process (device actor / max_concurrency>1 / "
                    "in_process=True) where env isolation is impossible"
                )
            _actors_isolated_counter.inc(tags={"mode": "in_process"})
        return spec.func(*args, **kwargs), None

    def _execute_actor_creation(self, spec: TaskSpec) -> TaskResult:
        kill_event = threading.Event()
        with self._lock:
            self._running[spec.task_id] = kill_event
        try:
            args, kwargs = self._materialize_args(spec)
            if _is_async_actor(spec.func):
                runner = _AsyncActorRunner(
                    spec.actor_id, spec.options.max_concurrency)
                run_one = self._run_actor_task_async
            else:
                runner = _ActorRunner(spec.actor_id, spec.options.max_concurrency)
                run_one = self._run_actor_task
            runner.instance, runner.process = self._build_actor_instance(
                spec, args, kwargs
            )
            # the node may have died while __init__ ran: report the crash so
            # the owner reschedules instead of marking the actor ALIVE here
            if kill_event.is_set() or self._stopped.is_set():
                if runner.process is not None:
                    runner.process.terminate()
                raise WorkerCrashedError("node died during actor creation")
            runner.start(run_one)
            with self._lock:
                self._actors[spec.actor_id] = runner
            self._seal_returns(spec, [None])
            _tasks_counter.inc(tags={"outcome": "ok"})
            return TaskResult(spec.task_id, ok=True, values=[None])
        except (WorkerCrashedError, ActorProcessCrash) as e:
            _tasks_counter.inc(tags={"outcome": "crashed"})
            return TaskResult(spec.task_id, ok=False,
                              error=WorkerCrashedError(str(e)))
        except BaseException as e:  # noqa: BLE001
            _tasks_counter.inc(tags={"outcome": "error"})
            return TaskResult(spec.task_id, ok=False, error=e, is_application_error=True)
        finally:
            with self._lock:
                self._running.pop(spec.task_id, None)

    def _submit_actor_task(self, spec: TaskSpec, done: DoneCallback) -> None:
        # dead-check and registration are ONE critical section against
        # kill_actor's sweep: checking dead outside it would let a kill
        # land between the check and the registration, leaving a done
        # callback nothing will ever claim (caller hangs)
        with self._lock:
            runner = self._actors.get(spec.actor_id)
            dead = runner is None or runner.dead
            if not dead:
                # actor tasks do not re-acquire placement resources
                self._pending_actor_dones[spec.task_id] = done
                runner.pending_ids.add(spec.task_id)
        if dead:
            cause = runner.death_cause if runner else None
            done(TaskResult(spec.task_id, ok=False,
                            error=WorkerCrashedError(f"actor is dead: {cause}")))
            return
        runner.mailbox.put((spec, lambda: None))

    def _run_actor_task(self, runner: _ActorRunner, spec: TaskSpec, release: Callable[[], None]) -> None:
        done = self._pending_actor_dones.pop(spec.task_id, None)
        runner.pending_ids.discard(spec.task_id)
        if done is None:
            return
        if runner.dead:
            done(TaskResult(spec.task_id, ok=False,
                            error=WorkerCrashedError(f"actor is dead: {runner.death_cause}")))
            return
        kill_event = threading.Event()
        with self._lock:
            self._running[spec.task_id] = kill_event
        try:
            args, kwargs = self._materialize_args(spec)
            values = self._call_user_function(
                spec, runner.instance, args, kwargs, kill_event
            )
            self._seal_returns(spec, values)
            _tasks_counter.inc(tags={"outcome": "ok"})
            done(TaskResult(spec.task_id, ok=True, values=values))
        except (WorkerCrashedError, ActorProcessCrash) as e:
            runner.dead = True
            runner.death_cause = e
            _tasks_counter.inc(tags={"outcome": "crashed"})
            done(TaskResult(spec.task_id, ok=False,
                            error=WorkerCrashedError(str(e))))
        except BaseException as e:  # noqa: BLE001
            _tasks_counter.inc(tags={"outcome": "error"})
            done(TaskResult(spec.task_id, ok=False, error=e, is_application_error=True))
        finally:
            with self._lock:
                self._running.pop(spec.task_id, None)

    @staticmethod
    def _shape_returns(spec: TaskSpec, out: Any) -> List[Any]:
        """num_returns shaping shared by the thread and event-loop lanes."""
        n = spec.options.num_returns
        if n == 1:
            return [out]
        if out is None and n == 0:
            return []
        if not isinstance(out, tuple) or len(out) != n:
            raise ValueError(f"task {spec.name} declared num_returns={n} but "
                             f"returned {type(out).__name__}")
        return list(out)

    async def _run_actor_task_async(self, runner: "_AsyncActorRunner",
                                    spec: TaskSpec) -> None:
        """Async-actor variant of _run_actor_task: the method's coroutine is
        awaited on the actor's event loop, so overlapping requests
        interleave at their await points. Arg materialization and return
        sealing (pickling) run in a thread — a large payload must not
        freeze every other in-flight request on the loop. Cancellation
        (actor kill) surfaces as an actor-death error, never a hang."""
        import asyncio
        import inspect

        done = self._pending_actor_dones.pop(spec.task_id, None)
        runner.pending_ids.discard(spec.task_id)
        if done is None:
            return
        if runner.dead:
            done(TaskResult(spec.task_id, ok=False,
                            error=WorkerCrashedError(f"actor is dead: {runner.death_cause}")))
            return
        kill_event = threading.Event()
        with self._lock:
            self._running[spec.task_id] = kill_event
        try:
            args, kwargs = await asyncio.to_thread(self._materialize_args, spec)
            func = getattr(runner.instance, spec.method_name)
            out = func(*args, **kwargs)
            if inspect.isawaitable(out):
                out = await out
            if kill_event.is_set():
                raise WorkerCrashedError("worker killed during execution")
            values = self._shape_returns(spec, out)
            await asyncio.to_thread(self._seal_returns, spec, values)
            _tasks_counter.inc(tags={"outcome": "ok"})
            done(TaskResult(spec.task_id, ok=True, values=values))
        except asyncio.CancelledError:
            _tasks_counter.inc(tags={"outcome": "crashed"})
            done(TaskResult(spec.task_id, ok=False,
                            error=WorkerCrashedError(
                                f"actor stopped: {runner.death_cause}")))
        except (WorkerCrashedError, ActorProcessCrash) as e:
            runner.dead = True
            runner.death_cause = e
            _tasks_counter.inc(tags={"outcome": "crashed"})
            done(TaskResult(spec.task_id, ok=False,
                            error=WorkerCrashedError(str(e))))
        except BaseException as e:  # noqa: BLE001
            _tasks_counter.inc(tags={"outcome": "error"})
            done(TaskResult(spec.task_id, ok=False, error=e,
                            is_application_error=True))
        finally:
            with self._lock:
                self._running.pop(spec.task_id, None)

    def submit_direct(self, actor_id: ActorID, fn: Callable[[Any], None]) -> None:
        """Enqueue fn(instance) on the actor's mailbox (compiled-graph path).
        Raises if the actor is not alive here."""
        with self._lock:
            runner = self._actors.get(actor_id)
        if runner is None or runner.dead:
            raise WorkerCrashedError(f"actor {actor_id} is not alive on this node")
        runner.mailbox.put(("__direct__", fn))

    def kill_actor(self, actor_id: ActorID, cause: str = "killed") -> bool:
        with self._lock:
            runner = self._actors.get(actor_id)
            if runner is None:
                return False
            # dead flips INSIDE the lock: paired with _submit_actor_task's
            # locked check-and-register, so no registration can slip
            # between this and the sweep below
            runner.dead = True
            runner.death_cause = WorkerCrashedError(cause)
        runner.stop()
        if runner.process is not None:
            runner.process.terminate()
        if runner.held_resources:
            self.resources.release(runner.held_resources)
            runner.held_resources = {}
            self._sync_load()
        self._sweep_actor_pending(runner)
        return True

    def _sweep_actor_pending(self, runner: _ActorRunner) -> None:
        """Fail any task whose done callback is still registered for a
        stopped runner — a callback a dead lane will never claim (e.g. a
        coroutine cancelled before its first step) must not hang its
        caller. Callbacks collected under the lock, invoked outside it
        (done callbacks re-enter the agent, e.g. kill on creation)."""
        to_fail = []
        with self._lock:
            for task_id in list(runner.pending_ids):
                runner.pending_ids.discard(task_id)
                done = self._pending_actor_dones.pop(task_id, None)
                if done is not None:
                    to_fail.append((task_id, done))
        for task_id, done in to_fail:
            done(TaskResult(task_id, ok=False, error=WorkerCrashedError(
                f"actor is dead: {runner.death_cause}")))

    def has_actor(self, actor_id: ActorID) -> bool:
        with self._lock:
            return actor_id in self._actors and not self._actors[actor_id].dead

    # ------------------------------------------------------- object transfer
    def _fetch_async(self, object_id: ObjectID, on_ready: Callable[[], None]) -> None:
        """Pull an object from a remote node's store (the PullManager path,
        `src/ray/object_manager/pull_manager.cc`). In-process 'nodes' share an
        address space so the pull is a store-to-store handoff with byte
        accounting; multi-process nodes go through the shm/rpc plane."""

        def attempt() -> None:
            if self.store.contains(object_id):
                on_ready()
                return
            holder = self._directory.locate(object_id, exclude=self.node_id)
            if holder is not None:
                try:
                    # raw: a SealedBytes stays sealed across the hop, so the
                    # fresh-copy-per-get guarantee survives multi-node paths
                    value = holder.store.get_raw(object_id, timeout=5.0)
                    self.store.put(object_id, value)
                    self._directory.add_location(object_id, self.node_id)
                    on_ready()
                    return
                except (TimeoutError, ObjectLostError):
                    pass
            # not yet anywhere: wait for a seal notification via the directory
            self._directory.subscribe_once(object_id, attempt)

        attempt()

    # ------------------------------------------------------------- lifecycle
    def _sync_load(self) -> None:
        if self.suspend_heartbeat:
            return
        try:
            # short deadline when the control plane is remote: the head
            # monitor loop pumps every agent serially, so one unreachable
            # head must not stall the sweep for the full call deadline
            self._cp.heartbeat(self.node_id, self.resources.available(),
                               **self._hb_kwargs)
        except (ConnectionError, RuntimeError):
            pass  # head restarting; the next sweep retries

    def kill_running_tasks(self) -> None:
        """Failure injection: crash every task currently executing here."""
        with self._lock:
            events = list(self._running.values())
        for e in events:
            e.set()

    # ------------------------------------------------------ profiling plane
    # The node-local half of profile_start/profile_fetch: the head (via
    # cross_host.HeadService) resolves a node and calls these — locally on
    # its own agent, over the dispatch socket for joined hosts. pid 0 (or
    # this process's pid) targets the agent process itself, where threaded
    # tasks and device actors run; a subprocess child (actor process /
    # pool worker, see profilable_pids) is driven by the signal handlers
    # util/profiler.install_child_handlers registered at its startup — so
    # a HUNG child can still be stack-dumped (faulthandler needs no GIL).

    def _session(self) -> str:
        from .logging import session_dir

        return session_dir()

    def profilable_pids(self) -> Dict[str, Any]:
        """Every pid profiling can target on this node: the agent process
        plus live subprocess actor/pool workers."""
        import os

        actors: Dict[str, int] = {}
        with self._lock:
            runners = list(self._actors.items())
        for actor_id, runner in runners:
            proc = getattr(runner, "process", None)
            pid = getattr(proc, "pid", None) if proc is not None else None
            if pid:
                actors[actor_id.hex()] = int(pid)
        pool_pids: List[int] = []
        with self._pool_lock:
            pool = self._pool
        if pool:
            try:
                pool_pids = pool.worker_pids()
            except Exception:
                pool_pids = []
        return {"agent": os.getpid(), "actors": actors, "pool": pool_pids}

    def profile_start(self, pid: int = 0, duration_s: float = 5.0,
                      hz: Optional[float] = None, kind: str = "cpu",
                      logdir: str = "") -> Dict[str, Any]:
        """Open a profiling window. kind="cpu" starts the sampling
        profiler (in-process, or SIGUSR1-toggled in a child); kind="jax"
        captures an xplane device trace into `logdir` for `duration_s`."""
        import os

        from ..util import profiler

        pid = int(pid or 0)
        if kind == "jax":
            logdir = logdir or os.path.join(self._session(), "jax_trace")
            self._start_jax_trace(logdir, float(duration_s or 5.0))
            return {"pid": os.getpid(), "kind": "jax", "logdir": logdir}
        if pid in (0, os.getpid()):
            out = profiler.start_profile(duration_s=duration_s, hz=hz)
            return {**out, "kind": "cpu"}
        profiler.toggle_child_profile(pid)
        return {"pid": pid, "kind": "cpu", "running": True}

    def profile_fetch(self, pid: int = 0, kind: str = "cpu") -> Dict[str, Any]:
        """Collect: kind="stack" returns a live all-threads dump (works
        on a hung child via the faulthandler signal); kind="cpu" stops
        the sampling window and returns the collapsed-stack profile."""
        import os

        from ..util import profiler

        pid = int(pid or 0)
        if kind == "pids":
            return self.profilable_pids()
        if kind == "stack":
            if pid in (0, os.getpid()):
                dump = profiler.dump_stacks()
                return {"pid": os.getpid(), "kind": "stack",
                        "threads": len(dump["threads"]),
                        "text": profiler.format_stacks(dump), "dump": dump}
            text = profiler.dump_child(pid, self._session())
            return {"pid": pid, "kind": "stack", "text": text}
        if pid in (0, os.getpid()):
            out = profiler.fetch_profile()
            return {"pid": out["pid"], "kind": "cpu",
                    "samples": out["samples"], "collapsed": out["collapsed"]}
        text = profiler.read_child_profile(pid, self._session())
        return {"pid": pid, "kind": "cpu", "collapsed": text}

    def _start_jax_trace(self, logdir: str, duration_s: float) -> None:
        """On-demand xplane capture on this node, bounded and one at a
        time (XLA's profiler cannot nest)."""
        if getattr(self, "_jax_trace_active", False):
            raise RuntimeError("a jax trace capture is already running")
        self._jax_trace_active = True

        def _capture():
            try:
                from ..util import timeline

                with timeline.trace_jax(logdir):
                    self._stopped.wait(max(0.1, duration_s))
            except Exception as e:
                logger.warning("jax trace capture failed: %r", e)
            finally:
                self._jax_trace_active = False

        threading.Thread(target=_capture, daemon=True,
                         name="jax-trace-capture").start()

    def stop(self, notify: bool = True) -> None:
        # notify is part of the RemoteNodeAgent duck surface (suppresses
        # the remote stop frame); a local agent has no one to notify
        del notify
        self._stopped.set()
        with self._pool_lock:
            pool, self._pool = self._pool, False
        if pool:
            from .process_pool import release_shared_pool

            release_shared_pool()
        with self._lock:
            actors = list(self._actors.values())
        for runner in actors:
            runner.dead = True
            runner.death_cause = WorkerCrashedError("node stopped")
            runner.stop()
            if runner.process is not None:
                runner.process.terminate()
        self.kill_running_tasks()
        # fail everything still queued so owners see the crash, not a hang
        while True:
            try:
                item = self._task_queue.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                spec, done = item
                done(TaskResult(spec.task_id, ok=False,
                                error=WorkerCrashedError("node stopped")))
        with self._lock:
            pending = list(self._pending_actor_dones.items())
            self._pending_actor_dones.clear()
            self._stream_cbs.clear()
        for task_id, done in pending:
            done(TaskResult(task_id, ok=False,
                            error=WorkerCrashedError("node stopped")))


class ObjectDirectory:
    """Cluster-wide object location registry.

    The reference's directory is ownership-based
    (`src/ray/object_manager/ownership_object_directory.cc`); a centralized
    map is equivalent for correctness at single-controller scale and keeps the
    pull path simple. Locations are node agents (for in-process pulls).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._locations: Dict[ObjectID, List[NodeID]] = {}
        # relay pullers mid-transfer: node -> bytes committed so far.
        # Partial holders never satisfy locate()/locations()/waiters —
        # they exist so the broadcast planner and the ledger can see
        # in-flight replicas, and so hygiene code can purge them.
        self._partials: Dict[ObjectID, Dict[NodeID, int]] = {}
        self._agents: Dict[NodeID, NodeAgent] = {}
        self._waiters: Dict[ObjectID, List[Callable[[], None]]] = {}
        # cross-host hook: every add_location also notifies joined worker
        # hosts via pubsub (set by cross_host.enable_cross_host)
        self.on_add: Optional[Callable[[ObjectID, NodeID], None]] = None
        # liveness hook (set by Runtime): locate() skips holders on nodes
        # the control plane no longer reports ALIVE, closing the window
        # between a DEAD mark and the directory purge
        self.alive_check: Optional[Callable[[NodeID], bool]] = None

    def register_agent(self, agent: NodeAgent) -> None:
        with self._lock:
            self._agents[agent.node_id] = agent

    def unregister_agent(self, node_id: NodeID) -> None:
        with self._lock:
            self._agents.pop(node_id, None)
            for oid in list(self._locations):
                locs = [n for n in self._locations[oid] if n != node_id]
                if locs:
                    self._locations[oid] = locs
                else:
                    del self._locations[oid]
            for oid in list(self._partials):
                self._partials[oid].pop(node_id, None)
                if not self._partials[oid]:
                    del self._partials[oid]

    def add_location(self, object_id: ObjectID, node_id: NodeID,
                     bytes_available: Optional[int] = None) -> None:
        """Register a holder. With bytes_available, the node is a PARTIAL
        holder (a relay mid-transfer): recorded for observability but
        invisible to locate()/locations()/waiters until the full add
        arrives, which promotes it (drops the partial entry)."""
        if bytes_available is not None:
            with self._lock:
                self._partials.setdefault(object_id, {})[node_id] = int(bytes_available)
            return
        with self._lock:
            locs = self._locations.setdefault(object_id, [])
            if node_id not in locs:
                locs.append(node_id)
            partials = self._partials.get(object_id)
            if partials is not None:
                partials.pop(node_id, None)
                if not partials:
                    del self._partials[object_id]
            callbacks = self._waiters.pop(object_id, [])
        for cb in callbacks:
            cb()
        if self.on_add is not None:
            self.on_add(object_id, node_id)

    def remove_location(self, object_id: ObjectID, node_id: NodeID) -> None:
        with self._lock:
            locs = self._locations.get(object_id)
            if locs and node_id in locs:
                locs.remove(node_id)
                if not locs:
                    del self._locations[object_id]
            partials = self._partials.get(object_id)
            if partials is not None:
                partials.pop(node_id, None)
                if not partials:
                    del self._partials[object_id]

    def partial_locations(self, object_id: ObjectID) -> Dict[NodeID, int]:
        """Snapshot of in-flight relay holders: node -> bytes committed."""
        with self._lock:
            return dict(self._partials.get(object_id, {}))

    def locations(self, object_id: ObjectID) -> List[NodeID]:
        with self._lock:
            return list(self._locations.get(object_id, []))

    def items(self) -> Dict[ObjectID, List[NodeID]]:
        """Full location-table snapshot (object_ledger's dead-node sweep)."""
        with self._lock:
            return {oid: list(locs) for oid, locs in self._locations.items()}

    def locate(self, object_id: ObjectID, exclude: Optional[NodeID] = None,
               prefer_local: bool = False) -> Optional[NodeAgent]:
        """First live holder, in registration order. With prefer_local,
        holders rank local-shm < local-memory < remote (is_remote
        cross-host proxies): a same-host shm replica is a zero-copy map,
        a same-host memory replica is an in-process reference, and only
        when neither exists does the pull go over a socket."""
        alive_check = self.alive_check
        with self._lock:
            best = None
            best_tier = 3
            for node_id in self._locations.get(object_id, []):
                if node_id == exclude:
                    continue
                agent = self._agents.get(node_id)
                if agent is None or agent._stopped.is_set():
                    continue
                if alive_check is not None and not alive_check(node_id):
                    continue
                if not prefer_local:
                    return agent
                if getattr(agent, "is_remote", False):
                    tier = 2
                elif getattr(agent.store, "kind", "memory") == "shm":
                    tier = 0
                else:
                    tier = 1
                if tier == 0:
                    return agent
                if tier < best_tier:
                    best, best_tier = agent, tier
            return best

    def subscribe_once(self, object_id: ObjectID, callback: Callable[[], None]) -> None:
        with self._lock:
            if object_id in self._locations:
                fire = True
            else:
                fire = False
                self._waiters.setdefault(object_id, []).append(callback)
        if fire:
            callback()

    def drop_everywhere(self, object_id: ObjectID) -> None:
        with self._lock:
            node_ids = list(self._locations.pop(object_id, []))
            agents = [self._agents[n] for n in node_ids if n in self._agents]
        for agent in agents:
            agent.store.delete(object_id)
