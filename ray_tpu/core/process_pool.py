"""Cross-process worker pool: CPU tasks in spawned processes, shm object plane.

The role of the reference's per-node worker processes (upstream ray
`src/ray/raylet/worker_pool.cc :: WorkerPool` + plasma `client.cc`): user
code runs OUTSIDE the runtime's address space, so a segfaulting or leaking
task kills one worker process — not the node. The TPU split (node_agent.py
docstring): device tasks stay on threads inside the device-owning process
(one process owns the TPU); CPU-only tasks route here when
RAY_TPU_WORKER_PROCESSES > 0.

Data plane: function+args and returns are pickled with protocol 5;
out-of-band buffers (numpy arrays) travel as separate sealed objects in the
C++ shared-memory store (core/_shm), so large arrays cross the process
boundary zero-copy. Payloads that exceed the arena fall back to the control
pipe. Functions are serialized with cloudpickle (closures, lambdas).

Crash semantics: a worker that dies mid-task fails ONLY that task
(WorkerCrashedError -> normal retry path); the pool respawns the worker.
"""

from __future__ import annotations

import contextlib
import os
import pickle
import queue
import signal
import sys
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Tuple

import multiprocessing as mp

import cloudpickle

from .logging import get_logger

logger = get_logger("process_pool")

_POOL_ARENA_BYTES = 256 << 20
_ID_SIZE = 20


class WorkerProcessCrash(RuntimeError):
    """The worker process executing the task died."""


class TaskNotSerializableError(RuntimeError):
    """The task (fn/args) cannot cross the process boundary; callers may
    fall back to in-process execution."""


# Runtime-handle types (ObjectRef, ActorHandle) pickle by id and would
# resolve against a NEW runtime inside a worker process — silently wrong
# without an RPC back-channel. Registered by the node agent; their presence
# anywhere in a task payload forces in-process execution.
_INLINE_ONLY_TYPES: tuple = ()


def register_inline_only_types(*types: type) -> None:
    global _INLINE_ONLY_TYPES
    _INLINE_ONLY_TYPES = tuple(set(_INLINE_ONLY_TYPES + types))


class _TaskPickler(cloudpickle.CloudPickler):
    def reducer_override(self, obj):
        if _INLINE_ONLY_TYPES and isinstance(obj, _INLINE_ONLY_TYPES):
            # With a head back-channel (worker_api), refs and handles ARE
            # resolvable inside worker/actor processes — let them cross.
            # Without one they would re-resolve against a meaningless
            # private runtime: keep the strict inline-only contract.
            if not os.environ.get("RAY_TPU_HEAD_ADDRESS"):
                raise TaskNotSerializableError(
                    f"{type(obj).__name__} cannot cross the process boundary "
                    "(no head back-channel; start the head with "
                    "system_config={'control_plane_rpc_port': 0})"
                )
        return super().reducer_override(obj)


def _cloudpickle_dumps(obj: Any, protocol: int = 5, buffer_callback=None) -> bytes:
    import io

    buf = io.BytesIO()
    _TaskPickler(buf, protocol=protocol, buffer_callback=buffer_callback).dump(obj)
    return buf.getvalue()


def _oid(tag: bytes) -> bytes:
    return (tag + uuid.uuid4().bytes)[:_ID_SIZE].ljust(_ID_SIZE, b"\0")


# ---------------------------------------------------------------------------
# shm-backed pickle transport
# ---------------------------------------------------------------------------


def _dump(store, obj: Any, *, use_cloudpickle: bool) -> Tuple[bytes, List[bytes], Optional[bytes]]:
    """-> (payload_or_empty, buffer_ids, inline_payload).

    Pickles with protocol 5; each out-of-band buffer is sealed as its own shm
    object. If the store can't take a buffer (arena full / too big), fall
    back to fully-inline pickling (buffers in-band through the pipe)."""
    buffers: List[pickle.PickleBuffer] = []
    dumps = _cloudpickle_dumps if use_cloudpickle else pickle.dumps

    def inline(o):
        # pickling-phase failures (any exception type — reducers can raise
        # ValueError, NotImplementedError, ...) classify as not-serializable
        # so callers may fall back in-process; infra errors stay distinct.
        try:
            return dumps(o, protocol=5)
        except TaskNotSerializableError:
            raise
        except Exception as e:
            raise TaskNotSerializableError(repr(e)) from e

    try:
        payload = dumps(obj, protocol=5, buffer_callback=buffers.append)
    except TaskNotSerializableError:
        raise  # inline retry would serialize everything again just to re-raise
    except Exception:
        # some object rejects out-of-band buffering; go fully inline
        return b"", [], inline(obj)
    buffer_ids: List[bytes] = []
    try:
        for buf in buffers:
            bid = _oid(b"b")
            store.put(bid, buf.raw())  # raw(): flat C-contiguous byte view
            buffer_ids.append(bid)
    except Exception:
        for bid in buffer_ids:
            try:
                store.delete(bid)
            except Exception:
                pass
        return b"", [], inline(obj)
    return payload, buffer_ids, None


def _load(store, payload: bytes, buffer_ids: List[bytes], inline: Optional[bytes]) -> Any:
    if inline is not None:
        return pickle.loads(inline)
    pinned: List[bytes] = []
    try:
        views = []
        for bid in buffer_ids:
            view = store.get_view(bid)
            if view is None:
                raise WorkerProcessCrash(f"shm buffer {bid.hex()[:8]} missing")
            pinned.append(bid)
            views.append(view)
        # copy-out on load: the deserialized arrays must outlive the pin
        return pickle.loads(payload, buffers=[bytes(v) for v in views])
    finally:
        for bid in pinned:
            store.release(bid)


def _load_sealed(store, payload: bytes, buffer_ids: List[bytes],
                 inline: Optional[bytes]):
    """Like _load, but hands back a store-ready SealedBytes instead of
    deserializing: the object store gives each consumer a private copy at
    get() time, so deserializing here would only add a redundant
    pickle round-trip. Out-of-band shm buffers are copied out once."""
    from .object_store import SealedBytes

    if inline is not None:
        return SealedBytes(inline)
    pinned: List[bytes] = []
    try:
        bufs = []
        for bid in buffer_ids:
            view = store.get_view(bid)
            if view is None:
                raise WorkerProcessCrash(f"shm buffer {bid.hex()[:8]} missing")
            pinned.append(bid)
            bufs.append(bytes(view))
        return SealedBytes(payload, bufs)
    finally:
        for bid in pinned:
            store.release(bid)


def _cleanup_buffers(store, buffer_ids: List[bytes]) -> None:
    for bid in buffer_ids:
        try:
            store.delete(bid)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# worker process
# ---------------------------------------------------------------------------


_main_guard = threading.Lock()


def _mp_context():
    """forkserver, not spawn: spawn re-imports the parent's __main__ in
    every worker, which crashes when the driver is <stdin>/REPL and
    re-executes side effects when it is a script. The forkserver child
    forks from a clean server process that never saw driver state (or
    jax/TPU handles). spawn is the fallback where forkserver is absent.
    Shared by the task pool and actor worker processes."""
    try:
        ctx = mp.get_context("forkserver")
        # the preload import arms PR_SET_PDEATHSIG inside the forkserver:
        # a SIGKILLed runtime (chaos tests, crashed drivers) must not
        # orphan the server + resource-tracker daemons forever
        ctx.set_forkserver_preload(["ray_tpu.core._pdeathsig"])
        return ctx
    except ValueError:
        return mp.get_context("spawn")


@contextlib.contextmanager
def _suppress_main_reimport():
    """Stop multiprocessing from re-running the driver's __main__ in workers.

    mp's spawn/forkserver preparation re-executes the parent's main module in
    every child — which crashes outright when the driver is <stdin>/REPL and
    re-runs script side effects otherwise. Workers here never need driver
    state: functions arrive by value via cloudpickle (main-module functions
    included).

    Mechanism: swap a BLANK module in as sys.modules['__main__'] while
    start() computes the preparation data (it reads main via sys.modules).
    Crucially this does NOT mutate the real main module: driver code that is
    concurrently executing resolves `__file__`/globals through its own frame
    globals (the real module's dict), so background worker prestart cannot
    race the driver's top-level code."""
    main = sys.modules.get("__main__")
    if main is None:
        yield
        return
    import types

    with _main_guard:
        blank = types.ModuleType("__main__")
        blank.__spec__ = None  # no spec + no file => child skips main fixup
        sys.modules["__main__"] = blank
        try:
            yield
        finally:
            sys.modules["__main__"] = main


def _worker_main(store_name: str, req_q, resp_q, log_dir: str = "") -> None:
    """Entry point of a spawned worker. Imports stay minimal: no jax."""
    from ._pdeathsig import set_pdeathsig
    from .shm_store import ShmObjectStore

    set_pdeathsig()  # die with the forkserver/runtime, never orphan

    # Runtime API calls inside a pool worker would _auto_init a PRIVATE
    # runtime whose refs/handles are meaningless to the parent; api.py
    # checks this flag and raises a clear error instead.
    os.environ["RAY_TPU_IN_POOL_WORKER"] = "1"
    if log_dir:
        # redirect the worker's stdio into the PARENT's session log dir
        # (worker-<pid>.out) so the LogMonitor attributes and echoes it;
        # the dir is passed in because session_dir() in the child would
        # mint a fresh session
        try:
            path = os.path.join(log_dir, f"worker-{os.getpid()}.out")
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            os.dup2(fd, 1)
            os.dup2(fd, 2)
            os.close(fd)
            sys.stdout = os.fdopen(1, "w", buffering=1, closefd=False)
            sys.stderr = os.fdopen(2, "w", buffering=1, closefd=False)
        except OSError:
            pass  # stdio capture is best-effort
    try:
        # flight recorder: mirror recent spans/logs/events to disk so a
        # SIGKILL (chaos, memory monitor) still leaves a postmortem
        from ..util import flight_recorder

        flight_recorder.attach(log_dir, "worker")
    except Exception:  # noqa: BLE001 — observability must not block startup
        pass
    try:
        # profiling plane: SIGUSR2 → all-threads stack dump (faulthandler —
        # fires even when this loop is wedged in user code), SIGUSR1 →
        # toggle the sampling profiler (util/profiler)
        from ..util import profiler

        profiler.install_child_handlers(log_dir)
    except Exception:  # noqa: BLE001 — observability must not block startup
        pass
    store = ShmObjectStore(store_name, create=False)
    while True:
        item = req_q.get()
        if item is None:
            return
        task_tag, payload, buffer_ids, inline = item
        try:
            fn, args, kwargs, renv, head_addr = _load(
                store, payload, buffer_ids, inline)
            # per-TASK, not per-spawn: the forkserver snapshots the
            # environment at ITS start, so a spawn-time address would be
            # stale (or absent) whenever runtimes cycle in one parent —
            # the back-channel (api._pool_worker_client) needs the address
            # of the head that submitted THIS task
            if head_addr:
                os.environ["RAY_TPU_HEAD_ADDRESS"] = head_addr
            else:
                os.environ.pop("RAY_TPU_HEAD_ADDRESS", None)
            from .runtime_env import applied

            with applied(renv):
                out = fn(*args, **kwargs)
            r_payload, r_bufs, r_inline = _dump(store, out, use_cloudpickle=False)
            resp_q.put((task_tag, True, r_payload, r_bufs, r_inline))
        except BaseException as e:  # noqa: BLE001 — user task may raise anything
            try:
                err = cloudpickle.dumps(e)
            except Exception:
                err = cloudpickle.dumps(RuntimeError(repr(e)))
            resp_q.put((task_tag, False, err, [], None))


@dataclass
class _Worker:
    proc: mp.process.BaseProcess
    req_q: Any
    resp_q: Any


class ProcessPool:
    """N spawned worker processes sharing one shm arena with the parent."""

    def __init__(self, num_workers: int, store_name: Optional[str] = None):
        from .shm_store import ShmObjectStore

        self.num_workers = max(1, num_workers)
        self.store_name = store_name or f"/ray_tpu_pool_{os.getpid()}_{uuid.uuid4().hex[:6]}"
        self.store = ShmObjectStore(
            self.store_name, capacity=_POOL_ARENA_BYTES, max_objects=8192
        )
        self._ctx = _mp_context()
        self._tasks: "queue.Queue[Optional[Tuple]]" = queue.Queue()
        self._closed = threading.Event()
        self._submit_lock = threading.Lock()
        self._inflight: dict = {}  # lane index -> (worker pid, start time)
        self._inflight_lock = threading.Lock()
        self._lane_pids: dict = {}  # lane index -> last spawned worker pid
        self._mem_monitor = None
        self._threads: List[threading.Thread] = []
        for i in range(self.num_workers):
            t = threading.Thread(
                target=self._lane, args=(i,), daemon=True, name=f"pool-lane-{i}"
            )
            t.start()
            self._threads.append(t)

    # ------------------------------------------------------------------ api

    def run(self, fn: Callable, args: tuple, kwargs: dict,
            timeout: Optional[float] = None, sealed: bool = False,
            runtime_env: Optional[dict] = None) -> Any:
        """Execute fn(*args, **kwargs) in a worker process; blocks the calling
        thread. Raises WorkerProcessCrash if the worker dies, or the task's
        own exception. sealed=True returns the worker's pickled result as a
        store-ready SealedBytes without deserializing it in this process
        (the caller's store hands each consumer a private copy on get)."""
        done = threading.Event()
        box: List[Any] = [None, None]  # (ok, value_or_error)

        def complete(ok: bool, value: Any) -> None:
            box[0], box[1] = ok, value
            done.set()

        # submit under the close lock: a task can never be enqueued after
        # close() drained the queue (it would strand this caller forever).
        # WorkerProcessCrash (not RuntimeError) so callers keep the normal
        # system-failure retry path when a node stop races a submission.
        with self._submit_lock:
            if self._closed.is_set():
                raise WorkerProcessCrash("process pool is closed")
            self._tasks.put((fn, args, kwargs, complete, sealed, runtime_env))
        if not done.wait(timeout):
            raise TimeoutError("process-pool task timed out")
        if box[0]:
            return box[1]
        raise box[1]

    def kill_newest_worker(self) -> Optional[int]:
        """Kill the worker process running the NEWEST in-flight task (the
        memory monitor's victim policy, matching the reference: newest =
        least progress lost, and its task retries via the normal
        worker-crash path). Returns the killed pid, or None when no task
        is in flight."""
        with self._inflight_lock:
            if not self._inflight:
                return None
            lane, (pid, t0) = max(self._inflight.items(),
                                  key=lambda kv: kv[1][1])
        # The victim may finish (and its lane restart a new worker — or the
        # OS may even reuse the pid) between choosing it and signalling:
        # re-verify the SAME (pid, start time) still holds the lane right
        # before SIGKILL, under the lock so _lane can't swap it mid-check.
        with self._inflight_lock:
            if self._inflight.get(lane) != (pid, t0):
                return None
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                return None
        return pid

    def ensure_memory_monitor(self) -> None:
        """Start the node memory monitor once per pool (idempotent); it
        kills the newest pool task under host memory pressure. Stopped by
        close()."""
        with self._submit_lock:
            if self._mem_monitor is None and not self._closed.is_set():
                from .memory_monitor import MemoryMonitor

                monitor = MemoryMonitor(self.kill_newest_worker)
                if monitor.enabled:
                    monitor.start()
                    self._mem_monitor = monitor

    def close(self) -> None:
        if self._mem_monitor is not None:
            self._mem_monitor.stop()
            self._mem_monitor = None
        with self._submit_lock:
            if self._closed.is_set():
                return
            self._closed.set()
            for _ in self._threads:
                self._tasks.put(None)
        all_joined = True
        for t in self._threads:
            t.join(timeout=5)
            all_joined = all_joined and not t.is_alive()
        # lanes exit at the top-of-loop closed check without draining: fail
        # anything still queued so no caller blocks in done.wait() forever
        while True:
            try:
                item = self._tasks.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                item[3](False, WorkerProcessCrash("process pool closed"))
        # a lane that outlived the join (task >5s) still holds the store;
        # leak the mapping rather than hand it a dead handle
        if all_joined:
            try:
                self.store.close()
            except Exception:
                pass

    # ------------------------------------------------------------ internals

    def _spawn(self) -> _Worker:
        req_q = self._ctx.Queue()
        resp_q = self._ctx.Queue()
        from .logging import log_dir

        proc = self._ctx.Process(
            target=_worker_main,
            args=(self.store_name, req_q, resp_q, log_dir()),
            daemon=True,
        )
        with _suppress_main_reimport():
            proc.start()
        return _Worker(proc, req_q, resp_q)

    def worker_pids(self) -> List[int]:
        """Pids of the pool's live worker processes (profiling plane:
        node_agent.profilable_pids). Dead lanes' stale pids are filtered
        with a 0-signal probe."""
        with self._inflight_lock:
            pids = list(self._lane_pids.values())
        alive = []
        for pid in pids:
            try:
                os.kill(pid, 0)
                alive.append(pid)
            except OSError:
                pass
        return alive

    def _lane(self, index: int) -> None:
        """One parent thread drives one worker process: ship task, await
        response or death. Worker death fails only the in-flight task."""
        # prestart (reference: worker_pool.cc prestarts workers): spawning
        # here, before the first task arrives, moves the ~0.5s forkserver
        # cost off the first submission's critical path
        worker: Optional[_Worker] = None
        try:
            worker = self._spawn()
        except Exception:  # noqa: BLE001 — retried lazily per task below
            worker = None
        if worker is not None:
            with self._inflight_lock:
                self._lane_pids[index] = worker.proc.pid
        while not self._closed.is_set():
            item = self._tasks.get()
            if item is None:
                break
            fn, args, kwargs, complete, sealed, renv = item
            if worker is None or not worker.proc.is_alive():
                worker = self._spawn()
                with self._inflight_lock:
                    self._lane_pids[index] = worker.proc.pid
            tag = uuid.uuid4().hex
            try:
                payload, buffer_ids, inline = _dump(
                    self.store,
                    (fn, args, kwargs, renv,
                     os.environ.get("RAY_TPU_HEAD_ADDRESS", "")),
                    use_cloudpickle=True,
                )
            except TaskNotSerializableError as e:
                # genuinely unpicklable task (see _dump's phase-based
                # classification): callers may fall back in-process
                complete(False, TaskNotSerializableError(repr(e)))
                continue
            except Exception as e:
                # store/infrastructure failure — NOT a serialization problem;
                # surface it so pool degradation is visible (ADVICE r2)
                logger.warning("pool transport failure: %r", e)
                complete(False, WorkerProcessCrash(f"pool transport failure: {e!r}"))
                continue
            with self._inflight_lock:
                self._inflight[index] = (worker.proc.pid, time.monotonic())
            worker.req_q.put((tag, payload, buffer_ids, inline))
            resp = None
            while resp is None:
                try:
                    resp = worker.resp_q.get(timeout=0.05)
                except queue.Empty:
                    if not worker.proc.is_alive():
                        break
                    if self._closed.is_set():
                        break
            with self._inflight_lock:
                self._inflight.pop(index, None)
            _cleanup_buffers(self.store, buffer_ids)
            if resp is None:
                code = worker.proc.exitcode
                if not self._closed.is_set():
                    # reap the crash into a postmortem artifact (flight
                    # mirror + stdout tail); pool teardown is not a crash
                    try:
                        from ..util import flight_recorder

                        flight_recorder.write_postmortem(
                            worker.proc.pid,
                            "worker process died while running task",
                            exitcode=code, stdout_hint="worker")
                    except Exception:  # noqa: BLE001 — must not mask the crash
                        pass
                worker = None  # respawn lazily for the next task
                complete(
                    False,
                    WorkerProcessCrash(
                        f"worker process died (exitcode {code}) while running task"
                    ),
                )
                continue
            rtag, ok, r_payload, r_bufs, r_inline = resp
            if rtag != tag:  # stale response from a previous crash window
                complete(False, WorkerProcessCrash("worker desynchronized"))
                worker.proc.terminate()
                worker = None
                continue
            try:
                if ok and sealed:
                    complete(True, _load_sealed(self.store, r_payload, r_bufs, r_inline))
                elif ok:
                    complete(True, _load(self.store, r_payload, r_bufs, r_inline))
                else:
                    complete(False, pickle.loads(r_payload))
            except Exception as e:
                complete(False, e)
            finally:
                _cleanup_buffers(self.store, r_bufs)
        if worker is not None and worker.proc.is_alive():
            try:
                worker.req_q.put(None)
                worker.proc.join(timeout=2)
                if worker.proc.is_alive():
                    worker.proc.terminate()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# process-wide shared pool
# ---------------------------------------------------------------------------
# Virtual nodes share one OS process, so per-agent pools would multiply
# worker processes and /dev/shm arenas for no isolation gain. Agents acquire
# a refcounted singleton instead; the last release closes it.

_shared_lock = threading.Lock()
_shared_pool: Optional[ProcessPool] = None
_shared_refs = 0


def acquire_shared_pool(num_workers: int) -> ProcessPool:
    global _shared_pool, _shared_refs
    with _shared_lock:
        if _shared_pool is None:
            _shared_pool = ProcessPool(num_workers)
            _shared_refs = 0
        _shared_refs += 1
        return _shared_pool


def release_shared_pool() -> None:
    global _shared_pool, _shared_refs
    with _shared_lock:
        if _shared_pool is None:
            return
        _shared_refs -= 1
        if _shared_refs > 0:
            return
        pool, _shared_pool = _shared_pool, None
    pool.close()
