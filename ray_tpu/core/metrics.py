"""Tagged metrics with Prometheus text exposition.

Equivalent of the reference's metric pipeline (upstream ray
`src/ray/stats/metric.h :: stats::Metric`, `metric_defs.cc`, and the Python
`ray/util/metrics.py :: Counter/Gauge/Histogram`): one registry per process,
metrics carry tag sets, and the whole registry renders to the Prometheus text
format for scraping by the node agent.
"""

from __future__ import annotations

import bisect
import contextlib
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
    "MICRO_BUCKETS", "render_merged",
]

TagMap = Tuple[Tuple[str, str], ...]


def _tags(tags: Optional[Dict[str, str]]) -> TagMap:
    return tuple(sorted((tags or {}).items()))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str = "", registry_: "MetricsRegistry | None" = None):
        self.name = name
        self.description = description
        self._lock = threading.Lock()
        (registry_ or registry).register(self)

    def samples(self) -> Iterable[Tuple[str, TagMap, float]]:
        raise NotImplementedError

    def reset(self) -> None:
        """Zero accumulated values while staying registered — the
        between-tests reset (`registry.fresh()`) that, unlike `clear()`,
        does not orphan module-level metric objects."""
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, description="", registry_=None):
        self._values: Dict[TagMap, float] = {}
        super().__init__(name, description, registry_)

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        key = _tags(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def get(self, tags: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return self._values.get(_tags(tags), 0.0)

    def samples(self):
        with self._lock:
            return [(self.name, k, v) for k, v in self._values.items()]

    def reset(self):
        with self._lock:
            self._values.clear()


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, description="", registry_=None):
        self._values: Dict[TagMap, float] = {}
        super().__init__(name, description, registry_)

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[_tags(tags)] = float(value)

    def add(self, delta: float, tags: Optional[Dict[str, str]] = None) -> None:
        key = _tags(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + delta

    def get(self, tags: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return self._values.get(_tags(tags), 0.0)

    @contextlib.contextmanager
    def track(self, tags: Optional[Dict[str, str]] = None):
        """In-flight tracking: +1 on entry, -1 on exit (exception included).
        The gauge reads as the number of bodies currently executing."""
        self.add(1, tags)
        try:
            yield
        finally:
            self.add(-1, tags)

    def samples(self):
        with self._lock:
            return [(self.name, k, v) for k, v in self._values.items()]

    def reset(self):
        with self._lock:
            self._values.clear()


_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60, 300)

# For sub-millisecond distributions (KV-cache migration, object pulls):
# the defaults bottom out at 1ms, which flattens a 2.9ms-mean migration
# and a sub-ms pull into two buckets.
MICRO_BUCKETS = (
    1e-6, 5e-6, 1e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.5, 1, 5, 30,
)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, description="", buckets: Sequence[float] = _DEFAULT_BUCKETS, registry_=None):
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[TagMap, List[int]] = {}
        self._sums: Dict[TagMap, float] = {}
        self._totals: Dict[TagMap, int] = {}
        super().__init__(name, description, registry_)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        key = _tags(tags)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            idx = bisect.bisect_left(self.buckets, value)
            if idx < len(counts):
                counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def count(self, tags: Optional[Dict[str, str]] = None) -> int:
        with self._lock:
            return self._totals.get(_tags(tags), 0)

    def sum(self, tags: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return self._sums.get(_tags(tags), 0.0)

    def samples(self):
        out = []
        with self._lock:
            for key, counts in self._counts.items():
                cumulative = 0
                for bound, c in zip(self.buckets, counts):
                    cumulative += c
                    out.append(
                        (f"{self.name}_bucket", key + (("le", repr(bound)),), float(cumulative))
                    )
                out.append((f"{self.name}_bucket", key + (("le", "+Inf"),), float(self._totals[key])))
                out.append((f"{self.name}_sum", key, self._sums[key]))
                out.append((f"{self.name}_count", key, float(self._totals[key])))
        return out

    def reset(self):
        with self._lock:
            self._counts.clear()
            self._sums.clear()
            self._totals.clear()


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> None:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and existing is not metric:
                raise ValueError(f"metric already registered: {metric.name}")
            self._metrics[metric.name] = metric

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str) -> bool:
        """Drop one metric by name so a fresh object may re-register it.
        Returns whether it was present."""
        with self._lock:
            return self._metrics.pop(name, None) is not None

    def clear(self) -> None:
        """Forget every metric. NOTE: module-level metric objects created
        at import time keep pointing at this registry but are no longer
        in it — their samples silently stop being exported, and creating
        a same-named replacement raises. Tests that want a clean slate
        should call `fresh()` instead."""
        with self._lock:
            self._metrics.clear()

    def fresh(self) -> None:
        """Zero every registered metric's accumulated values while
        keeping registrations intact — the safe between-tests reset."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.reset()

    def snapshot(self) -> List[Dict[str, Any]]:
        """A plain-data dump of every metric family (wire-friendly: only
        dicts/lists/tuples/scalars) for telemetry shipping to the head."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = []
        for m in sorted(metrics, key=lambda m: m.name):
            out.append({
                "name": m.name,
                "kind": m.kind,
                "description": m.description,
                "samples": [(sname, list(tags), float(value))
                            for sname, tags, value in m.samples()],
            })
        return out

    def render_prometheus(self) -> str:
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in sorted(metrics, key=lambda m: m.name):
            if m.description:
                lines.append(f"# HELP {m.name} {m.description}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for name, tags, value in m.samples():
                lines.append(_sample_line(name, tags, value))
        return "\n".join(lines) + "\n"


def _sample_line(name: str, tags, value: float) -> str:
    if tags:
        tag_str = ",".join(f'{k}="{v}"' for k, v in tags)
        return f"{name}{{{tag_str}}} {value}"
    return f"{name} {value}"


def render_merged(local: MetricsRegistry,
                  remote_snapshots: Dict[str, Dict[str, Any]]) -> str:
    """Prometheus text for the whole cluster: the local (head) registry
    plus per-node `registry.snapshot()` payloads shipped via telemetry
    (`remote_snapshots`: node_id -> {"role": ..., "metrics": [...]}).
    Remote samples gain `node_id`/`role` tags; each family gets one
    HELP/TYPE header even when several processes export it."""
    families: Dict[str, Dict[str, Any]] = {}

    def _add_family(name: str, kind: str, desc: str):
        fam = families.get(name)
        if fam is None:
            fam = families[name] = {"kind": kind, "desc": desc, "lines": []}
        return fam

    with local._lock:
        local_metrics = list(local._metrics.values())
    for m in local_metrics:
        fam = _add_family(m.name, m.kind, m.description)
        for sname, tags, value in m.samples():
            fam["lines"].append(_sample_line(sname, tags, value))

    for node_id, snap in sorted(remote_snapshots.items()):
        extra = (("node_id", node_id[:12]),)
        role = snap.get("role")
        if role:
            extra += (("role", role),)
        for fam_snap in snap.get("metrics", []):
            fam = _add_family(fam_snap["name"], fam_snap["kind"],
                              fam_snap.get("description", ""))
            for sname, tags, value in fam_snap["samples"]:
                merged = tuple(sorted(list(map(tuple, tags)) + list(extra)))
                fam["lines"].append(_sample_line(sname, merged, value))

    lines: List[str] = []
    for name in sorted(families):
        fam = families[name]
        if fam["desc"]:
            lines.append(f"# HELP {name} {fam['desc']}")
        lines.append(f"# TYPE {name} {fam['kind']}")
        lines.extend(fam["lines"])
    return "\n".join(lines) + "\n"


registry = MetricsRegistry()
