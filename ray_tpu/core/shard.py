"""Federated control plane: hash-sharded KV / directory / pubsub services.

ROADMAP item 3 ("make the head not the bottleneck and not the only copy"),
after the original Ray architecture (arXiv:1712.05889): the head keeps the
strongly-consistent tables it must own — node membership, the actor
directory, jobs, telemetry ingest — while the high-churn gossip planes
(cluster KV, object-location gossip, pubsub fan-out) shard across K
``ControlPlaneShard`` subprocesses with consistent key→shard routing
(`rpc.shard_for_key`). Each shard primary journals every mutation
(write-ahead JSONL, flushed per op) and snapshots on an interval using the
persistence idiom (atomic tmp+rename); a **warm standby** subprocess tails
the journal and is promoted onto the primary's port when the primary dies,
so a SIGKILL'd shard is a reconnect blip (PR 4 client loop rides it out),
not an outage.

Pieces:
- ``ControlPlaneShard``      — the sharded state machine (KV + object
                               directory + pubsub) with journal/replay.
- ``StandbyControl``         — the standby's control surface: tails the
                               journal, ``promote(port)`` binds the dead
                               primary's port over the replica.
- ``ShardSupervisor``        — head-side: spawns primary+standby pairs,
                               detects primary death, drives promotion,
                               respawns standbys; chaos hooks for tests.
- ``FederatedControlPlane``  — in-process head wrapper installed by
                               ``enable_federation``: routes kv_* and
                               pubsub through the shards, everything else
                               to the inner ControlPlane. Opt-in via
                               ``config.control_plane_shards`` (0 = off,
                               the existing single-head path, untouched).

Worker-side routing lives in ``rpc.ShardedControlPlane``.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

import cloudpickle

from .control_plane import (
    GOSSIP_NODE_PREFIXES,
    GOSSIP_RELAY_PREFIX,
    Pubsub,
    _is_gossip_key,
)
from .logging import get_logger
from .metrics import Counter, Gauge
from .rpc import (
    ControlPlaneServer,
    ControlPlaneUnavailable,
    RemoteControlPlane,
    shard_for_key,
)

logger = get_logger("shard")

SHARD_SNAPSHOT_VERSION = 1
# KV key where the head advertises the shard map to joining hosts
SHARD_MAP_KEY = "control_plane/shard_map"

_failovers_total = Counter(
    "control_plane_shard_failovers_total",
    "Shard primaries replaced by their warm standby after death",
)
_shard_health = Gauge(
    "control_plane_shard_health",
    "1 while the shard's primary is serving, 0 during failover",
)
_pubsub_dropped = Counter(
    "control_plane_pubsub_dropped_total",
    "Federated pubsub publishes dropped because the owning shard was "
    "unreachable past the publish deadline (best-effort during failover)",
)

# -- per-service RPC registries (raylint R3: idempotent ⊆ allowed) ----------
# the shard's served surface: the gossip planes only — membership/actors/
# jobs/telemetry stay on the head
_SHARD_ALLOWED_METHODS: Set[str] = {
    "kv_put", "kv_get", "kv_del", "kv_keys",
    "dir_add_location", "dir_remove_location", "dir_locations",
    "publish", "subscribe",
    "shard_info", "sweep_gossip", "purge_node",
}

# everything the shard serves is safe to resend after an ambiguous
# connection loss: kv/dir ops are set-semantics, sweeps/purges are
# absorbing, and pubsub channels carry state-styled messages (a duplicate
# delivery is read as a repeated state announcement, never a double-apply)
_SHARD_IDEMPOTENT_METHODS: Set[str] = {
    "kv_put", "kv_get", "kv_del", "kv_keys",
    "dir_add_location", "dir_remove_location", "dir_locations",
    "publish", "subscribe",
    "shard_info", "sweep_gossip", "purge_node",
}

# the standby's control surface (supervisor-only)
_STANDBY_ALLOWED_METHODS: Set[str] = {
    "promote", "shard_info",
}

# promote is deliberately NOT idempotent: a resend after an ambiguous loss
# could double-bind; the supervisor handles the error and re-checks state
_STANDBY_IDEMPOTENT_METHODS: Set[str] = {
    "shard_info",
}


# -- journal ----------------------------------------------------------------
def _journal_encode(method: str, args: Tuple[Any, ...]) -> bytes:
    return base64.b64encode(cloudpickle.dumps((method, args))) + b"\n"


def _journal_decode(line: bytes) -> Tuple[str, Tuple[Any, ...]]:
    return cloudpickle.loads(base64.b64decode(line.strip()))


class ControlPlaneShard:
    """One shard of the federated gossip planes. Thread-safe; mutations are
    journaled (when a journal is attached) in apply order under the lock,
    so a tailing standby replays to an identical state."""

    def __init__(self, shard_id: int = 0, nshards: int = 1) -> None:
        self.shard_id = int(shard_id)
        self.nshards = int(nshards)
        self.role = "primary"
        self._lock = threading.RLock()
        self.pubsub = Pubsub()
        self._kv: Dict[str, Any] = {}
        self._kv_stamp: Dict[str, float] = {}
        self._dir: Dict[str, Set[str]] = {}
        self._journal = None  # append handle; set on the serving primary

    # -- journal / replay ---------------------------------------------------
    def attach_journal(self, handle) -> None:
        with self._lock:
            self._journal = handle

    def journal_offset(self) -> int:
        with self._lock:
            if self._journal is None:
                return 0
            self._journal.flush()
            return self._journal.tell()

    def _record(self, method: str, args: Tuple[Any, ...]) -> None:
        # caller holds self._lock: records land in apply order. flush (no
        # fsync) per op — a SIGKILL loses only unflushed = unacked ops,
        # which clients retry (every shard method is idempotent).
        if self._journal is not None:
            self._journal.write(_journal_encode(method, args))
            self._journal.flush()

    def apply(self, method: str, args: Tuple[Any, ...]) -> None:
        """Replay one journal record (standby tail / restart recovery)."""
        with self._lock:
            if method == "kv_put":
                key, value = args
                self._kv[key] = value
                if _is_gossip_key(key):
                    self._kv_stamp[key] = time.monotonic()
            elif method == "kv_del":
                (key,) = args
                self._kv.pop(key, None)
                self._kv_stamp.pop(key, None)
            elif method == "dir_add":
                oid_hex, node_hex = args
                self._dir.setdefault(oid_hex, set()).add(node_hex)
            elif method == "dir_rm":
                oid_hex, node_hex = args
                locs = self._dir.get(oid_hex)
                if locs is not None:
                    locs.discard(node_hex)
                    if not locs:
                        del self._dir[oid_hex]
            elif method == "purge_node":
                (node_hex,) = args
                self._purge_locked(node_hex)
            elif method == "sweep":
                (keys,) = args
                for key in keys:
                    self._kv.pop(key, None)
                    self._kv_stamp.pop(key, None)
            else:
                logger.warning("unknown journal record %r skipped", method)

    # -- KV -----------------------------------------------------------------
    def kv_put(self, key: str, value: Any, overwrite: bool = True) -> bool:
        with self._lock:
            if not overwrite and key in self._kv:
                return False
            self._kv[key] = value
            if _is_gossip_key(key):
                self._kv_stamp[key] = time.monotonic()
            self._record("kv_put", (key, value))
            return True

    def kv_get(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._kv.get(key)

    def kv_del(self, key: str) -> bool:
        with self._lock:
            self._kv_stamp.pop(key, None)
            hit = self._kv.pop(key, None) is not None
            if hit:
                self._record("kv_del", (key,))
            return hit

    def kv_keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return [k for k in self._kv if k.startswith(prefix)]

    # -- object-location gossip --------------------------------------------
    def dir_add_location(self, oid_hex: str, node_hex: str,
                         bytes_available: Optional[int] = None) -> bool:
        # bytes_available accepted for wire compatibility with the head's
        # directory surface; the shard tracks membership only
        with self._lock:
            self._dir.setdefault(oid_hex, set()).add(node_hex)
            self._record("dir_add", (oid_hex, node_hex))
            return True

    def dir_remove_location(self, oid_hex: str, node_hex: str) -> bool:
        with self._lock:
            locs = self._dir.get(oid_hex)
            if locs is None:
                return True
            locs.discard(node_hex)
            if not locs:
                del self._dir[oid_hex]
            self._record("dir_rm", (oid_hex, node_hex))
            return True

    def dir_locations(self, oid_hex: str) -> List[str]:
        with self._lock:
            return sorted(self._dir.get(oid_hex, ()))

    # -- pubsub (ephemeral: never journaled) --------------------------------
    def publish(self, channel: str, message: Any) -> bool:
        self.pubsub.publish(channel, message)
        return True

    # -- hygiene ------------------------------------------------------------
    def _purge_locked(self, node_hex: str) -> None:
        for prefix in GOSSIP_NODE_PREFIXES:
            self._kv.pop(prefix + node_hex, None)
            self._kv_stamp.pop(prefix + node_hex, None)
        for key in [k for k in self._kv if k.startswith(GOSSIP_RELAY_PREFIX)]:
            val = self._kv.get(key)
            if isinstance(val, str) and val.rsplit("|", 1)[-1] == node_hex:
                self._kv.pop(key, None)
                self._kv_stamp.pop(key, None)
        for oid_hex in [o for o, locs in self._dir.items() if node_hex in locs]:
            locs = self._dir[oid_hex]
            locs.discard(node_hex)
            if not locs:
                del self._dir[oid_hex]

    def purge_node(self, node_hex: str) -> bool:
        """mark_node_dead fan-out: drop the dead node's gossip + locations."""
        with self._lock:
            self._purge_locked(node_hex)
            self._record("purge_node", (node_hex,))
            return True

    def sweep_gossip(self, alive_hexes: List[str],
                     ttl_s: Optional[float] = None) -> int:
        """TTL sweep, head-driven: the head owns liveness, so it ships the
        alive set. Swept keys journal as explicit deletions ("sweep") —
        the standby's write stamps differ from the primary's, so replicas
        must never re-derive the sweep decision."""
        if ttl_s is None:
            from .config import config

            ttl_s = float(config.control_plane_gossip_ttl_s)
        horizon = time.monotonic() - float(ttl_s)
        alive = set(alive_hexes)
        with self._lock:
            doomed: List[str] = []
            for key in self._kv:
                if key.startswith(GOSSIP_NODE_PREFIXES):
                    owner = key.rsplit("/", 1)[-1]
                elif key.startswith(GOSSIP_RELAY_PREFIX):
                    val = self._kv.get(key)
                    owner = (val.rsplit("|", 1)[-1]
                             if isinstance(val, str) else "")
                else:
                    continue
                if owner in alive:
                    continue
                if self._kv_stamp.get(key, horizon - 1.0) <= horizon:
                    doomed.append(key)
            for key in doomed:
                self._kv.pop(key, None)
                self._kv_stamp.pop(key, None)
            if doomed:
                self._record("sweep", (doomed,))
        return len(doomed)

    # -- introspection / persistence ---------------------------------------
    def shard_info(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "shard_id": self.shard_id,
                "nshards": self.nshards,
                "role": self.role,
                "kv_len": len(self._kv),
                "dir_len": len(self._dir),
                "pid": os.getpid(),
            }

    def snapshot_state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "version": SHARD_SNAPSHOT_VERSION,
                "shard_id": self.shard_id,
                "nshards": self.nshards,
                "time": time.time(),
                "kv": dict(self._kv),
                "dir": {k: sorted(v) for k, v in self._dir.items()},
            }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        if snap.get("version") != SHARD_SNAPSHOT_VERSION:
            raise ValueError(
                f"shard snapshot version {snap.get('version')} "
                f"!= {SHARD_SNAPSHOT_VERSION}")
        with self._lock:
            self._kv = dict(snap.get("kv", {}))
            self._kv_stamp = {}  # stamps are per-process; sweeps are journaled
            self._dir = {k: set(v) for k, v in snap.get("dir", {}).items()}


def write_shard_snapshot(shard: ControlPlaneShard, path: str) -> None:
    """Atomic tmp+rename (persistence.write_snapshot idiom). The journal
    byte offset is captured under the shard lock so snapshot + tail-from-
    offset reconstructs the exact primary state."""
    with shard._lock:
        state = shard.snapshot_state()
        state["journal_offset"] = shard.journal_offset()
    tmp = f"{path}.tmp.{os.getpid()}"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "wb") as f:
        f.write(cloudpickle.dumps(state))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_shard_snapshot(path: str) -> Optional[Dict[str, Any]]:
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return cloudpickle.loads(f.read())


def replay_journal(shard: ControlPlaneShard, path: str, offset: int = 0) -> int:
    """Apply journal records from ``offset`` to EOF (restart recovery).
    Returns the byte offset after the last complete record."""
    if not os.path.exists(path):
        return offset
    with open(path, "rb") as f:
        f.seek(offset)
        while True:
            pos = f.tell()
            line = f.readline()
            if not line or not line.endswith(b"\n"):
                return pos  # EOF or torn tail (unflushed ⇒ unacked)
            method, args = _journal_decode(line)
            shard.apply(method, args)


class _JournalTailer:
    """Standby-side: follows the primary's journal, applying each record."""

    def __init__(self, shard: ControlPlaneShard, path: str, offset: int = 0):
        self._shard = shard
        self._path = path
        self._offset = offset
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="shard-tail")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._offset = replay_journal(self._shard, self._path, self._offset)
            self._stop.wait(0.05)

    def stop_and_drain(self) -> int:
        """Stop tailing, then replay any remaining records to EOF. Returns
        the final offset — promotion appends from exactly here."""
        self._stop.set()
        self._thread.join(timeout=10.0)
        self._offset = replay_journal(self._shard, self._path, self._offset)
        return self._offset


class _SnapshotLoop:
    """Primary-side interval snapshotter (persistence.SnapshotWriter idiom,
    but for one shard's state + journal offset)."""

    def __init__(self, shard: ControlPlaneShard, path: str, interval_s: float):
        self._shard = shard
        self._path = path
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="shard-snapshot")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                write_shard_snapshot(self._shard, self._path)
            except Exception:
                logger.warning("shard snapshot failed", exc_info=True)

    def stop(self) -> None:
        self._stop.set()


class StandbyControl:
    """The standby subprocess's supervisor-facing surface. ``promote(port)``
    turns the tailing replica into the serving primary on the dead
    primary's port; clients' reconnect loops find the new listener at the
    same address and ride through."""

    def __init__(self, shard: ControlPlaneShard, journal_path: str,
                 snapshot_path: str, tailer: _JournalTailer,
                 host: str = "127.0.0.1"):
        self.pubsub = Pubsub()  # handler contract: every served object has one
        self._shard = shard
        self._journal_path = journal_path
        self._snapshot_path = snapshot_path
        self._tailer = tailer
        self._host = host
        self._server: Optional[ControlPlaneServer] = None
        self._snapshots: Optional[_SnapshotLoop] = None

    def shard_info(self) -> Dict[str, Any]:
        return self._shard.shard_info()

    def promote(self, port: int) -> bool:
        from .config import config

        self._tailer.stop_and_drain()
        self._shard.attach_journal(open(self._journal_path, "ab"))
        self._shard.role = "primary"
        # the dead primary's listening socket closed with it; TIME_WAIT on
        # established conns doesn't block a SO_REUSEADDR listen, so the
        # retry loop only covers the kill/bind race
        last: Optional[Exception] = None
        for _ in range(40):
            try:
                self._server = ControlPlaneServer(
                    self._shard, host=self._host, port=int(port),
                    allowed_methods=_SHARD_ALLOWED_METHODS)
                break
            except OSError as exc:
                last = exc
                time.sleep(0.05)
        else:
            raise RuntimeError(f"promote: could not bind port {port}: {last}")
        self._snapshots = _SnapshotLoop(
            self._shard, self._snapshot_path,
            float(config.control_plane_snapshot_interval_s))
        logger.info("shard %d standby promoted on port %d",
                    self._shard.shard_id, port)
        return True


# -- subprocess entry -------------------------------------------------------
def _watch_parent(parent_pid: int) -> None:
    def loop() -> None:
        while True:
            try:
                os.kill(parent_pid, 0)
            except OSError:
                os._exit(0)  # orphaned shard must not outlive its runtime
            time.sleep(1.0)

    threading.Thread(target=loop, daemon=True, name="parent-watch").start()


def _shard_paths(data_dir: str, shard_id: int) -> Tuple[str, str]:
    return (os.path.join(data_dir, f"shard-{shard_id}.journal"),
            os.path.join(data_dir, f"shard-{shard_id}.snap"))


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description="control-plane shard service")
    parser.add_argument("--shard-id", type=int, required=True)
    parser.add_argument("--nshards", type=int, required=True)
    parser.add_argument("--role", choices=("primary", "standby"),
                        default="primary")
    parser.add_argument("--port", type=int, default=0,
                        help="primary serve port (0 = ephemeral)")
    parser.add_argument("--data-dir", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--parent-pid", type=int, default=0)
    args = parser.parse_args(argv)

    from .config import config

    if args.parent_pid:
        _watch_parent(args.parent_pid)
    journal_path, snapshot_path = _shard_paths(args.data_dir, args.shard_id)
    shard = ControlPlaneShard(args.shard_id, args.nshards)
    snap = load_shard_snapshot(snapshot_path)
    offset = 0
    if snap is not None:
        shard.restore_state(snap)
        offset = int(snap.get("journal_offset", 0))

    if args.role == "primary":
        offset = replay_journal(shard, journal_path, offset)
        os.makedirs(args.data_dir, exist_ok=True)
        handle = open(journal_path, "ab")
        if handle.tell() > offset:
            # torn tail from a previous primary's death: unacked bytes —
            # truncate so the journal holds exactly the applied history
            handle.truncate(offset)
            handle.seek(offset)
        shard.attach_journal(handle)
        server = ControlPlaneServer(
            shard, host=args.host, port=args.port,
            allowed_methods=_SHARD_ALLOWED_METHODS)
        _SnapshotLoop(shard, snapshot_path,
                      float(config.control_plane_snapshot_interval_s))
        print(f"SHARD-READY {server.server_address[1]}", flush=True)
    else:
        shard.role = "standby"
        tailer = _JournalTailer(shard, journal_path, offset)
        ctl = StandbyControl(shard, journal_path, snapshot_path, tailer,
                             host=args.host)
        server = ControlPlaneServer(
            ctl, host=args.host, port=0,
            allowed_methods=_STANDBY_ALLOWED_METHODS)
        print(f"SHARD-STANDBY-READY {server.server_address[1]}", flush=True)

    while True:  # serve until killed (or the parent watchdog exits us)
        time.sleep(3600)


# -- head-side supervisor ---------------------------------------------------
class _ShardSlot:
    def __init__(self, shard_id: int) -> None:
        self.shard_id = shard_id
        self.port = 0  # the shard's stable advertised port
        self.primary: Optional[subprocess.Popen] = None
        self.standby: Optional[subprocess.Popen] = None
        self.ctl: Optional[RemoteControlPlane] = None  # standby control conn


class ShardSupervisor:
    """Spawns and babysits K primary+standby shard pairs. Failover: poll
    detects a dead primary, the standby is promoted onto the same port,
    and a fresh standby is respawned behind the new primary."""

    def __init__(self, nshards: int, data_dir: Optional[str] = None,
                 host: str = "127.0.0.1", spawn_standby: bool = True,
                 poll_period_s: float = 0.1):
        self.nshards = int(nshards)
        self.data_dir = data_dir or tempfile.mkdtemp(prefix="ray_tpu_shards_")
        self.host = host
        self.spawn_standby = spawn_standby
        self.failovers: List[Dict[str, float]] = []
        self._poll_period = poll_period_s
        self._slots = [_ShardSlot(i) for i in range(self.nshards)]
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._monitor: Optional[threading.Thread] = None

    # -- process plumbing ---------------------------------------------------
    def _spawn(self, shard_id: int, role: str, port: int = 0,
               timeout_s: float = 60.0) -> Tuple[subprocess.Popen, int]:
        cmd = [sys.executable, "-m", "ray_tpu.core.shard",
               "--shard-id", str(shard_id), "--nshards", str(self.nshards),
               "--role", role, "--port", str(port),
               "--data-dir", self.data_dir, "--host", self.host,
               "--parent-pid", str(os.getpid())]
        # the child must import ray_tpu even when the parent loaded it from
        # an uninstalled checkout via sys.path (driver scripts, REPLs)
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, env=env)
        marker = ("SHARD-READY" if role == "primary"
                  else "SHARD-STANDBY-READY")
        result: List[int] = []

        def read_ready() -> None:
            for raw in proc.stdout:
                line = raw.decode("utf-8", "replace").strip()
                if line.startswith(marker):
                    result.append(int(line.split()[-1]))
                    break

        reader = threading.Thread(target=read_ready, daemon=True)
        reader.start()
        reader.join(timeout=timeout_s)
        if not result:
            proc.kill()
            proc.wait()
            raise RuntimeError(
                f"shard {shard_id} {role} did not come ready in {timeout_s}s")
        # drain the rest of stdout so the child never blocks on a full pipe
        threading.Thread(target=proc.stdout.read, daemon=True).start()
        return proc, result[0]

    def _spawn_standby(self, slot: _ShardSlot) -> None:
        proc, ctl_port = self._spawn(slot.shard_id, "standby")
        slot.standby = proc
        slot.ctl = RemoteControlPlane(
            f"{self.host}:{ctl_port}", role=f"standby-ctl{slot.shard_id}",
            allowed=_STANDBY_ALLOWED_METHODS,
            idempotent=_STANDBY_IDEMPOTENT_METHODS)

    def start(self) -> List[str]:
        for slot in self._slots:
            slot.primary, slot.port = self._spawn(slot.shard_id, "primary")
            _shard_health.set(1.0, {"shard": str(slot.shard_id)})
            if self.spawn_standby:
                self._spawn_standby(slot)
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True, name="shard-supervisor")
        self._monitor.start()
        return self.addresses

    @property
    def addresses(self) -> List[str]:
        return [f"{self.host}:{slot.port}" for slot in self._slots]

    def shard_map(self) -> bytes:
        return json.dumps({"nshards": self.nshards,
                           "addresses": self.addresses}).encode()

    # -- failover -----------------------------------------------------------
    def _monitor_loop(self) -> None:
        while not self._stop.wait(self._poll_period):
            for slot in self._slots:
                proc = slot.primary
                if proc is not None and proc.poll() is not None:
                    try:
                        self._failover(slot)
                    except Exception:
                        logger.exception("shard %d failover failed",
                                         slot.shard_id)

    def _failover(self, slot: _ShardSlot) -> None:
        detected = time.monotonic()
        _shard_health.set(0.0, {"shard": str(slot.shard_id)})
        logger.warning("shard %d primary died (pid %s); promoting standby",
                       slot.shard_id, slot.primary.pid)
        if slot.standby is None or slot.ctl is None:
            raise RuntimeError(f"shard {slot.shard_id} has no standby")
        slot.ctl._call("promote", slot.port, _deadline_s=30.0)
        promoted = time.monotonic()
        with self._lock:
            slot.primary, slot.standby = slot.standby, None
            ctl, slot.ctl = slot.ctl, None
            self.failovers.append({
                "shard_id": slot.shard_id,
                "detected_at": detected,
                "promoted_at": promoted,
                "promote_s": promoted - detected,
            })
        ctl.close()
        _failovers_total.inc()
        _shard_health.set(1.0, {"shard": str(slot.shard_id)})
        if self.spawn_standby:
            self._spawn_standby(slot)  # restore the warm spare

    # -- chaos hooks --------------------------------------------------------
    def kill_primary(self, shard_id: int) -> int:
        """SIGKILL a shard primary (tests/chaos). The monitor loop promotes
        the standby; returns the killed pid."""
        slot = self._slots[shard_id]
        pid = slot.primary.pid
        os.kill(pid, signal.SIGKILL)
        return pid

    def wait_healthy(self, timeout_s: float = 30.0) -> bool:
        """Block until every slot has a live primary (post-failover)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(s.primary is not None and s.primary.poll() is None
                   for s in self._slots):
                return True
            time.sleep(0.05)
        return False

    def stop(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        for slot in self._slots:
            if slot.ctl is not None:
                slot.ctl.close()
            for proc in (slot.primary, slot.standby):
                if proc is None or proc.poll() is not None:
                    continue
                proc.terminate()
        for slot in self._slots:
            for proc in (slot.primary, slot.standby):
                if proc is None:
                    continue
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()


# -- head-side federation wrapper -------------------------------------------
class FederatedPubsub:
    """Pubsub fan-out through the shards: a channel lives on the shard that
    owns its name, so subscribers anywhere in the fleet (head included)
    register with that shard and publishes route to it. Publish is
    best-effort during a failover window — the client deadline bounds the
    stall and drops count on ``control_plane_pubsub_dropped_total``."""

    def __init__(self, clients: List[RemoteControlPlane]):
        self._clients = clients

    def _client(self, channel: str) -> RemoteControlPlane:
        return self._clients[shard_for_key(channel, len(self._clients))]

    def subscribe(self, channel, callback):
        return self._client(channel).subscribe(channel, callback)

    def publish(self, channel, message) -> None:
        try:
            self._client(channel)._call(
                "publish", channel, message, _deadline_s=5.0)
        except (ControlPlaneUnavailable, OSError):
            _pubsub_dropped.inc()
            logger.warning("pubsub publish to %r dropped (shard unreachable)",
                           channel)


class FederatedControlPlane:
    """Head-side wrapper installed by ``enable_federation``: the inner
    ControlPlane keeps membership/actors/jobs/telemetry; cluster KV and
    pubsub route through the shards. K=1 is behavior-identical to the
    single-head path modulo the extra hop."""

    def __init__(self, inner, supervisor: ShardSupervisor,
                 connect_timeout: float = 10.0):
        self._inner = inner
        self._sup = supervisor
        self._clients = [
            RemoteControlPlane(
                addr, connect_timeout=connect_timeout, role=f"head-shard{i}",
                allowed=_SHARD_ALLOWED_METHODS,
                idempotent=_SHARD_IDEMPOTENT_METHODS)
            for i, addr in enumerate(supervisor.addresses)
        ]
        self.pubsub = FederatedPubsub(self._clients)
        # migrate subscribers registered on the inner bus before federation
        # came up, then swap the bus: every internal publish (node/actor
        # state changes) now fans out through the owning shard
        old = inner.pubsub
        with old._lock:
            existing = {ch: list(cbs) for ch, cbs in old._subs.items()}
        for channel, cbs in existing.items():
            for cb in cbs:
                self.pubsub.subscribe(channel, cb)
        inner.pubsub = self.pubsub

    # -- sharded planes -----------------------------------------------------
    def _shard(self, key: str) -> RemoteControlPlane:
        return self._clients[shard_for_key(key, len(self._clients))]

    def kv_put(self, key: str, value: Any, overwrite: bool = True) -> bool:
        return self._shard(key)._call("kv_put", key, value, overwrite)

    def kv_get(self, key: str) -> Optional[Any]:
        return self._shard(key)._call("kv_get", key)

    def kv_del(self, key: str) -> bool:
        return self._shard(key)._call("kv_del", key)

    def kv_keys(self, prefix: str = "") -> List[str]:
        out: List[str] = []
        for client in self._clients:
            out.extend(client._call("kv_keys", prefix))
        return out

    def mark_node_dead(self, node_id, reason: str = "") -> None:
        self._inner.mark_node_dead(node_id, reason)
        node_hex = node_id.hex()
        for client in self._clients:
            try:
                client._call("purge_node", node_hex, _deadline_s=5.0)
            except (ControlPlaneUnavailable, OSError):
                # the TTL sweep is the backstop for a purge that raced a
                # shard failover
                logger.warning("purge_node(%s) dropped on one shard",
                               node_hex[:8])

    def sweep_gossip(self, ttl_s: Optional[float] = None) -> int:
        swept = self._inner.sweep_gossip(ttl_s)
        alive = [n.node_id.hex() for n in self._inner.alive_nodes()]
        for client in self._clients:
            try:
                swept += int(client._call(
                    "sweep_gossip", alive, ttl_s, _deadline_s=10.0))
            except (ControlPlaneUnavailable, OSError):
                pass  # next sweep retries
        return swept

    def shard_infos(self) -> List[Dict[str, Any]]:
        infos = []
        for client in self._clients:
            try:
                infos.append(client._call("shard_info", _deadline_s=5.0))
            except (ControlPlaneUnavailable, OSError):
                infos.append(None)
        return infos

    def close(self) -> None:
        for client in self._clients:
            client.close()

    def __getattr__(self, name: str):
        if name == "_inner":
            raise AttributeError(name)
        return getattr(self._inner, name)


def enable_federation(runtime, nshards: Optional[int] = None,
                      data_dir: Optional[str] = None):
    """Shard the runtime's control plane (api.init hook, opt-in via
    ``config.control_plane_shards``). Returns the (supervisor, federated
    plane) pair, also stashed on ``runtime._federation`` for shutdown."""
    from .config import config

    nshards = int(nshards if nshards is not None
                  else config.control_plane_shards)
    if nshards <= 0:
        return None
    data_dir = data_dir or str(config.control_plane_shard_dir) or None
    sup = ShardSupervisor(nshards, data_dir=data_dir)
    sup.start()
    fed = FederatedControlPlane(runtime.control_plane, sup)
    runtime.control_plane = fed
    # advertise the shard map so joining hosts route directly
    # (rpc.ShardedControlPlane); the key itself lives on its owning shard
    fed.kv_put(SHARD_MAP_KEY, sup.shard_map())
    runtime._federation = (sup, fed)
    logger.info("control plane federated across %d shard(s): %s",
                nshards, sup.addresses)
    return sup, fed


def stop_federation(runtime) -> None:
    fed_pair = getattr(runtime, "_federation", None)
    if not fed_pair:
        return
    sup, fed = fed_pair
    runtime._federation = None
    fed.close()
    sup.stop()


if __name__ == "__main__":
    sys.exit(main())
