"""Cross-host execution plane: remote task/actor dispatch between runtimes.

Reference analogue: the reference's whole lease/push path — a raylet on
another host grants a worker lease and the owner pushes the task to it
(`src/ray/raylet/node_manager.cc :: HandleRequestWorkerLease`,
`src/ray/core_worker/transport/actor_task_submitter.cc` /
`normal_task_submitter.cc`). TPU-native shape (SURVEY §7.1): a SINGLE
CONTROLLER — the head runtime owns the cluster scheduler and PUSHES task
specs to worker hosts over the wire; workers never lease-negotiate. This
matches how TPU pods are actually driven (one coordinator, jax.distributed
workers) and keeps every scheduling policy in one place.

Topology:

  head process                      worker host process
  ------------                      -------------------
  Runtime (scheduler, GCS)  <--RPC--  RemoteControlPlane (register,
   |  ControlPlaneServer               heartbeat, KV, dir_*, pubsub)
   |  ObjectTransferServer  <--pull--  NodeAgent._fetch_async (deps)
   |  RemoteNodeAgent  ----submit--->  WorkerNodeServer -> NodeAgent
   |       ^...........done+seal......   (executes, seals returns into
   |  ObjectDirectory  <--dir_add----     its local store)
   |  (locations)                      ObjectTransferServer (serves
   +--RemoteStoreProxy  ----pull---->     results to any puller)

The data plane stays the existing object-transfer plane (chunked TCP,
sealed payloads); this module only adds DISPATCH. Device arrays still never
cross it: intra-slice tensors ride XLA collectives over ICI.
"""

from __future__ import annotations

import pickle
import queue
import socket
import socketserver
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import object_ledger
from .config import config
from .control_plane import NodeInfo
from .metrics import Counter as _MetricCounter
from .ids import ActorID, NodeID, ObjectID
from .logging import get_logger
from .node_agent import NodeAgent, TaskResult, WorkerCrashedError
from .object_store import ObjectLostError
from .object_transfer import (
    HOST_PREFIX,
    KV_PREFIX,
    ObjectPullError,
    ObjectTransferClient,
    ObjectTransferServer,
    _host_token,
    pull_from_any,
)
from .rpc import ControlPlaneUnavailable, RemoteControlPlane
from .wire import MSG_REQUEST, MSG_RESPONSE, WireError, recv_msg, send_msg

logger = get_logger("cross_host")

NODE_SERVICE_PREFIX = "node_service/"  # KV: node_id hex -> dispatch address

_m_tele_dropped = _MetricCounter(
    "telemetry_dropped_total",
    "Telemetry items dropped by the heartbeat byte budget "
    "(config.telemetry_max_bytes), by kind.")

_m_tele_bytes = _MetricCounter(
    "telemetry_bytes_total",
    "Approximate serialized telemetry bytes shipped to the head, by "
    "field; delta-encoding shows up as these counters going flat while "
    "the cluster is steady.")


def _cap_telemetry(metrics: List[Any], spans: List[Any], events: List[Any],
                   budget: int) -> Tuple[List[Any], List[Any]]:
    """Fit (spans, events) under `budget` bytes alongside the metrics
    snapshot, dropping OLDEST first (both lists are append-ordered). The
    metrics/digest snapshot always ships — it is replace-not-append on
    the head, so it is naturally bounded; spans/events are the burst
    risk. Cursors still advance past dropped items: the budget is a
    deliberate loss, not a retry."""
    if budget <= 0 or (not spans and not events):
        return spans, events
    used = len(_dumps(metrics))
    kept: List[List[Any]] = []
    for kind, items in (("spans", spans), ("events", events)):
        remaining = max(0, budget - used)
        sizes = [len(_dumps(it)) for it in items]
        keep_from = len(items)
        acc = 0
        for i in range(len(items) - 1, -1, -1):  # newest backwards
            if acc + sizes[i] > remaining:
                break
            acc += sizes[i]
            keep_from = i
        used += acc
        dropped = keep_from
        if dropped:
            _m_tele_dropped.inc(dropped, tags={"kind": kind})
            logger.debug("telemetry budget dropped %d oldest %s",
                         dropped, kind)
        kept.append(items[keep_from:])
    return kept[0], kept[1]


def _dumps(obj: Any) -> bytes:
    try:
        return pickle.dumps(obj, protocol=5)
    except Exception:
        import cloudpickle

        return cloudpickle.dumps(obj, protocol=5)


def _dump_exc(e: Optional[BaseException]) -> Optional[bytes]:
    if e is None:
        return None
    try:
        return _dumps(e)
    except Exception:
        return _dumps(RuntimeError(repr(e)))


def _load_exc(blob: Optional[bytes]) -> Optional[BaseException]:
    if blob is None:
        return None
    try:
        return pickle.loads(blob)
    except Exception as e:  # noqa: BLE001 — a broken exc must not mask the task error
        return RuntimeError(f"remote error (undeserializable: {e!r})")


# ---------------------------------------------------------------------------
# Head side: service surface + remote-agent proxy
# ---------------------------------------------------------------------------


class HeadService:
    """The head runtime's served surface: the ControlPlane plus directory
    methods (worker hosts publish/resolve object locations) plus the
    ``proxy_*`` ownership back-channel (code running ON a joined host
    submits nested work through the head's ownership tables —
    `worker_api.WorkerAPIClient` is the client; reference:
    `core_worker.h :: CoreWorker` ownership, collapsed to
    single-controller).

    Served by ``rpc.serve_control_plane`` in place of the bare ControlPlane
    (same duck surface — unknown attributes forward to the control plane)."""

    # pins of clients that stop beating for this long are reaped (a pool
    # worker SIGKILLed mid-task, a joined host that died without close())
    PROXY_CLIENT_STALE_S = 90.0

    def __init__(self, runtime):
        self._runtime = runtime
        self.pubsub = runtime.control_plane.pubsub
        # oid hex -> (client_id, pinned ObjectRef): results a REMOTE caller
        # owns must survive the head's own GC until the caller releases
        # them — or until the caller itself is declared dead (keepalive)
        self._proxy_refs: Dict[str, Tuple[str, Any]] = {}
        self._proxy_clients: Dict[str, float] = {}
        self._proxy_lock = threading.Lock()

    def __getattr__(self, name: str):
        return getattr(self._runtime.control_plane, name)

    # -- ownership back-channel (worker -> head) ----------------------------
    def _pin(self, refs, client_id: str) -> List[str]:
        hexes = [r.object_id.hex() for r in refs]
        now = time.monotonic()
        with self._proxy_lock:
            self._proxy_clients[client_id] = now
            for h, r in zip(hexes, refs):
                self._proxy_refs[h] = (client_id, r)
        self._reap_stale_clients(now)
        return hexes

    def _reap_stale_clients(self, now: float) -> None:
        """Lazy sweep (no dedicated thread): any proxy call pays a cheap
        staleness check. A dead client's pins drop; objects some OTHER
        holder still references survive the head's refcount — the only
        loss window is a returned ref whose consumer never deserialized
        it before the producer died, which without a borrower protocol is
        unknowable (module docstring in worker_api)."""
        with self._proxy_lock:
            stale = [c for c, ts in self._proxy_clients.items()
                     if now - ts > self.PROXY_CLIENT_STALE_S]
            if not stale:
                return
            dead = set(stale)
            for c in stale:
                self._proxy_clients.pop(c, None)
            dropped = [h for h, (c, _r) in self._proxy_refs.items() if c in dead]
            refs = [self._proxy_refs.pop(h) for h in dropped]
        if dropped:
            logger.info("reaped %d pinned objects of %d stale proxy clients",
                        len(dropped), len(dead))
        del refs

    def proxy_keepalive(self, client_id: str) -> bool:
        now = time.monotonic()
        with self._proxy_lock:
            self._proxy_clients[client_id] = now
        self._reap_stale_clients(now)
        return True

    def proxy_job_id(self):
        return self._runtime.job_id

    def proxy_submit_task(self, spec_blob: bytes, client_id: str = "") -> List[str]:
        spec = pickle.loads(spec_blob)
        return self._pin(self._runtime.submit_task(spec), client_id)

    def proxy_create_actor(self, blob: bytes) -> Tuple[str, str, str]:
        cls, args, kwargs, options = pickle.loads(blob)
        info = self._runtime.create_actor(cls, args, kwargs, options)
        return info.actor_id.hex(), info.name or "", info.class_name

    def proxy_submit_actor_task(
        self, actor_id_hex: str, method_name: str,
        payload_blob: bytes, opts_blob: bytes, client_id: str = "",
        trace_ctx=None,
    ) -> List[str]:
        args, kwargs = pickle.loads(payload_blob)
        options = pickle.loads(opts_blob)
        return self._pin(self._runtime.submit_actor_task(
            ActorID.from_hex(actor_id_hex), method_name, args, kwargs,
            options, trace_ctx=trace_ctx),
            client_id)

    PROXY_STREAM_CHANNEL = "proxy_stream"

    def proxy_submit_streaming(self, spec_blob: bytes, client_id: str = "") -> str:
        """Streaming submission from a worker-side client: the head runs
        the generator task and FORWARDS each item ref over the pubsub
        plane (`proxy_stream` events carry (stream_id, index, oid_hex));
        a terminal event carries done/error. Items pin like any other
        proxy-owned refs."""
        import uuid as _uuid

        from .core_worker import ObjectRef

        spec = pickle.loads(spec_blob)
        gen = self._runtime.submit_streaming_task(spec)
        stream_id = _uuid.uuid4().hex
        pubsub = self._runtime.control_plane.pubsub

        def pump() -> None:
            i = 0
            try:
                for ref in gen:
                    self._pin([ref], client_id)
                    pubsub.publish(self.PROXY_STREAM_CHANNEL,
                                   (stream_id, i, ref.object_id.hex(), None))
                    i += 1
                pubsub.publish(self.PROXY_STREAM_CHANNEL,
                               (stream_id, -1, None, None))  # done
            except BaseException as e:  # noqa: BLE001 — forwarded to client
                pubsub.publish(self.PROXY_STREAM_CHANNEL,
                               (stream_id, -1, None, _dump_exc(e)))

        threading.Thread(target=pump, daemon=True,
                         name=f"proxy-stream-{stream_id[:8]}").start()
        return stream_id

    def proxy_kill_actor(self, actor_id_hex: str, no_restart: bool) -> bool:
        self._runtime.kill_actor(ActorID.from_hex(actor_id_hex),
                                 no_restart=no_restart)
        return True

    def proxy_ref_state(self, oid_hexes: List[str]) -> Dict[str, dict]:
        """Nonblocking tri-state per ref: pending | ready | error(+blob).
        Failed tasks seal nothing — the error lives only in the head's
        future table, so worker-side get() must ask here."""
        out: Dict[str, dict] = {}
        rt = self._runtime
        for h in oid_hexes:
            oid = ObjectID.from_hex(h)
            with rt._lock:
                fut = rt._futures.get(oid)
            if fut is None:
                state = "ready" if rt.directory.locations(oid) else "pending"
                out[h] = {"state": state, "error_blob": None}
            elif not fut.event.is_set():
                out[h] = {"state": "pending", "error_blob": None}
            elif fut.error is not None:
                out[h] = {"state": "error", "error_blob": _dump_exc(fut.error)}
            else:
                out[h] = {"state": "ready", "error_blob": None}
        return out

    def proxy_put(self, oid_hex: str, value_blob: bytes, client_id: str = "") -> bool:
        """Pool-worker put: no serving store on that side, so the value
        lands in the head driver's store (one copy, then normal pulls)."""
        from .core_worker import ObjectRef
        from .object_store import seal_value

        oid = ObjectID.from_hex(oid_hex)
        agent = self._runtime.driver_agent
        agent.store.put(oid, seal_value(pickle.loads(value_blob)))
        self._runtime.directory.add_location(oid, agent.node_id)
        self._pin([ObjectRef(oid, self._runtime)], client_id)
        return True

    def proxy_pin(self, oid_hex: str, client_id: str = "") -> bool:
        """Pin a worker-sealed object (put() on a joined host): head-side
        consumers' ref churn must not free it while the remote owner
        still holds it."""
        from .core_worker import ObjectRef

        self._pin([ObjectRef(ObjectID.from_hex(oid_hex), self._runtime)],
                  client_id)
        return True

    def proxy_free(self, oid_hexes: List[str], client_id: str = "") -> bool:
        with self._proxy_lock:
            if client_id:
                # a free IS liveness: a client whose churn keeps the free
                # batches flowing may send no explicit keepalive for
                # minutes — it must not be reaped as stale mid-churn
                self._proxy_clients[client_id] = time.monotonic()
            refs = [self._proxy_refs.pop(h, None) for h in oid_hexes]
        # dropping the pinned refs hands the decision to the head's
        # ReferenceCounter (other head-side holders keep the object alive)
        del refs
        return True

    def proxy_get_value(self, oid_hex: str, timeout: float) -> bytes:
        """Fallback get: the head resolves (incl. lineage reconstruction)
        and ships the value back over the RPC socket. Direct transfer-plane
        pulls are the primary path; this exists for holder-died races.
        Blocks THIS connection's handler thread — clients call it on a
        dedicated short-lived connection (worker_api._get_via_head)."""
        from .core_worker import ObjectRef

        ref = ObjectRef(ObjectID.from_hex(oid_hex), self._runtime)
        value = self._runtime.get([ref], timeout=min(timeout, 60.0))[0]
        return _dumps(value)

    # -- directory ops (worker -> head) ------------------------------------
    def dir_add_location(self, oid_hex: str, node_id_hex: str,
                         bytes_available: Optional[int] = None) -> bool:
        self._runtime.directory.add_location(
            ObjectID.from_hex(oid_hex), NodeID.from_hex(node_id_hex),
            bytes_available=bytes_available,
        )
        return True

    def dir_remove_location(self, oid_hex: str, node_id_hex: str) -> bool:
        self._runtime.directory.remove_location(
            ObjectID.from_hex(oid_hex), NodeID.from_hex(node_id_hex)
        )
        return True

    def dir_locations(self, oid_hex: str) -> List[str]:
        return [
            n.hex()
            for n in self._runtime.directory.locations(ObjectID.from_hex(oid_hex))
        ]

    # -- profiling plane (rpc allowlist: profile_start / profile_fetch) -----
    def _profile_agent(self, node: str):
        """Resolve a node-id hex (any unambiguous prefix; "" = the head's
        own driver node) to the agent holding the profiling duck — a local
        NodeAgent or a RemoteNodeAgent proxying a joined host."""
        rt = self._runtime
        if not node:
            return rt.driver_agent
        with rt._lock:
            agents = dict(rt.agents)
        matches = [(nid, a) for nid, a in agents.items()
                   if nid.hex().startswith(node)]
        if len(matches) == 1:
            return matches[0][1]
        known = sorted(nid.hex()[:12] for nid in agents)
        if not matches:
            raise KeyError(f"no node matches {node!r} (known: {known})")
        raise KeyError(f"node prefix {node!r} is ambiguous (known: {known})")

    def profile_start(self, node: str = "", pid: int = 0,
                      duration_s: float = 5.0, hz=None, kind: str = "cpu",
                      logdir: str = "") -> Dict[str, Any]:
        out = dict(self._profile_agent(node).profile_start(
            pid=pid, duration_s=duration_s, hz=hz, kind=kind, logdir=logdir))
        out.setdefault("node", node)
        return out

    def profile_fetch(self, node: str = "", pid: int = 0,
                      kind: str = "cpu") -> Dict[str, Any]:
        out = dict(self._profile_agent(node).profile_fetch(pid=pid, kind=kind))
        out.setdefault("node", node)
        return out


class _AgentStoreAdapter:
    """Serves EVERY local agent's store through one transfer server, so a
    single advertised address covers all of the head's (virtual) nodes."""

    def __init__(self, runtime):
        self._runtime = runtime

    def _stores(self):
        with self._runtime._lock:
            agents = list(self._runtime.agents.values())
        return [a.store for a in agents if isinstance(a, NodeAgent)]

    def contains(self, oid) -> bool:
        return any(s.contains(oid) for s in self._stores())

    def get(self, oid, timeout=None):
        for s in self._stores():
            if s.contains(oid):
                return s.get(oid, timeout=timeout)
        raise KeyError(oid)

    def get_raw(self, oid, timeout=None):
        for s in self._stores():
            if s.contains(oid):
                return s.get_raw(oid, timeout=timeout)
        raise KeyError(oid)


class RemoteStoreProxy:
    """Duck-typed store view of a remote runtime: get/get_raw pull over the
    transfer plane, delete goes over the dispatch connection."""

    def __init__(self, owner: "RemoteNodeAgent"):
        self._owner = owner
        self._transfer = ObjectTransferClient()

    def contains(self, oid) -> bool:
        try:
            return bool(
                self._transfer._call(self._owner.transfer_addr, "contains", oid.hex())
            )
        except ObjectPullError:
            return False

    def get(self, oid, timeout=None):
        # store duck contract: callers handle TimeoutError/ObjectLostError,
        # never the transfer plane's own error type
        try:
            return self._transfer.pull(self._owner.transfer_addr, oid)
        except ObjectPullError as e:
            raise ObjectLostError(oid) from e

    def get_raw(self, oid, timeout=None):
        try:
            return self._transfer.pull(self._owner.transfer_addr, oid, raw=True)
        except ObjectPullError as e:
            raise ObjectLostError(oid) from e

    def delete(self, oid) -> None:
        try:
            self._owner._call("store_delete", oid_hex=oid.hex())
        except (WireError, OSError, RuntimeError):
            pass  # holder gone: nothing to delete

    def put(self, oid, value, nbytes=None) -> None:
        raise NotImplementedError("push-to-remote-store is not part of the plane "
                                  "(the consumer pulls; see object_transfer.py)")

    def close(self) -> None:
        self._transfer.close()


class _RemoteResources:
    """NodeAgent.resources duck for a remote node: placement-group
    reservations acquire/release on the WORKER's own ledger over the
    dispatch plane, so its heartbeats (and its local task accounting)
    see them — the head holding a shadow ledger would desync the moment
    the worker heartbeat overwrote it. (Reference: bundle resources
    live in the raylet's local resource manager,
    `cluster_resource_manager.cc`.)"""

    def __init__(self, owner: "RemoteNodeAgent"):
        self._owner = owner

    def try_acquire(self, demand: Dict[str, float]) -> bool:
        try:
            return bool(self._owner._call("try_acquire", demand=dict(demand)))
        except (WorkerCrashedError, RuntimeError):
            return False

    def release(self, demand: Dict[str, float]) -> None:
        try:
            self._owner._call("release", demand=dict(demand))
        except (WorkerCrashedError, RuntimeError):
            pass  # node gone: its ledger died with it

    def available(self) -> Dict[str, float]:
        try:
            return dict(self._owner._call("resources_available"))
        except (WorkerCrashedError, RuntimeError):
            return {}


class RemoteNodeAgent:
    """Head-side proxy with NodeAgent's duck surface, dispatching to a
    WorkerNodeServer on another host.

    submit() is asynchronous: the spec ships as one frame; the worker sends
    the TaskResult frame whenever the task finishes (responses interleave,
    matched by id). Return VALUES never ride the dispatch plane — the worker
    seals them into its own store and registers locations with the head
    directory before acking, so a subsequent get() pulls them over the
    transfer plane exactly like any other remote object."""

    is_remote = True

    def __init__(self, info: NodeInfo, node_service_addr: str, transfer_addr: str):
        self.info = info
        self.node_id = info.node_id
        self.node_service_addr = node_service_addr
        self.transfer_addr = transfer_addr
        object_ledger.note_peer(transfer_addr, info.node_id.hex())
        self._stopped = threading.Event()
        self.store = RemoteStoreProxy(self)
        self.resources = _RemoteResources(self)
        host, _, port = node_service_addr.rpartition(":")
        self._sock = socket.create_connection((host, int(port)), timeout=10.0)
        # connect timeout only — the dispatch connection is long-lived and
        # idle between tasks; a lingering socket timeout would kill the
        # read loop after 10 quiet seconds
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._send_lock = threading.Lock()
        # callback-map mutations get their OWN mutex, never held across a
        # socket op: the read loop must not park behind _send_lock (held
        # across a blocking send_msg) or a full-buffer send could deadlock
        # the whole dispatch plane four ways (head write <-> worker write)
        self._cb_lock = threading.Lock()
        self._next_id = 0
        self._done_cbs: Dict[int, Callable[[TaskResult], None]] = {}
        self._stream_cbs: Dict[int, Callable] = {}
        self._replies: Dict[int, dict] = {}
        self._reply_cv = threading.Condition()
        # Completions run OFF the read loop: _on_task_done may call back
        # into this agent (e.g. kill_actor on killed-during-init), which
        # needs the read loop free to deliver the reply.
        self._completions: "queue.Queue[Optional[Tuple[Callable, TaskResult]]]" = queue.Queue()
        self._completion_thread = threading.Thread(
            target=self._completion_loop, daemon=True,
            name=f"remote-agent-done-{info.node_id.hex()[:8]}",
        )
        self._completion_thread.start()
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"remote-agent-{info.node_id.hex()[:8]}",
        )
        self._reader.start()

    def _completion_loop(self) -> None:
        while True:
            item = self._completions.get()
            if item is None:
                return
            cb, result = item
            try:
                cb(result)
            except Exception:  # noqa: BLE001
                logger.exception("task-done callback failed")

    # -- plumbing -----------------------------------------------------------
    def _read_loop(self) -> None:
        try:
            while not self._stopped.is_set():
                msg_type, payload = recv_msg(self._sock)
                if msg_type != MSG_RESPONSE:
                    continue
                req_id = payload.get("id")
                if "stream_item" in payload:
                    with self._cb_lock:
                        if self._stopped.is_set():
                            continue
                        scb = self._stream_cbs.get(req_id)
                        if scb is not None:
                            # enqueued UNDER the lock: the failure sweep
                            # enqueues its sentinel under the same lock, so
                            # no item can land after the sentinel and be
                            # silently dropped by the completion loop
                            self._completions.put((
                                lambda _r, _s=scb, _p=payload: _s(
                                    _p["stream_item"],
                                    ObjectID.from_hex(_p["oid_hex"])),
                                None,
                            ))
                    continue
                # pop AND enqueue under _cb_lock, mirroring
                # _fail_outstanding: a reply racing stop()/connection-drop
                # must land in exactly one of (this delivery, the failure
                # sweep) — never both, never neither (an enqueue outside
                # the lock could land after the sweep's stop sentinel and
                # never run)
                delivered = False
                with self._cb_lock:
                    if self._stopped.is_set():
                        continue
                    cb = self._done_cbs.pop(req_id, None)
                    self._stream_cbs.pop(req_id, None)
                    if cb is not None:
                        self._completions.put(
                            (cb, self._to_task_result(payload)))
                        delivered = True
                if not delivered:
                    with self._reply_cv:
                        self._replies[req_id] = payload
                        self._reply_cv.notify_all()
        except (WireError, OSError) as e:
            if not self._stopped.is_set():
                logger.warning("dispatch connection to node %s dropped: %r",
                               self.node_id.hex()[:8], e)
        except Exception:  # noqa: BLE001 — a cb bug must not die silently
            logger.exception("remote-agent read loop failed")
        finally:
            self._fail_outstanding(WorkerCrashedError(
                f"connection to node {self.node_id.hex()[:8]} lost"))

    def _fail_outstanding(self, error: BaseException) -> None:
        # under _cb_lock: _send registers callbacks under the same lock
        # and checks _stopped first, so a registration either lands before
        # this snapshot (and is failed here) or observes _stopped and
        # raises — no callback can be silently dropped between the two
        with self._cb_lock:
            self._stopped.set()
            cbs = list(self._done_cbs.values())
            self._done_cbs.clear()
            self._stream_cbs.clear()
            # sweep + sentinel enqueued under the SAME lock the read loop
            # enqueues deliveries under: the sentinel is provably last, so
            # the completion loop never exits with work still queued
            for cb in cbs:
                self._completions.put(
                    (cb, TaskResult(task_id=None, ok=False, error=error)))
            self._completions.put(None)  # drain, then stop the thread
        with self._reply_cv:
            self._replies[-1] = {"ok": False, "error": repr(error), "exc": None}
            self._reply_cv.notify_all()

    @staticmethod
    def _to_task_result(payload: dict) -> TaskResult:
        if payload.get("ok"):
            return TaskResult(task_id=None, ok=True, values=None)
        error = _load_exc(payload.get("exc_blob")) or WorkerCrashedError(
            payload.get("error", "remote task failed"))
        return TaskResult(
            task_id=None, ok=False, error=error,
            is_application_error=bool(payload.get("is_application_error")),
        )

    def _send(self, method: str, *, done: Optional[Callable] = None,
              stream: Optional[Callable] = None, **fields) -> int:
        with self._send_lock:
            with self._cb_lock:
                if self._stopped.is_set():
                    raise WorkerCrashedError(
                        f"connection to node {self.node_id.hex()[:8]} lost")
                self._next_id += 1
                req_id = self._next_id
                if done is not None:
                    self._done_cbs[req_id] = done
                if stream is not None:
                    # registered BEFORE the frame ships: a stream item can
                    # race back before this method returns
                    self._stream_cbs[req_id] = stream
            try:
                send_msg(self._sock, MSG_REQUEST,
                         {"id": req_id, "method": method, **fields})
            except (WireError, OSError) as e:
                with self._cb_lock:
                    had_done = self._done_cbs.pop(req_id, None) is not None
                    self._stream_cbs.pop(req_id, None)
                if done is not None and not had_done:
                    # the failure sweep raced in and already swept this
                    # callback into the completions queue: delivery is the
                    # sweep's; raising would make the caller deliver TWICE
                    return req_id
                raise WorkerCrashedError(
                    f"dispatch to node {self.node_id.hex()[:8]} failed: {e}")
        return req_id

    def _call(self, method: str, timeout: float = 30.0, **fields) -> Any:
        req_id = self._send(method, **fields)
        deadline = time.monotonic() + timeout
        with self._reply_cv:
            while req_id not in self._replies:
                if self._stopped.is_set():
                    raise WorkerCrashedError(
                        f"connection to node {self.node_id.hex()[:8]} lost")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise WorkerCrashedError(f"rpc {method} timed out")
                self._reply_cv.wait(timeout=min(1.0, remaining))
            resp = self._replies.pop(req_id)
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", f"{method} failed"))
        return resp.get("value")

    # -- NodeAgent duck surface --------------------------------------------
    def submit(self, spec, done: Callable[[TaskResult], None],
               stream: Optional[Callable] = None) -> None:
        if self._stopped.is_set():
            done(TaskResult(spec.task_id, ok=False,
                            error=WorkerCrashedError("remote node disconnected")))
            return

        def on_result(result: TaskResult) -> None:
            result.task_id = spec.task_id
            done(result)

        try:
            self._send("submit", done=on_result, stream=stream,
                       spec_blob=_dumps(spec))
        except WorkerCrashedError as e:
            done(TaskResult(spec.task_id, ok=False, error=e))

    def kill_actor(self, actor_id: ActorID, cause: str = "killed") -> bool:
        try:
            return bool(self._call("kill_actor", actor_id_hex=actor_id.hex(),
                                   cause=cause))
        except (WorkerCrashedError, RuntimeError):
            return False

    def has_actor(self, actor_id: ActorID) -> bool:
        try:
            return bool(self._call("has_actor", actor_id_hex=actor_id.hex()))
        except (WorkerCrashedError, RuntimeError):
            return False

    def prefetch_object(self, oid_hex: str, timeout: float = 120.0) -> bool:
        """Ask the worker host to pull one object into its local store
        (broadcast fan-out). Synchronous: returns once the replica is
        sealed and its location registered, raising on pull failure."""
        return bool(self._call("prefetch_object", timeout=timeout,
                               oid_hex=oid_hex))

    def submit_direct(self, actor_id: ActorID, fn) -> None:
        self.submit_direct_blob(actor_id, _dumps(fn))

    def submit_direct_blob(self, actor_id: ActorID, fn_blob: bytes) -> None:
        """Compiled-graph mailbox enqueue on a remote actor: the closure
        ships as one frame (its channels pickle as DistChannel handles,
        core/channels.py; CompiledDAG serializes each remote closure ONCE
        at compile) and the worker enqueues it INLINE on its dispatch
        loop — one connection, serial handling, so mailbox order equals
        execute() order. Fire-and-forget: an actor dying in the window
        between execute()'s liveness pre-check and the remote enqueue is
        logged here and surfaces as the ref's timeout — the documented
        stranded-envelope semantics — where the local path would raise
        synchronously. A dead CONNECTION still raises here like the
        local path's dead-actor check."""
        def on_done(result: TaskResult) -> None:
            if not result.ok:
                logger.warning("remote submit_direct failed: %r", result.error)

        self._send("submit_direct", done=on_done,
                   actor_id_hex=actor_id.hex(), fn_blob=fn_blob)

    def kill_running_tasks(self) -> None:
        try:
            self._call("kill_running_tasks", timeout=5.0)
        except (WorkerCrashedError, RuntimeError):
            pass

    # -- profiling plane (util/profiler via node_agent) ---------------------
    def profilable_pids(self) -> Dict[str, Any]:
        return dict(self._call("profilable_pids", timeout=10.0))

    def profile_start(self, pid: int = 0, duration_s: float = 5.0,
                      hz: Optional[float] = None, kind: str = "cpu",
                      logdir: str = "") -> Dict[str, Any]:
        return dict(self._call(
            "profile_start", timeout=15.0, pid=int(pid),
            duration_s=float(duration_s), hz=hz, kind=kind, logdir=logdir))

    def profile_fetch(self, pid: int = 0, kind: str = "cpu") -> Dict[str, Any]:
        return dict(self._call("profile_fetch", timeout=15.0, pid=int(pid),
                               kind=kind))

    def _sync_load(self) -> None:
        """No-op: the worker host heartbeats the control plane itself."""

    def stop(self, notify: bool = True) -> None:
        """notify=False drops the proxy without telling the worker host to
        exit: used when the head reaps a node on a stale heartbeat — the
        host may only be partitioned and will rejoin, so sending the stop
        frame would kill a survivor."""
        if self._stopped.is_set():
            return
        if notify:
            try:
                self._send("stop")
            except (WorkerCrashedError, OSError):
                pass
        self._fail_outstanding(WorkerCrashedError("node removed"))
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
        self.store.close()


def enable_cross_host(runtime) -> ObjectTransferServer:
    """Turn the head runtime into a joinable cluster head: serve its agents'
    stores on the transfer plane and attach a RemoteNodeAgent for every
    worker host that registers (reference: node addition through GCS node
    table + raylet connection, `gcs_node_manager.cc`)."""
    transfer = ObjectTransferServer(
        _AgentStoreAdapter(runtime),
        host=config.control_plane_rpc_host,
    )
    # the ADVERTISED address must be reachable from workers: a wildcard
    # bind (0.0.0.0) would advertise an address that resolves to the
    # WORKER's own host — substitute the head's cluster-facing node_host
    bind_host, _, bind_port = transfer.address.rpartition(":")
    if bind_host in ("0.0.0.0", "::", ""):
        advertised = f"{config.node_host}:{bind_port}"
    else:
        advertised = transfer.address

    # one address serves every local (virtual) node's store
    def _advertise_local(node_id: NodeID) -> None:
        runtime.control_plane.kv_put(KV_PREFIX + node_id.hex(), advertised)

    with runtime._lock:
        local_ids = list(runtime.agents)
    for nid in local_ids:
        _advertise_local(nid)

    def on_node_event(event: Tuple[str, NodeInfo]) -> None:
        state, info = event
        if state == "DEAD":
            # drop the proxy so a rejoining host (same ID, re-register)
            # dials fresh instead of reusing a dead socket. remove_node is
            # idempotent (agent already popped -> early return) and its
            # mark_node_dead on an already-DEAD node does not re-publish,
            # so this cannot loop.
            runtime.remove_node(info.node_id)
            return
        if state != "ALIVE":
            return
        with runtime._lock:
            known = info.node_id in runtime.agents
        if known:
            return
        svc = runtime.control_plane.kv_get(NODE_SERVICE_PREFIX + info.node_id.hex())
        taddr = runtime.control_plane.kv_get(KV_PREFIX + info.node_id.hex())
        if not svc or not taddr:
            _advertise_local(info.node_id)  # a local late-joining virtual node
            return
        svc = svc.decode() if isinstance(svc, bytes) else svc
        taddr = taddr.decode() if isinstance(taddr, bytes) else taddr
        try:
            proxy = RemoteNodeAgent(info, svc, taddr)
        except OSError as e:
            logger.warning("cannot reach joining node %s at %s: %s",
                           info.node_id.hex()[:8], svc, e)
            runtime.control_plane.mark_node_dead(info.node_id, f"unreachable: {e}")
            return
        runtime.directory.register_agent(proxy)
        with runtime._lock:
            runtime.agents[info.node_id] = proxy
        logger.info("remote node %s joined (dispatch %s, transfer %s)",
                    info.node_id.hex()[:8], svc, taddr)
        runtime.pg_manager._retry_queued()
        runtime._kick_scheduler()

    runtime.control_plane.pubsub.subscribe("node", on_node_event)
    # catch-up sweep: the RPC server starts serving BEFORE this subscribe
    # (api.init order), so a worker re-registering into a restarted head in
    # that window would be ALIVE in the table but never dialed — replay
    # registrations that raced in
    for info in runtime.control_plane.alive_nodes():
        on_node_event(("ALIVE", info))
    # workers block on object availability via this channel (obj_loc):
    # publish every directory add so RemoteDirectoryClient.subscribe_once
    # wakes without polling
    runtime.directory.on_add = lambda oid, nid: runtime.control_plane.pubsub.publish(
        "obj_loc", oid.hex()
    )
    runtime._transfer_server = transfer
    return transfer


# ---------------------------------------------------------------------------
# Worker side: join a head, serve dispatch
# ---------------------------------------------------------------------------


class RemoteDirectoryClient:
    """Worker-side view of the head's ObjectDirectory (duck-typed for
    NodeAgent): location writes go to the head; reads resolve holders into
    pull-capable proxies via the KV-advertised transfer addresses."""

    def __init__(self, control_plane: RemoteControlPlane, self_node_id: NodeID):
        self._cp = control_plane
        self._self_id = self_node_id
        self._transfer = ObjectTransferClient()
        self._lock = threading.Lock()
        self._waiters: Dict[str, List[Callable[[], None]]] = {}
        self._subscribed = False
        # ~1s-cached ALIVE node set: locate() must not hand out holders on
        # nodes the head already marked DEAD (the mark -> KV-purge window),
        # but a per-locate alive_nodes RPC would double every pull's RTT
        self._alive_hexes: Optional[set] = None
        self._alive_at = 0.0
        # host tokens are immutable per boot: cache them forever so the
        # prefer_local ranking in locate() costs one KV round-trip per
        # holder total, not per pull
        self._host_tokens: Dict[str, str] = {}
        # waiter callbacks run OFF the control-plane read loop: they issue
        # blocking RPCs (dir_locations, kv_get) on the SAME connection whose
        # read loop delivers the replies — firing inline would deadlock the
        # whole worker (pull hangs, heartbeats wedge)
        self._fire_queue: "queue.Queue[Optional[str]]" = queue.Queue()
        self._last_fire: Dict[str, float] = {}
        threading.Thread(
            target=self._fire_loop, daemon=True, name="dir-obj-ready"
        ).start()

    def _fire_loop(self) -> None:
        while True:
            oid_hex = self._fire_queue.get()
            if oid_hex is None:
                return
            with self._lock:
                has_waiters = bool(self._waiters.get(oid_hex))
            if not has_waiters:
                # duplicate enqueue (subscribe-check + pubsub event race):
                # nothing to fire, and sleeping here would head-of-line
                # delay ready callbacks for unrelated objects
                continue
            # throttle per object: a pull that keeps failing against a
            # stale location (dead holder not yet reaped) re-subscribes and
            # immediately re-fires — unthrottled, that hammers the head
            # with dir_locations/kv_get RPCs for the whole reap window
            gap = 0.1 - (time.monotonic() - self._last_fire.get(oid_hex, 0.0))
            if gap > 0:
                time.sleep(gap)
            if len(self._last_fire) > 4096:
                self._last_fire.clear()
            self._last_fire[oid_hex] = time.monotonic()
            self._fire(oid_hex)

    def add_location(self, object_id: ObjectID, node_id: NodeID,
                     bytes_available: Optional[int] = None) -> None:
        self._cp.dir_add_location(object_id.hex(), node_id.hex(),
                                  bytes_available=bytes_available)

    def remove_location(self, object_id: ObjectID, node_id: NodeID) -> None:
        self._cp.dir_remove_location(object_id.hex(), node_id.hex())

    def locations(self, object_id: ObjectID) -> List[NodeID]:
        return [NodeID.from_hex(h) for h in self._cp.dir_locations(object_id.hex())]

    def _alive(self) -> Optional[set]:
        now = time.monotonic()
        if self._alive_hexes is None or now - self._alive_at > 1.0:
            try:
                self._alive_hexes = {
                    n.node_id.hex() for n in self._cp.alive_nodes()}
                self._alive_at = now
            except Exception:  # noqa: BLE001 — fall back to unfiltered
                self._alive_at = now
        return self._alive_hexes

    def _host_token_of(self, hexid: str) -> str:
        token = self._host_tokens.get(hexid)
        if token is None:
            try:
                raw = self._cp.kv_get(HOST_PREFIX + hexid)
            except Exception:  # noqa: BLE001 — tokens are advisory
                raw = None
            token = raw.decode() if isinstance(raw, bytes) else (raw or "")
            self._host_tokens[hexid] = token
        return token

    def locate(self, object_id: ObjectID, exclude: Optional[NodeID] = None,
               prefer_local: bool = False):
        """First live holder. With prefer_local, holders whose advertised
        host token matches this process rank first — a same-host pull
        short-circuits to the shm fd handoff in ObjectTransferClient.pull
        instead of copying the payload through a loopback socket."""
        alive = self._alive()
        candidates = []
        for hexid in self._cp.dir_locations(object_id.hex()):
            node_id = NodeID.from_hex(hexid)
            if node_id == exclude:
                continue
            if alive is not None and hexid not in alive:
                continue  # directory entry outlived its node
            addr = self._cp.kv_get(KV_PREFIX + hexid)
            if not addr:
                continue
            addr = addr.decode() if isinstance(addr, bytes) else addr
            if not prefer_local:
                object_ledger.note_peer(addr, hexid)
                return _PullHolder(addr, self._transfer, node_id)
            candidates.append((hexid, node_id, addr))
        if not candidates:
            return None
        local = _host_token()
        candidates.sort(key=lambda c: self._host_token_of(c[0]) != local)
        hexid, node_id, addr = candidates[0]
        object_ledger.note_peer(addr, hexid)
        return _PullHolder(addr, self._transfer, node_id)

    def subscribe_once(self, object_id: ObjectID, callback: Callable[[], None]) -> None:
        oid_hex = object_id.hex()
        with self._lock:
            if not self._subscribed:
                self._cp.subscribe("obj_loc", self._on_obj_loc)
                self._subscribed = True
            self._waiters.setdefault(oid_hex, []).append(callback)
        # subscribe-then-check closes the race with a concurrent seal; fire
        # via the queue so a failed-pull -> resubscribe cycle cannot recurse
        # on this stack
        if self._cp.dir_locations(oid_hex):
            self._fire_queue.put(oid_hex)

    def _on_obj_loc(self, oid_hex: str) -> None:
        self._fire_queue.put(oid_hex)

    def _fire(self, oid_hex: str) -> None:
        with self._lock:
            callbacks = self._waiters.pop(oid_hex, [])
        for cb in callbacks:
            try:
                cb()
            except Exception:  # noqa: BLE001
                logger.exception("object-ready callback failed")


class _PullHolder:
    """Minimal holder handle: .store.get_raw pulls sealed bytes."""

    class _Store:
        def __init__(self, addr: str, client: ObjectTransferClient):
            self._addr = addr
            self._client = client

        def get_raw(self, oid, timeout=None):
            try:
                return self._client.pull(self._addr, oid, raw=True)
            except ObjectPullError as e:
                raise ObjectLostError(oid) from e

        def get(self, oid, timeout=None):
            try:
                return self._client.pull(self._addr, oid)
            except ObjectPullError as e:
                raise ObjectLostError(oid) from e

    def __init__(self, addr: str, client: ObjectTransferClient,
                 node_id: Optional[NodeID] = None):
        self.store = self._Store(addr, client)
        self.node_id = node_id
        self._stopped = threading.Event()  # duck parity with NodeAgent


class _WorkerDispatchHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: "WorkerNodeServer" = self.server  # type: ignore[assignment]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        send_lock = threading.Lock()

        def reply(payload: dict) -> None:
            try:
                with send_lock:
                    send_msg(sock, MSG_RESPONSE, payload)
            except (WireError, OSError):
                pass  # head gone; worker keeps running until told otherwise

        try:
            while True:
                msg_type, req = recv_msg(sock)
                if msg_type != MSG_REQUEST:
                    raise WireError(f"unexpected message type {msg_type}")
                try:
                    self._dispatch(server, req, reply)
                except Exception as e:  # noqa: BLE001 — serialized to caller
                    reply({"id": req.get("id"), "ok": False, "error": repr(e)})
        except (WireError, OSError):
            pass

    def _dispatch(self, server: "WorkerNodeServer", req: dict, reply) -> None:
        method = req.get("method")
        req_id = req.get("id")
        agent = server.agent
        if method == "submit":
            spec = pickle.loads(req["spec_blob"])

            def done(result: TaskResult) -> None:
                if result.ok:
                    reply({"id": req_id, "ok": True})
                else:
                    reply({
                        "id": req_id, "ok": False,
                        "error": repr(result.error),
                        "exc_blob": _dump_exc(result.error),
                        "is_application_error": result.is_application_error,
                    })

            stream_cb = None
            if spec.options.num_returns == "streaming":
                def stream_cb(i, oid):
                    reply({"id": req_id, "stream_item": i, "oid_hex": oid.hex()})

            # off the read loop: submit() pulls missing dependencies inline,
            # which must not serialize behind other dispatches
            threading.Thread(
                target=agent.submit, args=(spec, done),
                kwargs={"stream": stream_cb}, daemon=True,
                name=f"dispatch-{spec.task_id.hex()[:8]}",
            ).start()
        elif method == "submit_direct":
            # INLINE, never a thread: serial handling on this connection
            # is what makes remote mailbox order match execute() order
            fn = pickle.loads(req["fn_blob"])
            agent.submit_direct(ActorID.from_hex(req["actor_id_hex"]), fn)
            reply({"id": req_id, "ok": True})
        elif method == "kill_actor":
            ok = agent.kill_actor(ActorID.from_hex(req["actor_id_hex"]),
                                  cause=req.get("cause", "killed"))
            reply({"id": req_id, "ok": True, "value": ok})
        elif method == "has_actor":
            reply({"id": req_id, "ok": True,
                   "value": agent.has_actor(ActorID.from_hex(req["actor_id_hex"]))})
        elif method == "store_delete":
            agent.store.delete(ObjectID.from_hex(req["oid_hex"]))
            reply({"id": req_id, "ok": True, "value": True})
        elif method == "prefetch_object":
            # broadcast fan-out: pull the object into THIS host's store
            # (joining the relay tree if one is forming). Off the read
            # loop — a 1GB pull must not stall unrelated dispatches.
            def _prefetch():
                try:
                    rt = getattr(server, "runtime", None)
                    if rt is None:
                        raise RuntimeError("worker runtime not attached")
                    rt.prefetch_object(req["oid_hex"])
                    reply({"id": req_id, "ok": True, "value": True})
                except Exception as e:  # noqa: BLE001 — serialized to caller
                    reply({"id": req_id, "ok": False, "error": repr(e)})

            threading.Thread(target=_prefetch, daemon=True,
                             name="dispatch-prefetch").start()
        elif method == "try_acquire":
            # placement-group bundle reservation on THIS node's ledger
            ok = agent.resources.try_acquire(req["demand"])
            agent._sync_load()
            reply({"id": req_id, "ok": True, "value": ok})
        elif method == "release":
            agent.resources.release(req["demand"])
            agent._sync_load()
            reply({"id": req_id, "ok": True, "value": True})
        elif method == "resources_available":
            reply({"id": req_id, "ok": True,
                   "value": agent.resources.available()})
        elif method == "kill_running_tasks":
            agent.kill_running_tasks()
            reply({"id": req_id, "ok": True, "value": True})
        elif method == "profilable_pids":
            reply({"id": req_id, "ok": True, "value": agent.profilable_pids()})
        elif method == "profile_start":
            reply({"id": req_id, "ok": True, "value": agent.profile_start(
                pid=req.get("pid", 0),
                duration_s=req.get("duration_s", 5.0),
                hz=req.get("hz"), kind=req.get("kind", "cpu"),
                logdir=req.get("logdir", ""))})
        elif method == "profile_fetch":
            # dump_child blocks on the signalled child writing its file:
            # off the read loop so a slow dump can't stall other dispatches
            def _fetch():
                try:
                    value = agent.profile_fetch(
                        pid=req.get("pid", 0), kind=req.get("kind", "cpu"))
                    reply({"id": req_id, "ok": True, "value": value})
                except Exception as e:  # noqa: BLE001 — serialized to caller
                    reply({"id": req_id, "ok": False, "error": repr(e)})

            threading.Thread(target=_fetch, daemon=True,
                             name="dispatch-profile-fetch").start()
        elif method == "ping":
            reply({"id": req_id, "ok": True, "value": True})
        elif method == "stop":
            reply({"id": req_id, "ok": True, "value": True})
            server.owner_requested_stop.set()
        else:
            reply({"id": req_id, "ok": False, "error": f"unknown method {method!r}"})


class WorkerNodeServer(socketserver.ThreadingTCPServer):
    """Serves one worker host's NodeAgent for head dispatch."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, agent: NodeAgent, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _WorkerDispatchHandler)
        self.agent = agent
        # back-reference set by WorkerRuntime: prefetch_object needs the
        # runtime's transfer client/server, not just the agent
        self.runtime: Optional["WorkerRuntime"] = None
        self.owner_requested_stop = threading.Event()
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="worker-dispatch"
        )
        self._thread.start()
        logger.info("worker dispatch on %s:%d", *self.server_address)

    @property
    def address(self) -> str:
        host, port = self.server_address
        return f"{host}:{port}"

    def stop(self) -> None:
        self.shutdown()
        self.server_close()


class WorkerRuntime:
    """A worker host joined to a head: one NodeAgent + the servers that make
    it reachable. Created by ``ray_tpu.init(address=...)`` or
    ``ray-tpu start --address=...``.

    This process is a WORKER, not a driver: the head owns scheduling and
    object futures (single-controller, SURVEY §7.1). The task-submission
    API still works here — it proxies to the head's ownership tables over
    the back-channel (``api_client()`` / `worker_api.WorkerAPIClient`),
    mirroring the reference's every-worker-is-a-CoreWorker pattern without
    giving up the single scheduler."""

    def __init__(
        self,
        address: str,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        node_host: Optional[str] = None,
    ):
        from ..api import default_node_resources

        if node_host is None:
            node_host = config.node_host

        self.head_address = address
        self._node_host = node_host
        self.control_plane = RemoteControlPlane(address, role="worker")
        # federated head? adopt shard routing for KV/pubsub so this host's
        # gossip never rides the head connection (dir_* stays head-routed:
        # the head's ObjectDirectory is the transfer plane's authority)
        shard_map = self._probe_shard_map(self.control_plane)
        if shard_map:
            from .rpc import ShardedControlPlane

            self.control_plane = ShardedControlPlane(
                self.control_plane, shard_map["addresses"], role="worker")
            logger.info("joined a federated control plane (%d shards)",
                        len(shard_map["addresses"]))
        node_resources = default_node_resources(num_cpus, num_tpus, resources)
        self.info = NodeInfo(
            node_id=NodeID.generate(),
            address=f"{node_host}",
            resources_total=node_resources,
            labels=labels or {},
        )
        self.node_id = self.info.node_id
        object_ledger.set_local_node(self.node_id.hex())
        self.directory = RemoteDirectoryClient(self.control_plane, self.node_id)
        self.agent = NodeAgent(self.info, self.control_plane, self.directory)
        self.dispatch_server = WorkerNodeServer(self.agent, host=node_host)
        self.dispatch_server.runtime = self
        self.transfer_server = ObjectTransferServer(self.agent.store, host=node_host)
        self._stopped = threading.Event()
        # advertise BEFORE registering: the head resolves both addresses
        # inside the node-ALIVE pubsub handler (ordering guaranteed: one
        # socket, serialized requests)
        self.control_plane.kv_put(
            NODE_SERVICE_PREFIX + self.node_id.hex(), self.dispatch_server.address)
        self.control_plane.kv_put(
            KV_PREFIX + self.node_id.hex(), self.transfer_server.address)
        self.control_plane.kv_put(
            HOST_PREFIX + self.node_id.hex(), _host_token())
        # compiled-graph channels homed here (consumer-side queues) are
        # reachable through this process's channel service
        from .channels import KV_CHANNEL_PREFIX, ensure_service

        self.control_plane.kv_put(
            KV_CHANNEL_PREFIX + self.node_id.hex(), ensure_service(node_host))
        self.control_plane.register_node(self.info)
        # head restart: the reconnected client has resubscribed pubsub, but
        # the head's node table and object directory are not persisted —
        # push our registration and held-object locations back
        self.control_plane.add_reconnect_listener(self._rejoin)
        self._api_client = None
        self._api_client_lock = threading.Lock()
        # pool-worker children inherit this and build their own back-channel
        # client lazily on first API touch (api._auto_init)
        import os as _os

        _os.environ["RAY_TPU_HEAD_ADDRESS"] = address
        # telemetry flush cursors (advanced only after a successful report,
        # so a failed flush retries the same tail next beat)
        self._telemetry_span_cursor = 0
        self._telemetry_event_cursor = 0
        self._last_telemetry = 0.0
        # per-field wire-form hashes of the last CONFIRMED report
        # (delta-encoding: unchanged fields ship as None = keep-previous)
        self._telemetry_sent_hash: Dict[str, int] = {}
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True, name="worker-heartbeat"
        )
        self._hb_thread.start()
        logger.info("joined cluster at %s as node %s (%s)",
                    address, self.node_id.hex()[:8], node_resources)

    def api_client(self):
        """The ownership back-channel for code running in THIS process
        (in-process tasks/actors on a joined host): a Runtime-duck client
        proxying submissions to the head (see `worker_api`). Lazy — the
        dedicated connection only exists if the API is actually used."""
        with self._api_client_lock:
            if self._api_client is None:
                if self._stopped.is_set():
                    raise RuntimeError("worker runtime is shut down")
                from .worker_api import WorkerAPIClient

                self._api_client = WorkerAPIClient(
                    self.head_address,
                    local_store=self.agent.store,
                    local_node_id=self.node_id,
                )
            return self._api_client

    def prefetch_object(self, oid_hex: str) -> bool:
        """Pull one object into this host's store (broadcast fan-out
        target). Joins the collective relay tree when one is forming:
        this host serves its committed prefix to later pullers while its
        own pull is still streaming. Raises ObjectPullError if no holder
        can serve the object."""
        oid = ObjectID.from_hex(oid_hex)
        if self.agent.store.contains(oid):
            return True
        nid = self.node_id.hex()
        pull_from_any(
            self.control_plane, oid,
            client=self.directory._transfer,
            cache_store=self.agent.store,
            on_cached=lambda o: self.control_plane.dir_add_location(
                o.hex(), nid),
            relay_server=self.transfer_server,
            node_hex=nid,
        )
        return True

    @staticmethod
    def _probe_shard_map(cp) -> Optional[Dict[str, Any]]:
        """Read the head's shard-map advertisement (shard.SHARD_MAP_KEY);
        None on a single-head cluster or any decode trouble (the plain
        head connection always works, so adoption is best-effort)."""
        import json as _json

        try:
            raw = cp.kv_get("control_plane/shard_map")
            if not raw:
                return None
            if isinstance(raw, bytes):
                raw = raw.decode("utf-8")
            parsed = _json.loads(raw)
            if parsed.get("addresses"):
                return parsed
        except Exception:  # noqa: BLE001 — fall back to the head connection
            logger.debug("shard-map probe failed", exc_info=True)
        return None

    def _rejoin(self) -> None:
        """Re-introduce this host to a restarted head: the snapshot restores
        KV/jobs/named actors but deliberately NOT the node table or object
        directory (restored liveness would be a lie — see persistence.py),
        so the survivors rebuild both. Re-put the advertised addresses,
        re-advertise every locally-held object, then register_node LAST —
        the head's node-ALIVE handler resolves the KV addresses when it
        dials back. Also the recovery path for a false reap (heartbeat
        returned False): same sequence, same ordering constraint."""
        if self._stopped.is_set():
            return
        from .channels import KV_CHANNEL_PREFIX, ensure_service

        # a head that forgot us has no previous telemetry to keep: drop
        # the delta-encoding hashes so the next flush ships every field
        self._telemetry_sent_hash.clear()
        try:
            nid = self.node_id.hex()
            self.control_plane.kv_put(
                NODE_SERVICE_PREFIX + nid, self.dispatch_server.address)
            self.control_plane.kv_put(
                KV_PREFIX + nid, self.transfer_server.address)
            self.control_plane.kv_put(HOST_PREFIX + nid, _host_token())
            self.control_plane.kv_put(
                KV_CHANNEL_PREFIX + nid, ensure_service(self._node_host))
            held = self.agent.store.list_objects()
            for oid, _nbytes in held:
                self.control_plane.dir_add_location(oid.hex(), nid)
            self.control_plane.register_node(self.info)
            logger.info("re-registered with head at %s (%d objects "
                        "re-advertised)", self.head_address, len(held))
        except (ConnectionError, RuntimeError) as e:
            # head flapped again mid-rejoin: the next reconnect (or the
            # heartbeat loop seeing False) retries the whole sequence
            logger.warning("rejoin attempt failed (%s); will retry", e)

    def _heartbeat_loop(self) -> None:
        period = config.health_check_period_ms / 1000.0
        while not self._stopped.is_set():
            # a stop request beats everything, including an unreachable
            # head: the owner asked us to exit
            if self.dispatch_server.owner_requested_stop.is_set():
                logger.info("head requested stop; shutting worker down")
                self.shutdown()
                return
            try:
                alive = self.control_plane.heartbeat(
                    self.node_id, self.agent.resources.available(),
                    _deadline_s=max(2.0, period))
            except ControlPlaneUnavailable:
                # head down or restarting: ride it out — the client is
                # already reconnecting with backoff, and _rejoin fires on
                # the reconnect listener
                logger.warning("head unreachable; worker riding out the "
                               "outage (reconnect in progress)")
                self._stopped.wait(period)
                continue
            except (WireError, OSError, RuntimeError):
                if self._stopped.is_set():
                    return
                self._stopped.wait(period)
                continue
            if alive is False:
                # the head reaped us (partition outlived the health timeout)
                # or restarted without our registration: re-register instead
                # of zombie-ing on or dying — tasks we hold results for may
                # still be wanted
                logger.warning("head does not know this node; re-registering")
                self._rejoin()
            elif alive:
                self._maybe_report_telemetry()
            self._stopped.wait(period)

    def _maybe_report_telemetry(self) -> None:
        """Flush this process's metrics snapshot, SLO digests, trace
        spans, timeline events, and any fresh crash postmortems to the
        head, at most every config.telemetry_report_period_s (piggybacked
        on the heartbeat so a partition pauses telemetry along with
        liveness). Lossy-tolerant: cursors only advance on a confirmed
        report, and failures wait for the next beat rather than retrying
        inline. The whole payload is capped at config.telemetry_max_bytes
        (oldest spans/events dropped first, counted in
        telemetry_dropped_total{kind}) so a span burst cannot bloat a
        heartbeat into a megabyte RPC."""
        now = time.monotonic()
        if now - self._last_telemetry < float(config.telemetry_report_period_s):
            return
        from ..util import flight_recorder, profiler, slo, timeline, tracing
        from .metrics import registry as metrics_registry

        try:
            # refresh host CPU / RSS / device-memory gauges so every
            # telemetry flush federates them (no new protocol fields:
            # they ride the metrics snapshot like any other gauge)
            profiler.update_resource_gauges()
        except Exception:  # noqa: BLE001 — accounting must not block the beat
            pass
        span_cur, spans = tracing.drain_since(self._telemetry_span_cursor)
        event_cur, events = timeline.drain_since(self._telemetry_event_cursor)
        objects: List[Dict[str, Any]] = []
        channels: Dict[str, float] = {}
        try:
            # publish window-bandwidth gauges + the bounded ledger snapshot
            # so the head's object/flow matrices include this node
            object_ledger.refresh_flow_gauges()
            if object_ledger.enabled():
                objects = object_ledger.local_snapshots(
                    {self.node_id: self.agent})
            from .channels import channel_stats

            channels = channel_stats()
        except Exception:  # noqa: BLE001 — ledger must not block the beat
            pass
        metrics = metrics_registry.snapshot()
        spans, events = _cap_telemetry(
            metrics, spans, events, int(config.telemetry_max_bytes))
        digests = slo.snapshot()
        postmortems = flight_recorder.drain_postmortems()
        # delta-encoding: report_telemetry is replace-not-append with
        # None = keep-previous per field, so an unchanged snapshot need
        # not re-ship — hash the wire form and send None on a match
        # (reported_at still refreshes head-side, so stale-eviction is
        # unaffected). Steady-state heartbeats shrink to near-empty
        # payloads BEFORE pod aggregation even starts.
        payload: Dict[str, Any] = {"metrics": metrics, "digests": digests,
                                   "objects": objects, "channels": channels}
        sent_hashes: Dict[str, int] = {}
        for field, value in payload.items():
            # hash the metrics field with telemetry_bytes_total itself
            # filtered out: shipping the snapshot increments that counter,
            # which would change the NEXT snapshot and keep the field
            # re-shipping forever
            hashed = value
            if field == "metrics":
                hashed = [m for m in value
                          if m.get("name") != "telemetry_bytes_total"]
            blob = _dumps(hashed)
            digest = hash(blob)
            if self._telemetry_sent_hash.get(field) == digest:
                payload[field] = None
            else:
                sent_hashes[field] = digest
                _m_tele_bytes.inc(len(blob), {"field": field})
        try:
            self.control_plane.report_telemetry(
                self.node_id.hex(),
                role="worker",
                metrics=payload["metrics"],
                spans=spans,
                events=events,
                event_cursor=event_cur,
                digests=payload["digests"],
                postmortems=postmortems,
                objects=payload["objects"],
                channels=payload["channels"],
                _deadline_s=5.0,
            )
        except (ControlPlaneUnavailable, WireError, OSError, RuntimeError) as e:
            logger.debug("telemetry flush failed (%s); retrying next beat", e)
            flight_recorder.requeue_postmortems(postmortems)
            return
        # hashes advance only on a confirmed report (like the cursors): a
        # failed flush re-ships the field next beat
        self._telemetry_sent_hash.update(sent_hashes)
        self._telemetry_span_cursor = span_cur
        self._telemetry_event_cursor = event_cur
        self._last_telemetry = now

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the worker shuts down (head death or stop request)."""
        return self._stopped.wait(timeout)

    @property
    def is_running(self) -> bool:
        return not self._stopped.is_set()

    def shutdown(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        import os as _os

        if _os.environ.get("RAY_TPU_HEAD_ADDRESS") == self.head_address:
            _os.environ.pop("RAY_TPU_HEAD_ADDRESS", None)
        with self._api_client_lock:
            if self._api_client is not None:
                self._api_client.close()
                self._api_client = None
        try:
            # short deadlines: when the head is gone this is best-effort
            # cleanup, not worth stalling shutdown for the full default
            self.control_plane._call(
                "kv_del", NODE_SERVICE_PREFIX + self.node_id.hex(),
                _deadline_s=2.0)
            self.control_plane._call(
                "kv_del", KV_PREFIX + self.node_id.hex(), _deadline_s=2.0)
            self.control_plane._call(
                "mark_node_dead", self.node_id, "worker shutdown",
                _deadline_s=2.0)
        except (WireError, OSError, RuntimeError):
            pass
        self.dispatch_server.stop()
        self.transfer_server.stop()
        self.agent.stop()
        self.control_plane.close()


def join_cluster(address: str, **kwargs) -> WorkerRuntime:
    """Join an existing cluster as a worker host (push-dispatch target)."""
    return WorkerRuntime(address, **kwargs)
