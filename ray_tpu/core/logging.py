"""Structured process logging.

Equivalent of the reference's RAY_LOG/spdlog setup plus the per-session log
directory convention (upstream ray `src/ray/util/logging.h :: RayLog`,
`/tmp/ray/session_latest/logs/`): each process logs to stderr and to a
per-process file under the session log dir, with component and worker context
prefixed so a tail-aggregator can attribute lines.

Log↔trace correlation: a record emitted while a trace span is active on
the emitting thread carries ` trace_id=<id>` in its prefix, so log_monitor
output and crash postmortems join to `/api/v0/traces/<id>` by grep. Every
formatted line also feeds the per-process flight recorder ring
(util/flight_recorder) — the "recent log lines" half of a postmortem.
"""

from __future__ import annotations

import logging
import os
import sys
import time
from typing import Optional

_SESSION_DIR: Optional[str] = None
_FMT = "[%(asctime)s %(levelname).1s %(process)d %(name)s%(trace_ctx)s] %(message)s"


def session_dir() -> str:
    """Session directory (/tmp/ray_tpu/session_<ts> with a `latest` symlink)."""
    global _SESSION_DIR
    if _SESSION_DIR is None:
        base = os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")
        stamp = time.strftime("session_%Y%m%d_%H%M%S") + f"_{os.getpid()}"
        path = os.path.join(base, stamp)
        os.makedirs(os.path.join(path, "logs"), exist_ok=True)
        latest = os.path.join(base, "session_latest")
        try:
            if os.path.islink(latest) or os.path.exists(latest):
                os.remove(latest)
            os.symlink(path, latest)
        except OSError:
            pass
        _SESSION_DIR = path
    return _SESSION_DIR


def log_dir() -> str:
    return os.path.join(session_dir(), "logs")


class _TraceContextFilter(logging.Filter):
    """Stamps `record.trace_ctx` from the thread's active span (lazy
    tracing import: logging is imported everywhere, tracing must stay
    optional at this layer)."""

    _current_span = None  # resolved once, cached on the class

    def filter(self, record: logging.LogRecord) -> bool:
        fn = _TraceContextFilter._current_span
        if fn is None:
            try:
                from ..util.tracing import current_span as fn
            except Exception:
                fn = lambda: None  # noqa: E731
            _TraceContextFilter._current_span = fn
        span = fn()
        record.trace_ctx = f" trace_id={span.trace_id}" if span is not None else ""
        return True


class _FlightHandler(logging.Handler):
    """Mirrors every formatted line into the flight-recorder ring."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            from ..util import flight_recorder
            flight_recorder.on_log(self.format(record))
        except Exception:
            pass  # crash forensics must never break logging


def get_logger(component: str, to_file: bool = True) -> logging.Logger:
    logger = logging.getLogger(f"ray_tpu.{component}")
    if getattr(logger, "_ray_tpu_configured", False):
        return logger
    logger.setLevel(os.environ.get("RAY_TPU_LOG_LEVEL", "INFO").upper())
    formatter = logging.Formatter(_FMT)
    logger.addFilter(_TraceContextFilter())
    stream = logging.StreamHandler(sys.stderr)
    stream.setFormatter(formatter)
    logger.addHandler(stream)
    if to_file:
        try:
            path = os.path.join(log_dir(), f"{component}_{os.getpid()}.log")
            fh = logging.FileHandler(path)
            fh.setFormatter(formatter)
            logger.addHandler(fh)
        except OSError:
            pass
    flight = _FlightHandler()
    flight.setFormatter(formatter)
    logger.addHandler(flight)
    logger.propagate = False
    logger._ray_tpu_configured = True  # type: ignore[attr-defined]
    return logger
