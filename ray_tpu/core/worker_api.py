"""Worker-side runtime API: nested submission from joined hosts.

Reference analogue: in the reference EVERY worker embeds a full CoreWorker
with its own ownership tables (`src/ray/core_worker/core_worker.h ::
CoreWorker`, `reference_count.cc :: ReferenceCounter`), so tasks spawn
tasks, replicas call handles, trials place trainers — the tree-of-tasks
pattern. Here ownership stays at the HEAD by design (single controller,
SURVEY §7.1): this module gives worker-host code a *client* to the head's
ownership tables, not a scheduler. `put/get/remote/wait/actor calls` from
code running on a joined host proxy over a dedicated control-plane
connection:

  worker host / pool worker           head
  -------------------------           ----
  WorkerAPIClient --proxy_submit_*--> HeadService -> Runtime.submit_task
       | get():  batched proxy_ref_state poll + pulls over the transfer
       |         plane (data rides the RPC socket only on the holder-died
       |         fallback, which uses its own short-lived connection)
       | errors: proxy_ref_state carries pickled task errors (failed
       |         tasks seal no object to wait on)
       | GC:     local refcount; zero -> proxy_free -> head unpins
       | liveness: the free thread doubles as a keepalive; the head reaps
       |         pins of clients that stopped beating (crash/SIGKILL)

The head PINS every proxy-submitted return ref (`HeadService._proxy_refs`)
so its own GC can't free results the remote caller still wants; the
client's local ReferenceCounter mirrors ObjectRef lifetime and releases
pins asynchronously. Refs that ESCAPE this process (pickled into a task
return or into another submission) are never auto-freed — the eventual
deserializer takes its own head-side reference at unpickle time, which can
be long after this process's last local ref dropped; pinning-until-head-
shutdown is the price of not running a borrower protocol (reference:
`reference_count.cc` borrower bookkeeping, deliberately collapsed).

A worker-host `put()` seals into the LOCAL store and registers the
location with the head directory (zero-copy on the data path); a
pool-worker `put()` (no serving store) ships the value to the head once.
"""

from __future__ import annotations

import queue
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .config import config
from .ids import ActorID, NodeID, ObjectID, TaskID
from .logging import get_logger
from .object_store import ObjectLostError, SealedBytes, seal_value
from .rpc import RemoteControlPlane
from .wire import WireError

logger = get_logger("worker_api")

KEEPALIVE_PERIOD_S = 10.0


class _ClientRefCounter:
    """Local mirror of ObjectRef liveness; zero count releases the head pin
    (reference: distributed refcounting in `reference_count.cc`, collapsed
    to borrower-notifies-owner). Escaped refs (see module docstring) are
    exempt from auto-free."""

    def __init__(self, client: "WorkerAPIClient"):
        self._client = client
        self._lock = threading.Lock()
        self._counts: Dict[ObjectID, int] = {}
        self._escaped: set = set()
        self.gc_enabled = True

    def add_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            self._counts[object_id] = self._counts.get(object_id, 0) + 1

    def note_escaped(self, object_id: ObjectID) -> None:
        with self._lock:
            self._escaped.add(object_id)

    def remove_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            n = self._counts.get(object_id, 0) - 1
            if n > 0:
                self._counts[object_id] = n
                return
            self._counts.pop(object_id, None)
            should_free = self.gc_enabled and object_id not in self._escaped
        if should_free:
            self._client._enqueue_free(object_id)

    def count(self, object_id: ObjectID) -> int:
        with self._lock:
            return self._counts.get(object_id, 0)


class _ActorInfoShim:
    __slots__ = ("actor_id", "name", "class_name")

    def __init__(self, actor_id: ActorID, name: str, class_name: str):
        self.actor_id = actor_id
        self.name = name
        self.class_name = class_name


class WorkerAPIClient:
    """Runtime-duck client for code running OFF the head (joined-host
    process or pool worker). Implements the subset of ``Runtime`` that
    ``ray_tpu.api`` touches; everything else raises clearly."""

    is_proxy_client = True

    def __init__(
        self,
        head_address: str,
        local_store=None,
        local_node_id: Optional[NodeID] = None,
    ):
        # DEDICATED connection: get() may park seconds on it; sharing the
        # WorkerRuntime's heartbeat connection would wedge health checks
        self._cp = RemoteControlPlane(head_address)
        self.control_plane = self._cp
        self.head_address = head_address
        self.client_id = uuid.uuid4().hex
        self._local_store = local_store
        self._local_node_id = local_node_id
        self._client_task_id = TaskID.of()
        self._put_index = 0
        self._put_lock = threading.Lock()
        self._stream_lock = threading.Lock()
        self._stream_subscribed = False
        self._streams: Dict[str, "queue.Queue"] = {}
        self._stream_backlog: Dict[str, list] = {}
        self.is_shutdown = False
        try:
            self.job_id = self._cp.proxy_job_id()
        except BaseException:
            # half-built client must not leak its socket (init can fail
            # with RuntimeError from the server, not just OSError)
            self._cp.close()
            raise
        self.reference_counter = _ClientRefCounter(self)
        from .cross_host import RemoteDirectoryClient  # cycle: worker_api <- cross_host

        self._directory = RemoteDirectoryClient(
            self._cp, local_node_id or NodeID.generate())
        # frees ride a background thread: ObjectRef.__del__ must never
        # block on (or raise through) a socket. The same thread beats the
        # keepalive so the head can reap this client's pins if the process
        # dies without close().
        self._free_q: "queue.Queue[Optional[ObjectID]]" = queue.Queue()
        threading.Thread(
            target=self._free_loop, daemon=True, name="worker-api-free"
        ).start()

    # ------------------------------------------------------------ internals
    def _free_loop(self) -> None:
        last_beat = 0.0
        while True:
            try:
                oid = self._free_q.get(timeout=KEEPALIVE_PERIOD_S / 2)
            except queue.Empty:
                oid = False  # idle tick: keepalive only
            if oid is None:
                return
            batch = []
            if oid is not False:
                batch.append(oid)
                try:
                    while len(batch) < 256:
                        nxt = self._free_q.get_nowait()
                        if nxt is None:
                            self._free_q.put(None)  # re-arm shutdown
                            break
                        batch.append(nxt)
                except queue.Empty:
                    pass
            try:
                if batch:
                    # frees carry the client id: they refresh head-side
                    # liveness, so a busy-freeing client never starves
                    # its own keepalive
                    self._cp.proxy_free([o.hex() for o in batch],
                                        self.client_id)
                    last_beat = time.monotonic()
                elif time.monotonic() - last_beat >= KEEPALIVE_PERIOD_S:
                    self._cp.proxy_keepalive(self.client_id)
                    last_beat = time.monotonic()
            except (WireError, OSError, RuntimeError):
                if self.is_shutdown:
                    return
                # head restarting: drop this batch (a restarted head has no
                # pins for us anyway) and keep the thread alive so frees
                # and keepalives resume once the client reconnects
                continue

    def _enqueue_free(self, oid: ObjectID) -> None:
        if not self.is_shutdown:
            self._free_q.put(oid)

    def note_escaped(self, object_id: ObjectID) -> None:
        """Called from ObjectRef.__reduce__: this ref's id left the process
        (task return / nested submission); its head pin must outlive our
        local refcount."""
        self.reference_counter.note_escaped(object_id)

    def _make_refs(self, oid_hexes: List[str]) -> List[Any]:
        from .core_worker import ObjectRef

        return [ObjectRef(ObjectID.from_hex(h), self) for h in oid_hexes]

    # ----------------------------------------------------------- submission
    def submit_task(self, spec) -> List[Any]:
        from .cross_host import _dumps

        self._package_renv(spec)
        return self._make_refs(self._cp.proxy_submit_task(
            _dumps(spec), self.client_id))

    def submit_streaming_task(self, spec):
        """Streaming over the back-channel: the head runs the generator
        task and forwards item refs as `proxy_stream` pubsub events; this
        side yields ObjectRefs as the events land (same consume-while-
        producing contract as the head's ObjectRefGenerator)."""
        from .cross_host import _dumps

        self._package_renv(spec)
        with self._stream_lock:
            if not self._stream_subscribed:
                self._cp.subscribe("proxy_stream", self._on_stream_event)
                self._stream_subscribed = True
        stream_id = self._cp.proxy_submit_streaming(
            _dumps(spec), self.client_id)
        q: "queue.Queue" = queue.Queue()
        with self._stream_lock:
            self._streams[stream_id] = q
            # events that raced ahead of the registration replay in order
            for ev in self._stream_backlog.pop(stream_id, []):
                q.put(ev)
        return _ProxyRefStream(self, stream_id, q)

    def _on_stream_event(self, event) -> None:
        stream_id, index, oid_hex, err_blob = event
        with self._stream_lock:
            q = self._streams.get(stream_id)
            if q is None:
                # subscribe() races proxy_submit_streaming's reply: buffer
                # until the stream registers (bounded: streams register
                # within one RPC round trip)
                self._stream_backlog.setdefault(stream_id, []).append(event)
                return
        q.put(event)

    def create_actor(self, cls, args, kwargs, options) -> _ActorInfoShim:
        from .cross_host import _dumps

        spec_like = (cls, args, kwargs, options)
        actor_hex, name, class_name = self._cp.proxy_create_actor(
            _dumps(spec_like))
        return _ActorInfoShim(ActorID.from_hex(actor_hex), name, class_name)

    def submit_actor_task(
        self, actor_id: ActorID, method_name: str, args, kwargs, options,
        trace_ctx=None,
    ) -> List[Any]:
        from ..util import tracing
        from .cross_host import _dumps

        if trace_ctx is None:
            trace_ctx = tracing.current_context()
        return self._make_refs(self._cp.proxy_submit_actor_task(
            actor_id.hex(), method_name, _dumps((args, kwargs)),
            _dumps(options), self.client_id, trace_ctx))

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self._cp.proxy_kill_actor(actor_id.hex(), no_restart)

    def _package_renv(self, spec) -> None:
        """working_dir must be read from THIS host's filesystem — the head
        never sees the path (mirrors Runtime._prepare_runtime_env)."""
        renv = spec.options.runtime_env
        if not renv or not renv.get("working_dir"):
            return
        import dataclasses

        from . import runtime_env

        packaged = runtime_env.package_working_dir(renv, self._cp)
        spec.options = dataclasses.replace(spec.options, runtime_env=packaged)

    # -------------------------------------------------------------- get/put
    def put(self, value: Any) -> Any:
        from .core_worker import ObjectRef
        from .cross_host import _dumps

        with self._put_lock:
            self._put_index += 1
            oid = ObjectID.for_put(self._client_task_id, self._put_index)
        if self._local_store is not None and self._local_node_id is not None:
            # worker-host process: seal locally, advertise the location —
            # consumers pull over the transfer plane (no head copy)
            self._local_store.put(oid, seal_value(value))
            self._directory.add_location(oid, self._local_node_id)
            self._cp.proxy_pin(oid.hex(), self.client_id)
        else:
            # pool worker: no serving store here — ship to the head once
            self._cp.proxy_put(oid.hex(), _dumps(value), self.client_id)
        return ObjectRef(oid, self)

    def get(self, refs: Sequence[Any], timeout: Optional[float] = None) -> List[Any]:
        """Batched resolve: ONE proxy_ref_state poll per iteration covers
        every unresolved ref (the head API takes a list for exactly this);
        pulls happen as refs turn ready. Pure poll with backoff — no
        per-ref pubsub machinery on this side of the wire."""
        from .core_worker import GetTimeoutError

        deadline = None if timeout is None else time.monotonic() + timeout
        out: List[Any] = [None] * len(refs)
        pending: Dict[str, List[int]] = {}
        for i, ref in enumerate(refs):
            pending.setdefault(ref.object_id.hex(), []).append(i)
        stale_pulls: Dict[str, int] = {}
        poll = 0.03
        while pending:
            states = self._cp.proxy_ref_state(list(pending))
            progressed = False
            for h in list(pending):
                st = states[h]
                if st["state"] == "error":
                    raise _load_error(st["error_blob"])
                if st["state"] != "ready":
                    continue
                oid = ObjectID.from_hex(h)
                value, ok = self._pull_ready(oid, h, stale_pulls, deadline)
                if not ok:
                    continue
                for i in pending.pop(h):
                    out[i] = value
                progressed = True
            if not pending:
                break
            if deadline is not None and time.monotonic() >= deadline:
                missing = [h[:16] for h in pending]
                raise GetTimeoutError(f"get() timed out on {missing}")
            if not progressed:
                time.sleep(poll)
                poll = min(poll * 1.7, 0.35)
        return out

    def _pull_ready(self, oid: ObjectID, h: str, stale_pulls: Dict[str, int],
                    deadline: Optional[float]) -> Tuple[Any, bool]:
        from .object_transfer import _cache_hits, _cache_misses

        if self._local_store is not None and self._local_store.contains(oid):
            # pull-through cache hit: a prior get on this host already
            # sealed the object locally (objects are immutable, so the
            # replica is as good as the origin)
            _cache_hits.inc()
            return self._local_store.get(oid, timeout=10.0), True
        # prefer_local: a holder sharing this boot's host token serves
        # over the shm fd handoff (zero socket bytes) instead of a
        # loopback copy — ranked ahead of genuinely remote holders
        holder = self._directory.locate(oid, prefer_local=True)
        if holder is None:
            # ready but no location: sealed value lost (holder died) or
            # the dir write is in flight — give the directory two beats,
            # then let the head resolve (lineage reconstruction lives there)
            stale_pulls[h] = stale_pulls.get(h, 0) + 1
            if stale_pulls[h] >= 3:
                return self._get_via_head(oid, deadline), True
            return None, False
        try:
            if (self._local_store is not None
                    and self._local_node_id is not None
                    and config.object_pull_through_cache):
                # seal the pulled payload locally and advertise the
                # location: repeat gets stay on-host, and OTHER hosts can
                # pull from us instead of the origin. Best-effort: any
                # cache failure degrades to returning the value.
                _cache_misses.inc()
                raw = holder.store.get_raw(oid, timeout=10.0)
                try:
                    self._local_store.put(oid, raw)
                    self._directory.add_location(oid, self._local_node_id)
                except Exception:  # noqa: BLE001 — caching never fails a get
                    pass
                return (raw.load() if isinstance(raw, SealedBytes)
                        else raw), True
            return holder.store.get(oid, timeout=10.0), True
        except (TimeoutError, ObjectLostError):
            stale_pulls[h] = stale_pulls.get(h, 0) + 1
            if stale_pulls[h] >= 3:
                return self._get_via_head(oid, deadline), True
            return None, False

    def _get_via_head(self, oid: ObjectID, deadline: Optional[float]) -> Any:
        """Fallback: the head resolves (incl. reconstruction) and ships the
        value back. Runs on its OWN short-lived connection — the shared
        one serves every concurrent task on this host, and the head
        handler blocks for the duration (rpc.py is one thread per
        connection)."""
        import pickle

        rem = 30.0 if deadline is None else max(1.0, deadline - time.monotonic())
        wait_s = min(rem, 60.0)
        cp = RemoteControlPlane(self.head_address)
        try:
            # the server parks up to wait_s before replying: the call
            # deadline must outlast it or every slow resolve would abort
            # as ControlPlaneUnavailable at the config default
            blob = cp._call("proxy_get_value", oid.hex(), wait_s,
                            _deadline_s=wait_s + 10.0)
        finally:
            cp.close()
        return pickle.loads(blob)

    def wait(
        self,
        refs: Sequence[Any],
        num_returns: int = 1,
        timeout: Optional[float] = None,
    ) -> Tuple[List[Any], List[Any]]:
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds number of refs")
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[Any] = []
        pending = list(refs)
        poll = 0.02
        while len(ready) < num_returns and pending:
            states = self._cp.proxy_ref_state(
                [r.object_id.hex() for r in pending])
            for r in list(pending):
                if states[r.object_id.hex()]["state"] in ("ready", "error"):
                    ready.append(r)
                    pending.remove(r)
                    if len(ready) >= num_returns:
                        break
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(poll)
            poll = min(poll * 1.7, 0.25)
        return ready, pending

    def free_object(self, object_id: ObjectID) -> None:
        self._enqueue_free(object_id)

    @property
    def is_alive(self) -> bool:
        """False only once close()d: a dropped head connection now heals
        itself (rpc.RemoteControlPlane reconnects), so cached clients stay
        valid across a head restart."""
        return not self.is_shutdown and not self._cp._closed.is_set()

    # --------------------------------------------------------------- misc
    def task_table(self):
        raise RuntimeError("the task table lives on the head; use the state "
                           "API from the driver")

    def close(self) -> None:
        self.is_shutdown = True
        self.reference_counter.gc_enabled = False
        self._free_q.put(None)
        self._cp.close()


class _ProxyRefStream:
    """Client-side ObjectRefGenerator duck: yields ObjectRefs as the
    head's proxy_stream events arrive; raises the producer's error after
    the yielded prefix (same contract as core_worker.ObjectRefGenerator)."""

    def __init__(self, client: WorkerAPIClient, stream_id: str, q):
        self._client = client
        self._id = stream_id
        self._q = q
        self._done = False
        self._error: Optional[BaseException] = None

    def __iter__(self):
        return self

    def __next__(self):
        from .core_worker import ObjectRef
        from .ids import ObjectID

        if self._done:
            if self._error is not None:
                raise self._error
            raise StopIteration
        _sid, index, oid_hex, err_blob = self._q.get()
        if index < 0:  # terminal event
            self._done = True
            with self._client._stream_lock:
                self._client._streams.pop(self._id, None)
            if err_blob is not None:
                self._error = _load_error(err_blob)
                raise self._error
            raise StopIteration
        return ObjectRef(ObjectID.from_hex(oid_hex), self._client)

    def completed(self) -> bool:
        return self._done


def _load_error(blob: Optional[bytes]) -> BaseException:
    import pickle

    if blob is None:
        return RuntimeError("remote task failed (no error detail)")
    try:
        return pickle.loads(blob)
    except Exception as e:  # noqa: BLE001 — broken blob must not mask failure
        return RuntimeError(f"remote task failed (undeserializable: {e!r})")
