"""Node memory monitor: kill the newest retriable task under pressure.

Reference analogue: `src/ray/raylet/worker_killing_policy.cc` +
`memory_monitor.cc` — when host memory crosses a threshold, the raylet
kills the most recently started retriable task's worker so the node
survives and the task resubmits through the normal worker-crash retry
path. Same policy here: the monitor samples host (or cgroup) memory and
calls the pool's ``kill_newest_worker``; the killed task surfaces as
WorkerCrashedError and retries under ``max_retries``.

TPU note: this guards the HOST side only (pool workers doing decode,
data preprocessing, rollouts). Device HBM is governed by XLA's allocator
and is compile-time-shaped; there is nothing to kill at runtime there.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .config import config, declare
from .logging import get_logger
from .metrics import Counter, Gauge

logger = get_logger("memory_monitor")

declare(
    "memory_monitor_threshold", 0.95,
    "Host memory-used fraction above which the newest pool task is "
    "killed (retries via the worker-crash path). 0 disables the monitor.",
)
declare("memory_monitor_interval_ms", 1000,
        "Milliseconds between memory-monitor samples.")

_m_killed = Counter(
    "memory_monitor_tasks_killed",
    "Pool tasks killed by the memory monitor under host memory pressure.",
)
_m_used_fraction = Gauge(
    "host_memory_used_fraction",
    "Host (or cgroup) memory-used fraction, sampled by the memory "
    "monitor — the health plane's memory_pressure rule reads this.",
)


def system_memory_fraction() -> float:
    """Fraction of memory in use, preferring the cgroup (container) limit
    over the host figure — inside a container /proc/meminfo shows the
    machine, but the OOM killer enforces the cgroup."""
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            limit = f.read().strip()
        if limit != "max":
            with open("/sys/fs/cgroup/memory.current") as f:
                current = int(f.read().strip())
            # memory.current includes page cache the kernel reclaims for
            # free; counting it would OOM-kill healthy IO-heavy workloads
            # (streaming parquet fills the cgroup with cache). Subtract
            # inactive_file, as the reference memory_monitor.cc does.
            inactive_file = 0
            try:
                with open("/sys/fs/cgroup/memory.stat") as f:
                    for line in f:
                        if line.startswith("inactive_file "):
                            inactive_file = int(line.split()[1])
                            break
            except OSError:
                pass
            return max(0, current - inactive_file) / max(1, int(limit))
    except OSError:
        pass
    try:
        total = available = None
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    available = int(line.split()[1])
                if total is not None and available is not None:
                    break
        if total:
            return 1.0 - (available or 0) / total
    except OSError:
        pass
    return 0.0  # no probe available: never trigger


class MemoryMonitor:
    """Samples memory every interval; above threshold calls ``kill_fn``
    (expected: ProcessPool.kill_newest_worker). One kill per sample at
    most — the next sample observes the reclaim before killing again."""

    def __init__(self, kill_fn: Callable[[], Optional[int]],
                 threshold: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 probe: Callable[[], float] = system_memory_fraction):
        self.threshold = (config.memory_monitor_threshold
                          if threshold is None else threshold)
        self.interval_s = (config.memory_monitor_interval_ms / 1000.0
                           if interval_s is None else interval_s)
        self._kill_fn = kill_fn
        self._probe = probe
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def enabled(self) -> bool:
        return self.threshold > 0

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="memory-monitor")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                used = self._probe()
            except Exception:  # noqa: BLE001 — a broken probe must not spin
                logger.warning("memory probe failed; monitor disabled",
                               exc_info=True)
                return
            _m_used_fraction.set(used)
            if used < self.threshold:
                continue
            # announce the pressure kill as a health alert + flight-recorder
            # event BEFORE pulling the trigger: the postmortem and the alert
            # stream should both show why the worker died
            try:
                from ..util import flight_recorder
                flight_recorder.record("memory_pressure", used=used,
                                       threshold=self.threshold)
                from .health import get_health_plane
                plane = get_health_plane(create=False)
                if plane is not None:
                    plane.inject(
                        "memory_pressure", {"source": "memory_monitor"},
                        used, severity="critical")
            except Exception:  # noqa: BLE001 — alerting must not block the kill
                pass
            pid = self._kill_fn()
            if pid is not None:
                _m_killed.inc()
                logger.warning(
                    "host memory %.0f%% >= %.0f%%: killed newest pool "
                    "task's worker (pid %d); it retries via the "
                    "worker-crash path", used * 100, self.threshold * 100,
                    pid,
                )
            else:
                logger.warning(
                    "host memory %.0f%% >= %.0f%% but no pool task is "
                    "in flight to kill", used * 100, self.threshold * 100,
                )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
