"""Task specifications and scheduling strategies.

Equivalent of the reference's TaskSpec (upstream ray
`src/ray/common/task/task_spec.h :: TaskSpecification`,
`python/ray/util/scheduling_strategies.py`): the unit handed from a submitting
worker to the scheduler. TPU-native addition: resource shapes may carry an ICI
topology request (``TopologyRequest``) instead of a scalar chip count.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID


class TaskKind(enum.Enum):
    NORMAL = "normal"
    ACTOR_CREATION = "actor_creation"
    ACTOR_TASK = "actor_task"


@dataclass(frozen=True)
class TopologyRequest:
    """A TPU sub-slice request with an ICI topology shape, e.g. (2, 2, 4).

    The scheduler packs these onto the torus without fragmenting it — the
    TPU-native replacement for the reference's scalar ``num_gpus``.
    """

    shape: Tuple[int, ...]

    @property
    def num_chips(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n


@dataclass(frozen=True)
class SchedulingStrategy:
    """Base: DEFAULT hybrid policy."""


@dataclass(frozen=True)
class SpreadSchedulingStrategy(SchedulingStrategy):
    pass


@dataclass(frozen=True)
class NodeAffinitySchedulingStrategy(SchedulingStrategy):
    node_id: NodeID = None  # type: ignore[assignment]
    soft: bool = False


@dataclass(frozen=True)
class NodeLabelSchedulingStrategy(SchedulingStrategy):
    """Label-constrained placement (reference:
    `python/ray/util/scheduling_strategies.py :: NodeLabelSchedulingStrategy`
    + the raylet label policy). hard: every expression must match for a
    node to be eligible; soft: matching nodes preferred, any feasible
    node otherwise. Expressions: {key: ("in", [v1, v2])} or
    {key: ("not_in", [v1])} — exact string matching on NodeInfo.labels
    (e.g. accelerator generation, zone, provider id)."""

    hard: Any = None  # Dict[str, Tuple[str, List[str]]]
    soft: Any = None

    @staticmethod
    def _matches(exprs, labels: Dict[str, str]) -> bool:
        for key, (op, values) in (exprs or {}).items():
            has = labels.get(key)
            if op == "in" and has not in values:
                return False
            if op == "not_in" and has in values:
                return False
        return True


@dataclass(frozen=True)
class PlacementGroupSchedulingStrategy(SchedulingStrategy):
    placement_group_id: PlacementGroupID = None  # type: ignore[assignment]
    bundle_index: int = -1


@dataclass
class TaskOptions:
    """User-settable knobs from ``@remote(...)`` / ``.options(...)``."""

    num_cpus: float = 1.0
    num_tpus: float = 0.0
    topology: Optional[TopologyRequest] = None
    resources: Dict[str, float] = field(default_factory=dict)
    max_retries: Optional[int] = None
    retry_exceptions: bool = False
    max_restarts: int = 0  # actors only
    max_task_retries: int = 0  # actors only
    # int, or "streaming": the task is a GENERATOR whose yields seal into
    # the object plane one by one; the caller consumes an ObjectRefGenerator
    # while the task still runs (reference: num_returns="streaming",
    # core-worker streaming generator returns in task_manager.cc)
    num_returns: Any = 1
    name: str = ""
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    runtime_env: Optional[Dict[str, Any]] = None
    max_concurrency: int = 1  # actors only
    # actors only: None = policy decides (CPU actors isolate into a worker
    # process; device actors stay in-process); True forces in-process
    in_process: Optional[bool] = None

    def resource_demand(self) -> Dict[str, float]:
        demand = dict(self.resources)
        if self.num_cpus:
            demand["CPU"] = demand.get("CPU", 0.0) + self.num_cpus
        if self.num_tpus:
            demand["TPU"] = demand.get("TPU", 0.0) + self.num_tpus
        if self.topology is not None:
            demand["TPU"] = demand.get("TPU", 0.0) + self.topology.num_chips
        return demand


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    kind: TaskKind
    func: Optional[Callable[..., Any]]  # None for cross-process (pickled) specs
    args: Tuple[Any, ...]
    kwargs: Dict[str, Any]
    options: TaskOptions
    return_ids: List[ObjectID]
    actor_id: ActorID = field(default_factory=ActorID.nil)
    method_name: str = ""
    # ObjectIDs this task depends on (plasma-stored args), for the resolver.
    dependencies: List[ObjectID] = field(default_factory=list)
    attempt: int = 0
    # True when a placement-group bundle already holds the resources: the
    # node agent must not double-acquire from the node ledger.
    skip_node_resources: bool = False
    # Distributed-tracing context (util/tracing): stamped at submission
    # when the submitting thread has an active span; the executing node
    # parents its execute-span under it. None = tracing inactive.
    trace_ctx: Optional[Dict[str, str]] = None

    @property
    def name(self) -> str:
        if self.options.name:
            return self.options.name
        if self.func is not None:
            return getattr(self.func, "__qualname__", repr(self.func))
        return self.method_name or "task"
