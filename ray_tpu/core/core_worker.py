"""Owner-side runtime: ObjectRefs, task manager (retries + lineage), actors.

Equivalent of the reference's CoreWorker (upstream ray
`src/ray/core_worker/core_worker.cc :: CoreWorker`, `task_manager.cc ::
TaskManager` for retries/lineage, `reference_count.cc :: ReferenceCounter`,
`object_recovery_manager.cc`): the driver (and each worker) owns the objects
and tasks it creates; retries on worker/node death are resubmitted from the
stored spec; lost objects are reconstructed from lineage.

The ``Runtime`` singleton composes the whole single-controller deployment:
control plane + object directory + cluster scheduler + node agents. Virtual
multi-node clusters (tests) add several agents; a real deployment runs one
agent per TPU host with the same code.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import object_ledger
from .config import config
from .control_plane import ActorInfo, ActorState, ControlPlane, NodeInfo
from .ids import ActorID, JobID, NodeID, ObjectID, TaskID
from .logging import get_logger
from .node_agent import (
    NodeAgent,
    ObjectDirectory,
    TaskResult,
    WorkerCrashedError,
)
from .object_store import ObjectLostError, SealedBytes
from .object_transfer import _cache_hits, _cache_misses
from .scheduler import ClusterScheduler
from .metrics import Counter as _MetricCounter
from .task_spec import (
    PlacementGroupSchedulingStrategy,
    SchedulingStrategy,
    TaskKind,
    TaskOptions,
    TaskSpec,
)

logger = get_logger("core_worker")

_m_local_admits = _MetricCounter(
    "scheduler_local_admits_total",
    "Tasks admitted by the local node agent's bottom-up fast path "
    "(no ClusterScheduler view walk)")


def _timeline_now_us() -> float:
    from ..util import timeline

    return timeline._now_us()


class RayTaskError(Exception):
    """Wraps an application exception raised inside a task; re-raised on get."""

    def __init__(self, task_name: str, cause: BaseException):
        super().__init__(f"task {task_name} failed: {cause!r}")
        self.task_name = task_name
        self.cause = cause

    def __reduce__(self):
        # default Exception pickling replays only the formatted message —
        # the two-arg constructor then fails at LOAD time and the error
        # degrades to a generic RuntimeError on the far side of the wire
        return (RayTaskError, (self.task_name, self.cause))


class RayActorError(Exception):
    pass


class GetTimeoutError(TimeoutError):
    pass


class ObjectRef:
    """Handle to a (future) object. Comparable/hashable by ObjectID."""

    __slots__ = ("object_id", "_runtime", "__weakref__")

    def __init__(self, object_id: ObjectID, runtime: "Runtime | None" = None):
        self.object_id = object_id
        self._runtime = runtime
        if runtime is not None:
            runtime.reference_counter.add_ref(object_id)

    def hex(self) -> str:
        return self.object_id.hex()

    def __reduce__(self):
        # Crossing into a task: the receiving side resolves by id. Ownership
        # transfer bookkeeping is handled at submission time (deps list).
        runtime = self._runtime
        if runtime is not None:
            note = getattr(runtime, "note_escaped", None)
            if note is not None:
                # proxy-client refs (worker_api): an id leaving this
                # process may be deserialized long after our local count
                # hits zero — exempt it from auto-free
                note(self.object_id)
        return (_deserialize_ref, (self.object_id.binary(),))

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.object_id == self.object_id

    def __hash__(self):
        return hash(self.object_id)

    def __repr__(self):
        return f"ObjectRef({self.object_id.hex()[:16]})"

    def __del__(self):
        runtime = self._runtime
        if runtime is not None:
            try:
                runtime.reference_counter.remove_ref(self.object_id)
            except Exception:
                pass


def _deserialize_ref(binary: bytes) -> "ObjectRef":
    from . import core_worker as _self

    rt = _global_runtime
    return ObjectRef(ObjectID(binary), rt)


class ReferenceCounter:
    """Driver-side distributed refcount (simplified single-owner model).
    Escaped refs — ids that were pickled out of this process or into a
    task result/argument (see ``ObjectRef.__reduce__``) — are exempt from
    auto-free: a serialized copy may be deserialized long after every
    local Python handle has been collected."""

    def __init__(self, runtime: "Runtime"):
        self._runtime = runtime
        self._lock = threading.Lock()
        self._counts: Dict[ObjectID, int] = {}
        self._escaped: set = set()
        self.gc_enabled = True

    def add_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            self._counts[object_id] = self._counts.get(object_id, 0) + 1

    def note_escaped(self, object_id: ObjectID) -> None:
        with self._lock:
            self._escaped.add(object_id)

    def remove_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            n = self._counts.get(object_id, 0) - 1
            if n > 0:
                self._counts[object_id] = n
                return
            self._counts.pop(object_id, None)
            should_free = self.gc_enabled and object_id not in self._escaped
        if should_free and not self._runtime.is_shutdown:
            self._runtime.free_object(object_id)

    def count(self, object_id: ObjectID) -> int:
        with self._lock:
            return self._counts.get(object_id, 0)

    def is_escaped(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._escaped


@dataclass
class _PendingTask:
    spec: TaskSpec
    retries_left: int
    retry_exceptions: bool
    submitted_at: float = field(default_factory=time.monotonic)
    target_node: Optional[NodeID] = None
    pg_lease: Optional[Tuple[Any, int, Dict[str, float]]] = None
    # streaming tasks: per-item callback (index, ObjectID) threaded down to
    # the executing agent (None for ordinary tasks)
    stream: Optional[Callable[[int, ObjectID], None]] = None


class _StreamRecord:
    """Owner-side state of one streaming task's output sequence."""

    __slots__ = ("cv", "refs", "done", "error")

    def __init__(self):
        self.cv = threading.Condition()
        self.refs: List["ObjectRef"] = []
        self.done = False
        self.error: Optional[BaseException] = None


class ObjectRefGenerator:
    """Iterator over a streaming task's return refs, yielding each as soon
    as the producer seals it — the consumer runs concurrently with the
    still-executing task (reference: ObjectRefGenerator /
    num_returns="streaming"). A producer error raises HERE, after every
    item produced before the failure has been yielded."""

    # try_next() sentinel: the stream is exhausted (distinct from None =
    # "nothing sealed yet"); a sentinel rather than StopIteration so
    # callers inside generator bodies don't trip PEP 479
    DONE = object()

    def __init__(self, runtime: "Runtime", task_id: TaskID, record: _StreamRecord):
        self._runtime = runtime
        self.task_id = task_id
        self._record = record
        self._idx = 0

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> "ObjectRef":
        rec = self._record
        with rec.cv:
            while True:
                if self._idx < len(rec.refs):
                    ref = rec.refs[self._idx]
                    self._idx += 1
                    return ref
                if rec.done:
                    if rec.error is not None:
                        raise rec.error
                    raise StopIteration
                rec.cv.wait(timeout=1.0)

    def try_next(self):
        """Non-blocking poll: the next sealed ref, None while the producer
        is still working on the next one, or ObjectRefGenerator.DONE once
        the stream is exhausted (raising the producer's error first, after
        every ref sealed before the failure has been handed out). Lets a
        multiplexing consumer drain whichever of several streams has data
        without parking on any single one."""
        rec = self._record
        with rec.cv:
            if self._idx < len(rec.refs):
                ref = rec.refs[self._idx]
                self._idx += 1
                return ref
            if rec.done:
                if rec.error is not None:
                    raise rec.error
                return ObjectRefGenerator.DONE
            return None

    def completed(self) -> bool:
        return self._record.done


class _Future:
    """Completion latch. wait()/get() park on the event as before;
    Runtime.wait registers per-future callbacks so N waiters over M refs
    cost one notification each instead of a 1ms busy-poll O(M) rescan."""

    __slots__ = ("event", "error", "_lock", "_waiters", "_next_token")

    def __init__(self):
        self.event = threading.Event()
        self.error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self._waiters: Dict[int, Callable[[], None]] = {}
        self._next_token = 0

    def finish(self, error: Optional[BaseException] = None) -> None:
        """Complete the future and fire registered waiters exactly once
        (idempotent — concurrent producers race benignly)."""
        with self._lock:
            if error is not None and self.error is None:
                self.error = error
            if self.event.is_set():
                return
            self.event.set()
            waiters = list(self._waiters.values())
            self._waiters.clear()
        for cb in waiters:
            try:
                cb()
            except Exception:  # noqa: BLE001 — a waiter never blocks completion
                pass

    def add_waiter(self, callback: Callable[[], None]) -> Optional[int]:
        """Register a completion callback; fires immediately (returning
        None) if already complete, else returns a token for remove_waiter."""
        with self._lock:
            if not self.event.is_set():
                self._next_token += 1
                token = self._next_token
                self._waiters[token] = callback
                return token
        callback()
        return None

    def remove_waiter(self, token: Optional[int]) -> None:
        if token is None:
            return
        with self._lock:
            self._waiters.pop(token, None)


class Runtime:
    """The composed deployment. One per process (see init/shutdown in api)."""

    def __init__(self, job_id: Optional[JobID] = None):
        self.job_id = job_id or JobID.next()
        self.control_plane = ControlPlane()
        self.directory = ObjectDirectory()
        # locate() consults this so it never hands a puller a holder on a
        # node the control plane already marked DEAD (satellite fix; the
        # DEAD-mark -> directory-purge window used to leak through)
        self.directory.alive_check = self._node_is_alive
        self.scheduler = ClusterScheduler(
            self.control_plane, config.scheduler_spread_threshold
        )
        self.reference_counter = ReferenceCounter(self)
        self.agents: Dict[NodeID, NodeAgent] = {}
        self.head_node_id: Optional[NodeID] = None
        self.is_shutdown = False
        # With an autoscaler attached, currently-infeasible demands stay
        # pending (they ARE the scale-up signal) instead of failing fast.
        self.autoscaling_enabled = False
        self._lock = threading.RLock()
        self._futures: Dict[ObjectID, _Future] = {}
        self._task_table: Dict[TaskID, Dict[str, Any]] = {}
        self._pending: List[_PendingTask] = []
        self._pending_cv = threading.Condition()
        self._lineage: Dict[ObjectID, TaskSpec] = {}
        self._actor_specs: Dict[ActorID, TaskSpec] = {}
        self._put_index = 0
        # batched-get fan-out pool (lazy; config.get_concurrency workers)
        self._get_pool = None
        self._get_pool_lock = threading.Lock()
        # object ids this runtime pulled through from a remote holder and
        # sealed locally — distinguishes cache hits from plain local gets
        self._pulled_through: set = set()
        self._cache_lock = threading.Lock()
        # lost-object recovery coalescing: concurrent waiters on one lost
        # object share a single reconstruction (parallel get makes the
        # many-waiters race the common case, not the corner case)
        self._reconstruct_inflight: Dict[ObjectID, Dict[str, Any]] = {}
        self._reconstruct_lock = threading.Lock()
        self._driver_task_id = TaskID.of()
        self._sched_thread = threading.Thread(
            target=self._scheduling_loop, daemon=True, name="cluster-scheduler"
        )
        self._sched_thread.start()
        self._last_gossip_sweep = time.monotonic()  # TTL sweep throttle
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, daemon=True, name="health-monitor"
        )
        self._monitor_thread.start()
        self.control_plane.register_job(self.job_id)
        # placement group table: (pg_id, bundle_index) -> NodeID
        self.pg_table: Dict[Tuple, NodeID] = {}
        from ..sched.placement_group import PlacementGroupManager  # lazy: cycle

        self.pg_manager = PlacementGroupManager(self)
        self._actor_pg: Dict[ActorID, Tuple[Any, int, Dict[str, float]]] = {}
        # ICI slice registry: slice_id -> SliceInfo (topology + packer +
        # host->node map) consumed by topology-aware gang placement.
        self.slices: Dict[Any, Any] = {}

    def register_slice(self, slice_info) -> None:
        """Register a physical slice's topology so placement groups can
        reserve contiguous sub-boxes on it (sched/topology.py::SliceInfo)."""
        with self._lock:
            self.slices[slice_info.slice_id] = slice_info
        # new capacity: gangs queued for topology must get a pass now, not
        # when some unrelated group happens to be removed (upstream:
        # gcs_placement_group_manager pending-queue retry on node add)
        self.pg_manager._retry_queued()

    def unregister_slice(self, slice_id) -> None:
        with self._lock:
            self.slices.pop(slice_id, None)

    # ------------------------------------------------------------- topology
    def add_node(
        self,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        is_head: bool = False,
        **node_kwargs,
    ) -> NodeAgent:
        resources = dict(resources or {"CPU": 8.0})
        info = NodeInfo(
            node_id=NodeID.generate(),
            address=f"local:{len(self.agents)}",
            resources_total=resources,
            labels=labels or {},
            **node_kwargs,
        )
        agent = NodeAgent(info, self.control_plane, self.directory)
        self.directory.register_agent(agent)
        self.control_plane.register_node(info)
        with self._lock:
            self.agents[info.node_id] = agent
            if is_head or self.head_node_id is None:
                self.head_node_id = info.node_id
                # re-stamp every cycle: after shutdown()+init() this
                # process's identity is the NEW head node, and flow dst
                # labels must follow it
                object_ledger.set_local_node(info.node_id.hex())
        # node join = new capacity: kick queued placement groups too
        self.pg_manager._retry_queued()
        self._kick_scheduler()
        return agent

    def remove_node(self, node_id: NodeID, notify: bool = False) -> None:
        """Drop a node: tasks crash, objects are lost.

        notify=False (default, crash/reap semantics): a reaped REMOTE host
        may only be partitioned — the stop frame would kill a survivor that
        is about to rejoin. Clean worker exits still happen via
        Runtime.shutdown's stop(), and local (in-process) agents ignore the
        flag. notify=True is for DELIBERATE removal (autoscaler scale-down):
        the stop frame tells the worker to exit instead of rejoining."""
        with self._lock:
            agent = self.agents.pop(node_id, None)
            if agent is not None and self.head_node_id == node_id:
                # re-home the driver to any surviving node
                self.head_node_id = next(iter(self.agents), None)
        if agent is None:
            return
        # stop before mark_node_dead: a notified worker must learn it was
        # deliberately removed BEFORE its heartbeat sees the DEAD state, or
        # it would race a rejoin against its own shutdown
        agent.stop(notify=notify)
        self.control_plane.mark_node_dead(node_id, "removed")
        self.directory.unregister_agent(node_id)
        # actors on that node die; restart-eligible ones are rescheduled
        for actor in self.control_plane.list_actors():
            if actor.node_id == node_id and actor.state is ActorState.ALIVE:
                self._on_actor_death(actor, WorkerCrashedError("node died"))
        self._kick_scheduler()

    def _node_is_alive(self, node_id: NodeID) -> bool:
        from .control_plane import NodeState

        info = self.control_plane.get_node(node_id)
        # unknown to the control plane = not ours to veto (directory-only
        # holders, e.g. duck-typed stores); filter only tracked-and-DEAD
        return info is None or info.state is NodeState.ALIVE

    @property
    def driver_agent(self) -> NodeAgent:
        with self._lock:
            if self.head_node_id is None or self.head_node_id not in self.agents:
                raise RuntimeError("no alive node to host driver objects")
            return self.agents[self.head_node_id]

    # ------------------------------------------------------------ submission
    def _prepare_runtime_env(self, spec: TaskSpec) -> None:
        """Ship working_dir through the control-plane KV at submission, so
        the spec carries a content-addressed uri any executing node — a
        joined host included — can resolve (runtime_env.package_working_dir
        / resolve; reference: GCS package upload in working_dir.py)."""
        renv = spec.options.runtime_env
        if not renv or not renv.get("working_dir"):
            return
        import dataclasses

        from . import runtime_env

        wd = renv["working_dir"]
        cache = getattr(self, "_wd_uri_cache", None)
        if cache is None:
            cache = self._wd_uri_cache = {}
        uri = cache.get(wd)
        if uri is not None:
            # once per distinct dir, not per task: content-addressed uri
            # reused (snapshot-at-first-submission semantics, like the
            # reference's once-per-job package upload)
            packaged = dict(renv)
            packaged.pop("working_dir")
            packaged["working_dir_uri"] = uri
        else:
            packaged = runtime_env.package_working_dir(renv, self.control_plane)
            cache[wd] = packaged["working_dir_uri"]
        # replace, never mutate: options objects are shared across calls
        spec.options = dataclasses.replace(spec.options, runtime_env=packaged)

    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        self._prepare_runtime_env(spec)
        refs = [ObjectRef(oid, self) for oid in spec.return_ids]
        retries = (
            spec.options.max_retries
            if spec.options.max_retries is not None
            else config.task_max_retries
        )
        with self._lock:
            for oid in spec.return_ids:
                self._futures[oid] = _Future()
                self._lineage[oid] = spec
            self._task_table[spec.task_id] = {
                "name": spec.name,
                "state": "PENDING",
                "kind": spec.kind.value,
                "attempt": spec.attempt,
                "ts_submit": _timeline_now_us(),
            }
        pending = _PendingTask(
            spec, retries_left=retries, retry_exceptions=spec.options.retry_exceptions
        )
        self._enqueue_pending(pending)
        return refs

    def submit_streaming_task(self, spec: TaskSpec) -> ObjectRefGenerator:
        """Submit a generator task; returns the ref generator immediately.

        Crash retries apply only while the stream is EMPTY (a worker dying
        before the first yield replays transparently, matching ordinary
        read-task resilience); once any item has sealed, a partial stream
        cannot replay and the failure surfaces after the yielded prefix.
        No lineage reconstruction for streamed objects."""
        self._prepare_runtime_env(spec)
        record = _StreamRecord()

        def on_item(index: int, oid: ObjectID) -> None:
            ref = ObjectRef(oid, self)
            with record.cv:
                # index is authoritative: items may arrive batched but
                # never out of order (single producer)
                record.refs.append(ref)
                record.cv.notify_all()

        with self._lock:
            self._task_table[spec.task_id] = {
                "name": spec.name,
                "state": "PENDING",
                "kind": spec.kind.value,
                "attempt": 0,
                "ts_submit": _timeline_now_us(),
            }
            self._streams = getattr(self, "_streams", {})
            self._streams[spec.task_id] = record
        retries = (
            spec.options.max_retries
            if spec.options.max_retries is not None
            else config.task_max_retries
        )
        self._enqueue_pending(_PendingTask(
            spec, retries_left=retries, retry_exceptions=False, stream=on_item,
        ))
        return ObjectRefGenerator(self, spec.task_id, record)

    def create_actor(self, cls, args, kwargs, options: TaskOptions) -> "ActorInfo":
        actor_id = ActorID.of(self.job_id)
        task_id = TaskID.of(actor_id)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            kind=TaskKind.ACTOR_CREATION,
            func=cls,
            args=args,
            kwargs=kwargs,
            options=options,
            return_ids=[ObjectID.for_task_return(task_id, 0)],
            actor_id=actor_id,
            dependencies=_collect_deps(args, kwargs),
        )
        self._prepare_runtime_env(spec)
        info = ActorInfo(
            actor_id=actor_id,
            name=options.name,
            class_name=getattr(cls, "__name__", "Actor"),
            max_restarts=options.max_restarts,
        )
        self.control_plane.register_actor(info)
        with self._lock:
            self._actor_specs[actor_id] = spec
            self._futures[spec.return_ids[0]] = _Future()
            self._task_table[task_id] = {
                "name": f"{getattr(cls, '__name__', 'Actor')}.__init__",
                "state": "PENDING",
                "kind": spec.kind.value,
                "attempt": 0,
                "ts_submit": _timeline_now_us(),
            }
        self._enqueue_pending(_PendingTask(spec, retries_left=0, retry_exceptions=False))
        return info

    def submit_actor_task(
        self, actor_id: ActorID, method_name: str, args, kwargs, options: TaskOptions,
        trace_ctx: Optional[Dict[str, str]] = None,
    ) -> List[ObjectRef]:
        if trace_ctx is None:
            from ..util import tracing

            trace_ctx = tracing.current_context()
        task_id = TaskID.of(actor_id)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            kind=TaskKind.ACTOR_TASK,
            func=None,
            args=args,
            kwargs=kwargs,
            options=options,
            return_ids=[
                ObjectID.for_task_return(task_id, i)
                for i in range(max(1, options.num_returns))
            ],
            actor_id=actor_id,
            method_name=method_name,
            dependencies=_collect_deps(args, kwargs),
            trace_ctx=trace_ctx,
        )
        refs = [ObjectRef(oid, self) for oid in spec.return_ids]
        with self._lock:
            for oid in spec.return_ids:
                self._futures[oid] = _Future()
                self._lineage[oid] = spec
            self._task_table[spec.task_id] = {
                "name": f"{method_name}",
                "state": "PENDING",
                "kind": spec.kind.value,
                "attempt": 0,
                "ts_submit": _timeline_now_us(),
            }
        retries = options.max_task_retries
        self._enqueue_pending(_PendingTask(spec, retries_left=retries, retry_exceptions=False))
        return refs

    # -------------------------------------------------------------- get/put
    def put(self, value: Any) -> ObjectRef:
        with self._lock:
            self._put_index += 1
            oid = ObjectID.for_put(self._driver_task_id, self._put_index)
        agent = self.driver_agent
        from .object_store import seal_value

        # aliasing-safe: the caller may keep mutating `value` after put()
        agent.store.put(oid, seal_value(value))
        agent.store.annotate(oid, pin_reason=object_ledger.PIN_USER_PUT,
                             creator_task="driver")
        self.directory.add_location(oid, agent.node_id)
        fut = _Future()
        fut.finish()
        with self._lock:
            self._futures[oid] = fut
        return ObjectRef(oid, self)

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        """Resolve a batch of refs. Distinct object ids are deduped (each
        resolves once, every requesting slot shares the value) and fanned
        out over a bounded pool, so pulls from different holders overlap
        and the batch completes in ~max of the individual pull times. All
        refs share ONE deadline derived from `timeout`, instead of each
        ref re-budgeting whatever time the previous ones left."""
        refs = list(refs)
        if not refs:
            return []
        deadline = None if timeout is None else time.monotonic() + timeout
        distinct: "Dict[ObjectID, List[int]]" = {}
        for idx, ref in enumerate(refs):
            distinct.setdefault(ref.object_id, []).append(idx)
        uniques = [refs[slots[0]] for slots in distinct.values()]
        if len(uniques) == 1 or config.get_concurrency <= 1:
            results = [self._get_one(ref, deadline) for ref in uniques]
        else:
            # pool threads don't inherit this thread's trace context —
            # re-activate it around each pull so object_pull spans still
            # parent under the caller's span (None ctx: activate no-ops)
            from ..util import tracing

            ctx = tracing.current_context()

            def _traced_get_one(ref):
                with tracing.activate(ctx):
                    return self._get_one(ref, deadline)

            pool = self._get_executor()
            futures = [pool.submit(_traced_get_one, ref)
                       for ref in uniques]
            results, first_error = [], None
            for f in futures:
                try:
                    results.append(f.result())
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    results.append(None)
                    if first_error is None:
                        first_error = e
            if first_error is not None:
                # deterministic: the earliest failing ref wins, matching
                # what the serial loop would have raised first
                raise first_error
        out: List[Any] = [None] * len(refs)
        for value, slots in zip(results, distinct.values()):
            for idx in slots:
                out[idx] = value
        return out

    def _get_executor(self):
        with self._get_pool_lock:
            if self._get_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._get_pool = ThreadPoolExecutor(
                    max_workers=max(1, int(config.get_concurrency)),
                    thread_name_prefix="object-get",
                )
            return self._get_pool

    def _get_one(self, ref: ObjectRef, deadline: Optional[float]) -> Any:
        oid = ref.object_id
        fut = self._future_for(oid)
        remaining = (None if deadline is None
                     else max(0.0, deadline - time.monotonic()))
        if not fut.event.wait(remaining):
            raise GetTimeoutError(f"get() timed out on {ref}")
        if fut.error is not None:
            raise fut.error
        holder = self.directory.locate(oid, prefer_local=True)
        if holder is None:
            # object lost (e.g. node died) — attempt lineage reconstruction
            if self._reconstruct_once(oid, deadline):
                return self._get_one(ref, deadline)
            raise ObjectLostError(oid)
        try:
            if not getattr(holder, "is_remote", False):
                with self._cache_lock:
                    if oid in self._pulled_through:
                        _cache_hits.inc()
                return holder.store.get(oid, timeout=10.0)
            if config.object_pull_through_cache:
                return self._pull_through(oid, holder)
            return holder.store.get(oid, timeout=10.0)
        except (TimeoutError, ObjectLostError):
            # holder died between locate and pull (remote store proxies
            # surface this as ObjectLostError) — one coalesced
            # reconstruction attempt, retried against the REMAINING time
            # to the shared deadline, not the original timeout
            self.directory.remove_location(oid, holder.node_id)
            if self._reconstruct_once(oid, deadline):
                return self._get_one(ref, deadline)
            raise ObjectLostError(oid)

    def _pull_through(self, oid: ObjectID, holder) -> Any:
        """Remote get with pull-through caching: fetch the SEALED payload,
        seal it into the local driver store, and register the new location
        — repeat gets become local hits and later pullers anywhere in the
        cluster can fetch from us instead of the origin (broadcast fans
        out instead of hammering one holder). Objects are immutable once
        sealed, so the replica can never go stale. Caching is best-effort:
        any failure degrades to returning the pulled value."""
        _cache_misses.inc()
        raw = holder.store.get_raw(oid, timeout=10.0)
        try:
            agent = self.driver_agent
            if not getattr(agent, "is_remote", False):
                agent.store.put(oid, raw)
                agent.store.annotate(oid, pin_reason=object_ledger.PIN_CACHE)
                self.directory.add_location(oid, agent.node_id)
                with self._cache_lock:
                    self._pulled_through.add(oid)
                return agent.store.get(oid, timeout=0.0)
        except Exception:  # noqa: BLE001 — caching never fails the get
            logger.debug("pull-through cache of %s failed", oid, exc_info=True)
        return raw.load() if isinstance(raw, SealedBytes) else raw

    def _reconstruct_once(self, oid: ObjectID,
                          deadline: Optional[float]) -> bool:
        """Lineage recovery, coalesced: the first waiter to notice the loss
        leads the reconstruction; concurrent waiters for the same object
        block on its outcome instead of re-running the producing task once
        per waiter."""
        with self._reconstruct_lock:
            rec = self._reconstruct_inflight.get(oid)
            leader = rec is None
            if leader:
                rec = {"event": threading.Event(), "ok": False}
                self._reconstruct_inflight[oid] = rec
        if leader:
            try:
                rec["ok"] = self._try_reconstruct(oid)
            finally:
                with self._reconstruct_lock:
                    self._reconstruct_inflight.pop(oid, None)
                rec["event"].set()
            return bool(rec["ok"])
        remaining = (60.0 if deadline is None
                     else max(0.0, deadline - time.monotonic()))
        rec["event"].wait(remaining)
        return bool(rec["ok"])

    def broadcast(self, ref: ObjectRef,
                  nodes: Optional[Sequence[NodeID]] = None,
                  timeout: float = 120.0) -> Dict[str, Any]:
        """Disseminate one sealed object to every node (or the `nodes`
        subset) ahead of demand. In-process agents get a zero-copy store
        reference; remote hosts are dispatched `prefetch_object` in
        topology-ordered waves sized to the current replica count times
        `config.object_broadcast_fanout`, so concurrent pullers in a wave
        self-organize into the pipelined relay tree (each serves its
        committed prefix onward) and each completed wave multiplies the
        sources for the next. Returns {"object_id", "warmed", "failed"};
        per-node failures are recorded, never raised."""
        from .object_transfer import HOST_PREFIX, purge_relay_claims

        oid = ref.object_id
        fut = self._future_for(oid)
        if not fut.event.wait(timeout):
            raise GetTimeoutError(f"broadcast() timed out waiting on {ref}")
        if fut.error is not None:
            raise fut.error
        holders = set(self.directory.locations(oid))
        if not holders:
            if not self._reconstruct_once(oid, None):
                raise ObjectLostError(oid)
            holders = set(self.directory.locations(oid))
        with self._lock:
            agents = dict(self.agents)
        wanted = None if nodes is None else set(nodes)
        targets = [
            a for nid, a in agents.items()
            if nid not in holders
            and (wanted is None or nid in wanted)
            and not a._stopped.is_set()
            and self._node_is_alive(nid)
        ]
        warmed: List[str] = []
        failed: List[Tuple[str, str]] = []
        local = [a for a in targets if not getattr(a, "is_remote", False)]
        remote = [a for a in targets if getattr(a, "is_remote", False)]
        if local:
            src = self.directory.locate(oid, prefer_local=True)
            if src is not None:
                raw = src.store.get_raw(oid, timeout=30.0)
                for a in local:
                    try:
                        a.store.put(oid, raw)
                        a.store.annotate(
                            oid, pin_reason=object_ledger.PIN_CACHE)
                        self.directory.add_location(oid, a.node_id)
                        warmed.append(a.node_id.hex())
                    except Exception as e:  # noqa: BLE001 — per-node report
                        failed.append((a.node_id.hex(), repr(e)))

        def _host_of(a) -> str:
            try:
                tok = self.control_plane.kv_get(HOST_PREFIX + a.node_id.hex())
                return tok or ""
            except Exception:  # noqa: BLE001 — ordering is advisory
                return ""

        # same-host nodes adjacent in dispatch order -> adjacent relay
        # slots -> intra-host tree edges ride shm/loopback, not the fabric
        remote.sort(key=lambda a: (_host_of(a), a.node_id.hex()))
        fanout = max(1, int(config.object_broadcast_fanout))
        capacity = max(1, len(holders))
        deadline = time.monotonic() + timeout
        i = 0
        while i < len(remote):
            wave = remote[i:i + capacity * fanout]
            i += len(wave)
            results: Dict[NodeID, Any] = {}

            def _pull(a):
                left = max(1.0, deadline - time.monotonic())
                try:
                    a.prefetch_object(oid.hex(), timeout=left)
                    results[a.node_id] = True
                except Exception as e:  # noqa: BLE001 — per-node report
                    results[a.node_id] = e

            threads = [threading.Thread(target=_pull, args=(a,), daemon=True,
                                        name="broadcast-wave")
                       for a in wave]
            for t in threads:
                t.start()
            for t in threads:
                t.join(max(1.0, deadline - time.monotonic()))
            for a in wave:
                got = results.get(a.node_id)
                if got is True:
                    warmed.append(a.node_id.hex())
                    capacity += 1
                else:
                    failed.append((a.node_id.hex(),
                                   repr(got) if got else "timed out"))
        purge_relay_claims(oid.hex(), self.control_plane)
        return {"object_id": oid.hex(), "warmed": warmed, "failed": failed}

    def wait(
        self,
        refs: Sequence[ObjectRef],
        num_returns: int = 1,
        timeout: Optional[float] = None,
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        """Block until num_returns refs complete. Completion-driven: each
        future notifies a shared condition variable, so the wait costs one
        wakeup per completion instead of a 1ms busy-poll that rescans all
        refs (which burned a core at high fan-in)."""
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds number of refs")
        refs = list(refs)
        if num_returns <= 0:
            return [], refs
        deadline = None if timeout is None else time.monotonic() + timeout
        cv = threading.Condition()
        done_indices: List[int] = []

        def _on_done(idx: int) -> None:
            with cv:
                done_indices.append(idx)
                cv.notify_all()

        registrations: List[Tuple[_Future, Optional[int]]] = []
        try:
            for idx, ref in enumerate(refs):
                fut = self._future_for(ref.object_id)
                registrations.append(
                    (fut, fut.add_waiter(lambda i=idx: _on_done(i))))
            with cv:
                while len(done_indices) < num_returns:
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        break
                    cv.wait(remaining)
                chosen = set(sorted(done_indices)[:num_returns])
        finally:
            # always deregister: leaked waiters would accumulate on
            # long-lived futures across repeated wait() calls
            for fut, token in registrations:
                fut.remove_waiter(token)
        ready = [ref for i, ref in enumerate(refs) if i in chosen]
        pending = [ref for i, ref in enumerate(refs) if i not in chosen]
        return ready, pending

    def _future_for(self, oid: ObjectID) -> _Future:
        with self._lock:
            fut = self._futures.get(oid)
            if fut is None:
                # ref arrived from another process / was reconstructed
                fut = _Future()
                if self.directory.locations(oid):
                    fut.finish()
                else:
                    self.directory.subscribe_once(oid, fut.finish)
                self._futures[oid] = fut
            return fut

    def note_escaped(self, object_id: ObjectID) -> None:
        """Called from ObjectRef.__reduce__: this id was serialized (task
        result, nested argument, cross-process send) — exempt it from
        refcount-zero auto-free so the deserialized copy still resolves."""
        self.reference_counter.note_escaped(object_id)
        # stamp the pin reason wherever the object lives locally, so the
        # ledger can answer WHY the entry outlives its python handles
        with self._lock:
            agents = list(self.agents.values())
        for agent in agents:
            if getattr(agent, "is_remote", False):
                continue
            store = getattr(agent, "store", None)
            if store is not None and store.contains(object_id):
                store.annotate(object_id,
                               pin_reason=object_ledger.PIN_ESCAPED)

    def free_object(self, object_id: ObjectID) -> None:
        with self._lock:
            self._futures.pop(object_id, None)
            self._lineage.pop(object_id, None)
        with self._cache_lock:
            self._pulled_through.discard(object_id)
        self.directory.drop_everywhere(object_id)

    # ---------------------------------------------------------- health check
    def _monitor_loop(self) -> None:
        """Pump agent heartbeats and reap nodes whose heartbeat went stale
        (reference: `gcs_health_check_manager.cc` periodic ping)."""
        period = config.health_check_period_ms / 1000.0
        timeout = config.health_check_timeout_ms / 1000.0
        while not self.is_shutdown:
            time.sleep(period)
            with self._lock:
                agents = list(self.agents.values())
            for agent in agents:
                if not agent._stopped.is_set():
                    agent._sync_load()
            for node_id in self.control_plane.check_health(timeout):
                logger.warning("health check: reaping node %s", node_id.hex()[:8])
                self.remove_node(node_id)
            try:
                # throttles itself to config.object_sweep_period_s
                object_ledger.sweep(self)
            except Exception:  # noqa: BLE001 — sweep never kills the monitor
                logger.debug("object leak sweep failed", exc_info=True)
            now = time.monotonic()
            ttl = float(config.control_plane_gossip_ttl_s)
            if now - self._last_gossip_sweep > max(ttl / 4.0, period):
                self._last_gossip_sweep = now
                try:
                    self.control_plane.sweep_gossip()
                except Exception:  # noqa: BLE001
                    logger.debug("gossip TTL sweep failed", exc_info=True)

    def pending_resource_demand(self) -> List[Dict[str, float]]:
        """Resource shapes of queued-but-unplaced tasks — the autoscaler's
        demand signal (reference: resource load reported to GCS)."""
        with self._pending_cv:
            batch = list(self._pending)
        return [item.spec.options.resource_demand() for item in batch]

    # ------------------------------------------------------------ scheduling
    def _enqueue_pending(self, pending: _PendingTask) -> None:
        with self._pending_cv:
            self._pending.append(pending)
            self._pending_cv.notify_all()

    def _kick_scheduler(self) -> None:
        with self._pending_cv:
            self._pending_cv.notify_all()

    def _scheduling_loop(self) -> None:
        while not self.is_shutdown:
            with self._pending_cv:
                if not self._pending:
                    self._pending_cv.wait(timeout=0.05)
                batch = list(self._pending)
                self._pending.clear()
            leftover: List[_PendingTask] = []
            for item in batch:
                if not self._try_place(item):
                    leftover.append(item)
            if leftover:
                with self._pending_cv:
                    self._pending.extend(leftover)
                time.sleep(0.002)

    def _usable_agent(self, node_id: Optional[NodeID]):
        """Agent for node_id, or None if absent or stopped. A stopped
        agent (e.g. a remote proxy whose connection dropped before the
        health check reaps the node) must read as 'unavailable now' —
        submitting to it would fail instantly and burn the task's whole
        retry budget in milliseconds instead of failing over."""
        if node_id is None:
            return None
        agent = self.agents.get(node_id)
        if agent is None or agent._stopped.is_set():
            return None
        return agent

    def _local_admit(self, spec: TaskSpec, strategy) -> Optional[NodeID]:
        """Bottom-up fast path: defer to NodeAgent.try_admit on the head's
        own agent for plain default-strategy tasks. Returns the node to
        place on, or None = take the global path (which also preserves
        fail-fast ValueError and the autoscaler's pending-demand signal)."""
        if not config.scheduler_local_admit:
            return None
        if type(strategy) is not SchedulingStrategy:
            return None  # affinity/spread/label/PG need the cluster view
        agent = self._usable_agent(self.head_node_id)
        if agent is None or not hasattr(agent, "try_admit"):
            return None  # remote/proxied agent: no local view to consult
        if agent.try_admit(spec.options.resource_demand()):
            _m_local_admits.inc()
            return self.head_node_id
        return None

    def _try_place(self, item: _PendingTask) -> bool:
        spec = item.spec
        strategy = spec.options.scheduling_strategy
        if spec.kind is not TaskKind.ACTOR_TASK and isinstance(
            strategy, PlacementGroupSchedulingStrategy
        ):
            return self._try_place_in_pg(item, strategy)
        if spec.kind is TaskKind.ACTOR_TASK:
            actor = self.control_plane.get_actor(spec.actor_id)
            if actor is None or actor.state is ActorState.DEAD:
                self._fail_task(item, RayActorError(
                    f"actor {spec.actor_id.hex()[:8]} is dead: "
                    f"{actor.death_cause if actor else 'unknown'}"))
                return True
            if actor.state is not ActorState.ALIVE or actor.node_id is None:
                return False  # wait for (re)start
            agent = self._usable_agent(actor.node_id)
            if agent is None:
                return False
            self._mark_task(spec.task_id, "RUNNING")
            agent.submit(spec, lambda result: self._on_task_done(item, result),
                         stream=item.stream)
            return True

        # bottom-up fast path: the local node agent admits against its own
        # resource view (fresher than the control plane's) when the demand
        # fits under the spread threshold — exactly the node _hybrid's
        # local-first rule would pick, without walking the cluster view.
        # Overflow (and every non-default strategy) delegates to the
        # ClusterScheduler, preserving fail-fast and autoscaler demand.
        node_id = self._local_admit(spec, strategy)
        if node_id is None:
            try:
                node_id = self.scheduler.select_node(
                    spec, preferred_node=self.head_node_id, pg_table=self.pg_table
                )
            except ValueError as e:
                if self.autoscaling_enabled:
                    return False  # keep pending: this demand drives scale-up
                self._fail_task(item, e)
                return True
        if node_id is None:
            return False
        agent = self._usable_agent(node_id)
        if agent is None:
            return False
        item.target_node = node_id
        if spec.kind is TaskKind.ACTOR_CREATION:
            self.control_plane.update_actor(spec.actor_id, ActorState.STARTING, node_id)
        self._mark_task(spec.task_id, "RUNNING")
        agent.submit(spec, lambda result: self._on_task_done(item, result),
                         stream=item.stream)
        return True

    def _try_place_in_pg(self, item: _PendingTask, strategy) -> bool:
        """Place a task into a placement-group bundle: consume bundle capacity
        (not node capacity) and run on the bundle's reserved node."""
        spec = item.spec
        pg = self.pg_manager.get(strategy.placement_group_id)
        if pg is None or not pg.created:
            return False  # group still materializing
        demand = spec.options.resource_demand()
        indices = (
            [strategy.bundle_index]
            if strategy.bundle_index >= 0
            else list(range(len(pg.bundles)))
        )
        # fail fast if no eligible bundle could EVER satisfy the demand
        # (e.g. num_cpus=1 into a TPU-only bundle) instead of queueing forever
        if not any(
            all(pg.bundles[i].get(k, 0.0) >= v - 1e-9 for k, v in demand.items())
            for i in indices
            if 0 <= i < len(pg.bundles)
        ):
            self._fail_task(item, ValueError(
                f"task {spec.name} demand {demand} exceeds placement-group "
                f"bundle capacity {[pg.bundles[i] for i in indices if 0 <= i < len(pg.bundles)]}; "
                "request only resources reserved by the bundle (hint: num_cpus=0 "
                "for TPU-bundle tasks)"
            ))
            return True
        for idx in indices:
            if not pg.try_acquire(idx, demand):
                continue
            node_id = pg.bundle_node(idx)
            agent = self._usable_agent(node_id)
            if agent is None:
                pg.release(idx, demand)
                continue
            spec.skip_node_resources = True
            item.target_node = node_id
            item.pg_lease = (pg, idx, demand)
            if spec.kind is TaskKind.ACTOR_CREATION:
                self.control_plane.update_actor(spec.actor_id, ActorState.STARTING, node_id)
            self._mark_task(spec.task_id, "RUNNING")
            agent.submit(spec, lambda result: self._on_task_done(item, result),
                         stream=item.stream)
            return True
        return False

    # ------------------------------------------------------------ completion
    def _on_task_done(self, item: _PendingTask, result: TaskResult) -> None:
        spec = item.spec
        # was the actor killed while its __init__ was still running?
        killed_during_init = False
        if spec.kind is TaskKind.ACTOR_CREATION and result.ok:
            actor = self.control_plane.get_actor(spec.actor_id)
            killed_during_init = actor is None or actor.state is ActorState.DEAD
        if item.pg_lease is not None:
            pg, idx, demand = item.pg_lease
            if spec.kind is TaskKind.ACTOR_CREATION and result.ok and not killed_during_init:
                # actor keeps its bundle share until death
                with self._lock:
                    self._actor_pg[spec.actor_id] = item.pg_lease
            else:
                pg.release(idx, demand)
            item.pg_lease = None
            spec.skip_node_resources = False
        if result.ok:
            self._mark_task(spec.task_id, "FINISHED")
            self._finish_stream(spec.task_id, None)
            if spec.kind is TaskKind.ACTOR_CREATION:
                if killed_during_init:
                    # tear the fresh runner back down; DEAD stays DEAD
                    agent = self.agents.get(item.target_node) if item.target_node else None
                    if agent is not None:
                        agent.kill_actor(spec.actor_id, cause="killed during creation")
                else:
                    self.control_plane.update_actor(
                        spec.actor_id, ActorState.ALIVE, item.target_node
                    )
                self._kick_scheduler()  # pending method calls can now route
            with self._lock:
                futures = [self._futures.get(oid) for oid in spec.return_ids]
            for fut in futures:
                if fut is not None:
                    fut.finish()
            return

        # Actor-death detection must precede the retry decision: a crashed
        # actor task with retries left would otherwise re-enqueue, find the
        # dead runner, and burn its retries before anyone schedules the
        # restart (the retried task then routes once the new incarnation
        # is ALIVE).
        if spec.kind is TaskKind.ACTOR_TASK and not result.is_application_error:
            actor = self.control_plane.get_actor(spec.actor_id)
            if actor is not None and actor.state is ActorState.ALIVE:
                self._on_actor_death(actor, result.error)

        if item.stream is not None:
            record = getattr(self, "_streams", {}).get(spec.task_id)
            if record is not None and record.refs:
                # items already streamed to the consumer: a replay would
                # duplicate them — no retry past the first yield
                item.retries_left = 0
        retriable = not result.is_application_error or item.retry_exceptions
        if retriable and item.retries_left > 0:
            item.retries_left -= 1
            spec.attempt += 1
            self._mark_task(spec.task_id, "RETRYING")
            logger.info(
                "retrying task %s (attempt %d) after: %r",
                spec.name, spec.attempt, result.error,
            )
            self._enqueue_pending(item)
            return

        if spec.kind is TaskKind.ACTOR_CREATION:
            actor = self.control_plane.get_actor(spec.actor_id)
            if (
                not result.is_application_error
                and actor is not None
                and actor.num_restarts < actor.max_restarts
            ):
                # creation crashed with the node — reschedule like a death
                self._on_actor_death(actor, result.error)
                return
            self.control_plane.update_actor(
                spec.actor_id, ActorState.DEAD,
                death_cause=repr(result.error),
            )
        error: BaseException
        if result.is_application_error:
            error = RayTaskError(spec.name, result.error)  # type: ignore[arg-type]
        elif spec.kind is TaskKind.ACTOR_TASK:
            error = RayActorError(f"actor task {spec.name} failed: {result.error!r}")
        else:
            error = RayTaskError(spec.name, result.error)  # type: ignore[arg-type]
        self._fail_task(item, error)

    def _on_actor_death(self, actor: ActorInfo, cause: Optional[BaseException]) -> None:
        with self._lock:
            lease = self._actor_pg.pop(actor.actor_id, None)
        if lease is not None:
            pg, idx, demand = lease
            pg.release(idx, demand)
        if actor.num_restarts < actor.max_restarts:
            self.control_plane.update_actor(actor.actor_id, ActorState.RESTARTING)
            with self._lock:
                spec = self._actor_specs.get(actor.actor_id)
            if spec is not None:
                spec.attempt += 1
                logger.info("restarting actor %s (restart %d)",
                            actor.actor_id.hex()[:8], actor.num_restarts)
                self._enqueue_pending(_PendingTask(spec, retries_left=0, retry_exceptions=False))
        else:
            self.control_plane.update_actor(
                actor.actor_id, ActorState.DEAD, death_cause=repr(cause)
            )
            self._kick_scheduler()

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        actor = self.control_plane.get_actor(actor_id)
        if actor is None:
            return
        if actor.node_id is not None:
            agent = self.agents.get(actor.node_id)
            if agent is not None:
                agent.kill_actor(actor_id)
        if no_restart:
            with self._lock:
                lease = self._actor_pg.pop(actor_id, None)
            if lease is not None:
                pg, idx, demand = lease
                pg.release(idx, demand)
            self.control_plane.update_actor(actor_id, ActorState.DEAD, death_cause="ray_tpu.kill")
        else:
            self._on_actor_death(actor, WorkerCrashedError("killed"))

    def _finish_stream(self, task_id: TaskID, error: Optional[BaseException]) -> None:
        # pop, don't get: nothing writes a finished record again, and the
        # consumer's ObjectRefGenerator holds its own reference — keeping
        # it in the table would leak every stream's refs for the runtime's
        # lifetime
        record = getattr(self, "_streams", {}).pop(task_id, None)
        if record is None:
            return
        with record.cv:
            record.error = error
            record.done = True
            record.cv.notify_all()

    def _fail_task(self, item: _PendingTask, error: BaseException) -> None:
        self._mark_task(item.spec.task_id, "FAILED")
        self._finish_stream(item.spec.task_id, error)
        if item.spec.kind is TaskKind.ACTOR_CREATION:
            # a failed creation must kill the actor record, or pending method
            # calls wait forever for a start that will never come
            self.control_plane.update_actor(
                item.spec.actor_id, ActorState.DEAD, death_cause=repr(error)
            )
            self._kick_scheduler()
        with self._lock:
            futures = [self._futures.get(oid) for oid in item.spec.return_ids]
        for fut in futures:
            if fut is not None:
                fut.finish(error)

    def _mark_task(self, task_id: TaskID, state: str) -> None:
        from ..util import timeline

        emit = None
        with self._lock:
            entry = self._task_table.get(task_id)
            if entry is None:
                return
            entry["state"] = state
            now = timeline._now_us()
            if state == "RUNNING":
                entry["ts_start"] = now
            elif state in ("FINISHED", "FAILED", "RETRYING"):
                ts_start = entry.get("ts_start")
                ts_submit = entry.get("ts_submit")
                if ts_start is not None:
                    emit = (entry["name"], ts_submit, ts_start, now, state)
                if state == "RETRYING":
                    # next attempt gets its own queued/task spans
                    entry["ts_submit"] = now
                    entry["ts_start"] = None
        if emit is not None:
            name, ts_submit, ts_start, ts_end, final = emit
            if ts_submit is not None and ts_start > ts_submit:
                timeline.record(
                    f"{name} (queued)", "X", cat="queue",
                    ts_us=ts_submit, dur_us=ts_start - ts_submit,
                    pid="tasks", tid=name.split(".")[0],
                )
            timeline.record(
                name, "X", cat="task", ts_us=ts_start,
                dur_us=ts_end - ts_start, pid="tasks",
                tid=name.split(".")[0], args={"outcome": final},
            )

    # --------------------------------------------------------- reconstruction
    def _try_reconstruct(self, object_id: ObjectID) -> bool:
        """Lineage-based recovery: re-run the task that produced the object."""
        with self._lock:
            spec = self._lineage.get(object_id)
        if spec is None or spec.kind is not TaskKind.NORMAL:
            return False
        logger.info("reconstructing %s by re-executing %s", object_id, spec.name)
        done = threading.Event()
        outcome: Dict[str, Any] = {}

        def on_done(result: TaskResult) -> None:
            outcome["ok"] = result.ok
            done.set()

        spec.attempt += 1
        item = _PendingTask(spec, retries_left=1, retry_exceptions=False)
        # bypass futures (they are already set): place directly
        placed = False
        for _ in range(200):
            try:
                node_id = self.scheduler.select_node(spec, preferred_node=self.head_node_id)
            except ValueError:
                return False
            if node_id is not None and node_id in self.agents:
                self.agents[node_id].submit(spec, on_done)
                placed = True
                break
            time.sleep(0.01)
        if not placed:
            return False
        done.wait(timeout=60.0)
        return bool(outcome.get("ok"))

    # ------------------------------------------------------------- state API
    def task_table(self) -> Dict[TaskID, Dict[str, Any]]:
        with self._lock:
            return {k: dict(v) for k, v in self._task_table.items()}

    # -------------------------------------------------------------- shutdown
    def shutdown(self) -> None:
        self.is_shutdown = True
        with self._get_pool_lock:
            pool, self._get_pool = self._get_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        if config.event_log_dir:
            # durable task timeline for `ray-tpu timeline --events-dir`
            try:
                import os as _os

                from ..util import timeline as _tl

                _os.makedirs(config.event_log_dir, exist_ok=True)
                _tl.export(_os.path.join(
                    config.event_log_dir,
                    f"timeline_{_os.getpid()}_{int(time.time())}.json",
                ))
            except Exception:
                logger.debug("timeline export on shutdown failed", exc_info=True)
        writer = getattr(self, "_snapshot_writer", None)
        if writer is not None:
            writer.stop(final_write=True)
            self._snapshot_writer = None
        cp_server = getattr(self, "_cp_server", None)
        if cp_server is not None:
            cp_server.stop()
            self._cp_server = None
        transfer = getattr(self, "_transfer_server", None)
        if transfer is not None:
            transfer.stop()
            self._transfer_server = None
        self._kick_scheduler()
        self.control_plane.finish_job(self.job_id)
        with self._lock:
            agents = list(self.agents.values())
        for agent in agents:
            agent.stop()
        if getattr(self, "_federation", None) is not None:
            from .shard import stop_federation

            stop_federation(self)


_global_runtime: Optional[Runtime] = None


def get_runtime() -> Runtime:
    if _global_runtime is None:
        raise RuntimeError("ray_tpu is not initialized; call ray_tpu.init() first")
    return _global_runtime


def set_runtime(rt: Optional[Runtime]) -> None:
    global _global_runtime
    _global_runtime = rt


def runtime_initialized() -> bool:
    return _global_runtime is not None


def _collect_deps(args: tuple, kwargs: dict) -> List[ObjectID]:
    deps: List[ObjectID] = []
    for v in list(args) + list(kwargs.values()):
        if isinstance(v, ObjectRef):
            deps.append(v.object_id)
    return deps
