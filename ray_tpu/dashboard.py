"""Dashboard-lite (reference: `python/ray/dashboard/` — per SURVEY §7.5 the
React app is out of scope; ship the state API over HTTP + provisioned
Grafana dashboards, the reference's `dashboard/modules/metrics/` pattern).

Two pieces:
- `write_grafana_dashboards(dir)`: emits dashboard JSONs (core / serve /
  data planes, built from this repo's actual metric names) plus a
  provisioning config, mirroring the reference's bundled Grafana JSONs.
- `start_dashboard(...)`: one stdlib HTTP server with `/` (HTML status),
  `/api/v0/<nodes|actors|jobs|objects|summary>` (state API as JSON) and
  `/metrics` (Prometheus text) — the reference serves the same three
  surfaces from the dashboard head + agent.
"""

from __future__ import annotations

import html
import json
import os
import threading
from typing import Any, Dict, List, Optional

from .core.logging import get_logger
from .core.metrics import registry as metrics_registry

logger = get_logger("dashboard")


# ---------------------------------------------------------------------------
# Grafana provisioning
# ---------------------------------------------------------------------------


def _panel(title: str, expr: str, panel_id: int, y: int, unit: str = "short",
           legend: str = "{{__name__}}") -> Dict[str, Any]:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "gridPos": {"h": 8, "w": 12, "x": 12 * (panel_id % 2), "y": y},
        "fieldConfig": {"defaults": {"unit": unit}},
        "targets": [{
            "expr": expr,
            "legendFormat": legend,
            "refId": "A",
        }],
    }


def _dashboard(uid: str, title: str, panels: List[Dict[str, Any]]) -> Dict[str, Any]:
    return {
        "uid": uid,
        "title": title,
        "timezone": "browser",
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "panels": panels,
    }


def build_dashboards() -> Dict[str, Dict[str, Any]]:
    """name -> Grafana dashboard JSON, from this repo's metric names."""
    # the profiling board's gauges register on import (util/profiler is
    # pure stdlib); without this a dashboard export from a process that
    # never profiled would reference unregistered series
    from .util import profiler  # noqa: F401
    # likewise the fleet board's series live in serve/fleet.py (which
    # pulls in disagg's resume metrics)
    from .serve import fleet  # noqa: F401
    # the federation board's shard/aggregator series register on import
    # (neither module loads unless a sharded control plane is enabled)
    from .core import aggregator, shard  # noqa: F401
    core = _dashboard("raytpu-core", "ray_tpu / core", [
        _panel("Tasks finished (rate)", "rate(ray_tpu_tasks_finished[1m])",
               0, 0, legend="{{outcome}}"),
        _panel("Tasks running", "ray_tpu_tasks_running", 1, 0),
        _panel("Nodes by state", "ray_tpu_nodes", 2, 8, legend="{{state}}"),
        _panel("Actors by state", "ray_tpu_actors", 3, 8, legend="{{state}}"),
        _panel("Pool fallbacks (rate)", "rate(ray_tpu_pool_fallbacks[5m])",
               4, 16, legend="{{reason}}"),
        _panel("Object transfer (B/s)",
               "rate(object_transfer_bytes_pulled[1m])", 5, 16, unit="Bps"),
    ])
    serve = _dashboard("raytpu-serve", "ray_tpu / serve", [
        _panel("Requests finished (rate)",
               "rate(serve_requests_finished[1m])", 0, 0,
               legend="{{finish_reason}}"),
        _panel("Requests in decode slots", "serve_requests_running", 1, 0),
        _panel("Decode throughput (tok/s)",
               "rate(serve_tokens_generated[1m])", 2, 8),
        _panel("TTFT p50/p95",
               "histogram_quantile(0.5, rate(serve_ttft_seconds_bucket[5m]))",
               3, 8, unit="s", legend="p50"),
        # decode-step phase breakdown: the propose_wait vs propose_compute
        # split is the speculation-overlap evidence, kv_framing the
        # streamed-export framing cost
        _panel("Decode step time by phase (s/s)",
               "rate(serve_decode_step_phase_seconds_sum[5m])",
               4, 16, unit="s", legend="{{phase}} {{mode}}"),
        _panel("Spec acceptance rate", "serve_spec_acceptance_rate",
               5, 16, unit="percentunit", legend="acceptance"),
    ])
    # p95 as a second target on the TTFT panel
    serve["panels"][3]["targets"].append({
        "expr": "histogram_quantile(0.95, rate(serve_ttft_seconds_bucket[5m]))",
        "legendFormat": "p95",
        "refId": "B",
    })
    data = _dashboard("raytpu-data", "ray_tpu / data", [
        _panel("Tasks finished (rate)", "rate(ray_tpu_tasks_finished[1m])",
               0, 0, legend="{{outcome}}"),
        _panel("Transfer chunks (rate)",
               "rate(object_transfer_chunks_pulled[1m])", 1, 0),
    ])
    disagg = _dashboard("raytpu-disagg", "ray_tpu / disagg serving", [
        _panel("KV migration p50/p95",
               "histogram_quantile(0.5, "
               "rate(serve_kv_migration_seconds_bucket[5m]))",
               0, 0, unit="s", legend="p50 {{transport}}"),
        _panel("KV migration throughput (B/s)",
               "rate(serve_kv_migration_bytes[1m])", 1, 0, unit="Bps",
               legend="{{transport}}"),
        _panel("Queue depth by role", "serve_disagg_queue_depth", 2, 8,
               legend="{{role}} {{node_id}}"),
        _panel("In-flight by role", "serve_disagg_inflight", 3, 8,
               legend="{{role}} {{node_id}}"),
        _panel("Object pulls p95 (KV path rides this)",
               "histogram_quantile(0.95, rate(object_pull_seconds_bucket[5m]))",
               4, 16, unit="s", legend="p95 {{path}}"),
        _panel("TTFT p95 per node",
               "histogram_quantile(0.95, rate(serve_ttft_seconds_bucket[5m]))",
               5, 16, unit="s", legend="{{node_id}}"),
    ])
    disagg["panels"][0]["targets"].append({
        "expr": "histogram_quantile(0.95, "
                "rate(serve_kv_migration_seconds_bucket[5m]))",
        "legendFormat": "p95 {{transport}}",
        "refId": "B",
    })
    health = _dashboard("raytpu-health", "ray_tpu / health & SLOs", [
        _panel("Alerts firing by severity", "health_alerts_firing", 0, 0,
               legend="{{severity}}"),
        _panel("SLO quantiles per role (digests)",
               'slo_quantile_seconds{q="p95"}', 1, 0, unit="s",
               legend="p95 {{metric}} {{role}}"),
        _panel("Host memory used fraction", "host_memory_used_fraction",
               2, 8, unit="percentunit", legend="{{node_id}}"),
        _panel("Telemetry drops (rate)",
               "rate(telemetry_dropped_total[5m])", 3, 8,
               legend="{{kind}}"),
        _panel("Memory-monitor kills (rate)",
               "rate(memory_monitor_tasks_killed[5m])", 4, 16),
        _panel("Control-plane reconnects (rate)",
               "rate(control_plane_reconnects_total[5m])", 5, 16,
               legend="{{role}}"),
    ])
    health["panels"][1]["targets"].append({
        "expr": 'slo_quantile_seconds{q="p50"}',
        "legendFormat": "p50 {{metric}} {{role}}",
        "refId": "B",
    })
    profiling = _dashboard("raytpu-profiling", "ray_tpu / profiling & goodput", [
        _panel("Goodput: data stall (rate)",
               "rate(data_stage_stall_seconds_sum[5m])", 0, 0, unit="s",
               legend="stall {{stage}}"),
        _panel("Goodput: channel wait / migration (rate)",
               "rate(channel_recv_wait_seconds_sum[5m])", 1, 0, unit="s",
               legend="channel {{channel}}"),
        _panel("Host CPU used fraction", "host_cpu_used_fraction", 2, 8,
               unit="percentunit", legend="{{node_id}}"),
        _panel("Process RSS", "process_rss_bytes", 3, 8, unit="bytes",
               legend="{{node_id}} {{role}}"),
        _panel("Device memory in use (HBM)", "device_memory_bytes_in_use",
               4, 16, unit="bytes", legend="{{node_id}} {{device}}"),
        _panel("Sampling profilers active", "profiler_sampling_active",
               5, 16, legend="{{node_id}}"),
    ])
    profiling["panels"][1]["targets"].append({
        "expr": "rate(serve_kv_migration_seconds_sum[5m])",
        "legendFormat": "migration {{transport}}",
        "refId": "B",
    })
    profiling["panels"][0]["targets"].append({
        "expr": "train_pipeline_bubble_fraction",
        "legendFormat": "bubble {{stage}}",
        "refId": "B",
    })
    profiling["panels"][0]["targets"].append({
        "expr": "rate(train_pipeline_bubble_seconds[5m])",
        "legendFormat": "bubble {{kind}}",
        "refId": "C",
    })
    objects = _dashboard("raytpu-objects", "ray_tpu / object plane", [
        _panel("Live bytes per node/store", "object_store_live_bytes",
               0, 0, unit="bytes", legend="{{node}} {{store}}"),
        _panel("Per-edge bandwidth (window)", "object_flow_window_bps",
               1, 0, unit="Bps", legend="{{src}}→{{dst}} {{path}}"),
        _panel("Edge throughput (rate)", "rate(object_flow_bytes[1m])",
               2, 8, unit="Bps", legend="{{src}}→{{dst}} {{path}}"),
        _panel("Pull-through cache hit rate",
               "rate(object_cache_hits[5m]) / "
               "(rate(object_cache_hits[5m]) + rate(object_cache_misses[5m]))",
               3, 8, unit="percentunit", legend="hit rate"),
        _panel("Leaks by kind", "object_leaks", 4, 16, legend="{{kind}}"),
        _panel("Leaked bytes by kind", "object_leaked_bytes", 5, 16,
               unit="bytes", legend="{{kind}}"),
    ])
    fleet = _dashboard("raytpu-fleet", "ray_tpu / fleet actuation", [
        _panel("Target replicas vs demand", "serve_fleet_target_replicas",
               0, 0, legend="target {{role}}"),
        _panel("Demand signal", "serve_fleet_demand", 1, 0,
               legend="demand {{role}}"),
        _panel("Live resumes (rate)", "rate(serve_fleet_resumes[5m])",
               2, 8, legend="resumes"),
        _panel("Resume latency p95",
               "histogram_quantile(0.95, "
               "rate(serve_fleet_resume_seconds_bucket[5m]))",
               3, 8, unit="s", legend="p95"),
        _panel("Adapter residency", "serve_fleet_adapter_residency",
               4, 16, legend="{{adapter}}"),
        _panel("Remediation actions (rate)",
               "rate(serve_fleet_remediations[5m])", 5, 16,
               legend="{{stage}}"),
    ])
    # demand overlaid on the target panel: convergence at a glance
    fleet["panels"][0]["targets"].append({
        "expr": "serve_fleet_demand",
        "legendFormat": "demand {{role}}",
        "refId": "B",
    })
    rl = _dashboard("raytpu-rl", "ray_tpu / online RL", [
        _panel("Reward curve", "rl_reward_mean", 0, 0, legend="reward"),
        _panel("Rollout throughput (tok/s)",
               "rate(rl_rollout_tokens[5m])", 1, 0, legend="tokens/s"),
        _panel("Weight-version skew", "rl_weights_version_skew", 2, 8,
               legend="fleet skew"),
        _panel("Sync stall fraction", "rl_sync_stall_fraction", 3, 8,
               unit="percentunit", legend="weight_sync / wall"),
        _panel("Loop phase time (rate)", "rate(rl_phase_seconds[5m])",
               4, 16, unit="s", legend="{{phase}}"),
        _panel("Stale / dropped trajectories (rate)",
               "rate(rl_stale_trajectories[5m])", 5, 16,
               legend="stale {{policy}}"),
        _panel("Replica weights version", "serve_weights_version", 6, 24,
               legend="{{role}}"),
        _panel("Trajectories in flight", "rl_trajectories_inflight",
               7, 24, legend="inflight"),
    ])
    # dropped overlaid on the stale panel: one funnel, one glance
    rl["panels"][5]["targets"].append({
        "expr": "rate(rl_dropped_trajectories[5m])",
        "legendFormat": "dropped {{reason}}",
        "refId": "B",
    })
    federation = _dashboard("raytpu-federation", "ray_tpu / control plane federation", [
        _panel("Head CPU used fraction", "host_cpu_used_fraction",
               0, 0, unit="percentunit", legend="{{node_id}}"),
        _panel("Heartbeat lag (worst alive node)",
               "control_plane_heartbeat_lag_seconds", 1, 0, unit="s",
               legend="worst lag"),
        _panel("Shard health (1 = primary serving)",
               "control_plane_shard_health", 2, 8, legend="shard {{shard}}"),
        _panel("Shard failovers (rate)",
               "rate(control_plane_shard_failovers_total[5m])", 3, 8,
               legend="shard {{shard}}"),
        _panel("Client reconnects / throttled redials (rate)",
               "rate(control_plane_reconnects_total[5m])", 4, 16,
               legend="reconnect {{role}}"),
        _panel("Pubsub publishes dropped (rate)",
               "rate(control_plane_pubsub_dropped_total[5m])", 5, 16,
               legend="{{channel}}"),
        _panel("Aggregator flushes / reports absorbed (rate)",
               "rate(aggregator_flushes_total[5m])", 6, 24,
               legend="flush {{pod}}"),
        _panel("Telemetry shipped (delta-encoded B/s)",
               "rate(telemetry_bytes_total[5m])", 7, 24, unit="Bps",
               legend="{{field}}"),
        _panel("Gossip entries swept (rate)",
               "rate(control_plane_gossip_swept_total[5m])", 8, 32),
    ])
    # the dial-rate cap overlaid on the reconnect panel: a storm shows as
    # throttled redials climbing while reconnects stay flat
    federation["panels"][4]["targets"].append({
        "expr": "rate(control_plane_redials_throttled_total[5m])",
        "legendFormat": "throttled {{role}}",
        "refId": "B",
    })
    federation["panels"][6]["targets"].append({
        "expr": "rate(aggregator_reports_absorbed_total[5m])",
        "legendFormat": "absorbed {{pod}}",
        "refId": "B",
    })
    # the ingest board's series live in data/ingest.py + data/tenant.py
    from .data import ingest as _ingest  # noqa: F401
    ingest = _dashboard("raytpu-ingest", "ray_tpu / shared ingest service", [
        _panel("Preprocessed rows/s per tenant",
               "rate(ingest_rows_total[1m])", 0, 0,
               legend="{{tenant}}"),
        _panel("Ingest stall seconds/s per tenant",
               'rate(data_stage_stall_seconds{stage="ingest"}[1m])', 1, 0,
               unit="s", legend="{{tenant}}"),
        _panel("Fair-share ratio vs weight (1.0 = fair)",
               "ingest_fair_share_ratio", 2, 8, legend="{{tenant}}"),
        _panel("Cache hit rate per tenant",
               "rate(ingest_cache_hits_total[5m]) / "
               "(rate(ingest_cache_hits_total[5m]) + "
               "rate(ingest_cache_misses_total[5m]))",
               3, 8, unit="percentunit", legend="{{tenant}}"),
        _panel("Worker pool size vs pending demand",
               "ingest_pool_size", 4, 16, legend="pool"),
        _panel("In-flight bytes per tenant (budget gate)",
               "ingest_inflight_bytes", 5, 16, unit="bytes",
               legend="{{tenant}}"),
        _panel("Served bytes/s per tenant",
               "rate(ingest_tenant_bytes_total[1m])", 6, 24, unit="Bps",
               legend="{{tenant}}"),
        _panel("Cache evictions (rate)",
               "rate(ingest_cache_evicted_total[5m])", 7, 24,
               legend="evicted"),
    ])
    # pending-block backlog overlaid on the pool-size panel: the scale-up
    # trigger and its effect on one graph
    ingest["panels"][4]["targets"].append({
        "expr": "ingest_pending_blocks",
        "legendFormat": "pending {{tenant}}",
        "refId": "B",
    })
    return {"core": core, "serve": serve, "data": data, "disagg": disagg,
            "health": health, "profiling": profiling, "objects": objects,
            "fleet": fleet, "rl": rl, "federation": federation,
            "ingest": ingest}


def write_grafana_dashboards(directory: str) -> List[str]:
    """Write dashboard JSONs + a provisioning YAML; returns written paths.

    Point Grafana at the directory via its provisioning config (the
    reference ships the same layout in `dashboard/modules/metrics/export/`).
    """
    os.makedirs(directory, exist_ok=True)
    written = []
    for name, dash in build_dashboards().items():
        path = os.path.join(directory, f"ray_tpu_{name}.json")
        with open(path, "w") as f:
            json.dump(dash, f, indent=2)
        written.append(path)
    prov = os.path.join(directory, "provisioning.yaml")
    with open(prov, "w") as f:
        f.write(
            "apiVersion: 1\n"
            "providers:\n"
            "  - name: ray_tpu\n"
            "    folder: ray_tpu\n"
            "    type: file\n"
            "    options:\n"
            f"      path: {os.path.abspath(directory)}\n"
        )
    written.append(prov)
    return written


# ---------------------------------------------------------------------------
# HTTP dashboard (state API + HTML status + metrics)
# ---------------------------------------------------------------------------

_dash_server = None


def _render_metrics() -> str:
    """Cluster-wide Prometheus text: the head registry merged with the
    per-node snapshots workers federate via heartbeat telemetry (each
    remote series tagged node_id/role). Falls back to local-only when no
    runtime is up or no worker has reported."""
    from .core import core_worker
    from .core.metrics import render_merged

    snaps: Dict[str, Any] = {}
    if core_worker.runtime_initialized():
        try:
            cp = core_worker.get_runtime().control_plane
            snaps = cp.telemetry_snapshots()
        except Exception:  # noqa: BLE001 — /metrics must always render
            snaps = {}
    if not snaps:
        return metrics_registry.render_prometheus()
    return render_merged(metrics_registry, snaps)


def _profile_payload(rest: str, query: Dict[str, List[str]]) -> Dict[str, Any]:
    """/api/v0/profile/<node>[/<pid>] → the profiling-plane RPCs.

    kind=stack (default) returns a live all-threads dump — for a
    subprocess worker this works even when it is HUNG (SIGUSR2 →
    faulthandler). kind=cpu runs a one-shot sampling window of
    ?duration= seconds and returns the collapsed-stack profile;
    kind=jax starts an xplane capture; kind=pids (or no pid segment)
    lists what the node can profile."""
    import time as _time

    from .core import core_worker
    from .core.cross_host import HeadService

    svc = HeadService(core_worker.get_runtime())
    parts = [p for p in rest.split("/") if p]
    node = parts[0] if parts else ""
    if node in ("head", "local", "-"):
        node = ""
    pid = int(parts[1]) if len(parts) > 1 else 0
    kind = (query.get("kind") or [""])[0]
    if len(parts) < 2 and kind in ("", "pids"):
        return svc.profile_fetch(node=node, kind="pids")
    kind = kind or "stack"
    if kind == "jax":
        duration = float((query.get("duration") or ["5"])[0])
        return svc.profile_start(node=node, pid=pid, duration_s=duration,
                                 kind="jax")
    if kind == "cpu":
        duration = float((query.get("duration") or ["2"])[0])
        hz = query.get("hz")
        svc.profile_start(node=node, pid=pid, duration_s=duration,
                          hz=float(hz[0]) if hz else None, kind="cpu")
        _time.sleep(min(duration, 60.0))
        return svc.profile_fetch(node=node, pid=pid, kind="cpu")
    return svc.profile_fetch(node=node, pid=pid, kind=kind)


def _trace_payload(trace_id: str) -> Dict[str, Any]:
    """Phase breakdown for /api/v0/traces/<trace_id>. Accepts the raw
    trace id or an OpenAI X-Request-Id ('cmpl-<id>'/'chatcmpl-<id>' —
    the id embeds the trace id)."""
    from .util import tracing

    tid = trace_id.split("-")[-1]
    tree = tracing.get_trace(tid)
    if not tree:
        raise KeyError(trace_id)
    phases: Dict[str, Dict[str, float]] = {}
    pids = set()

    def _walk(nodes):
        for s in nodes:
            pids.add(s.get("pid"))
            dur_ms = ((s.get("end_us") or s["start_us"]) - s["start_us"]) / 1e3
            agg = phases.setdefault(s["name"], {"count": 0, "total_ms": 0.0})
            agg["count"] += 1
            agg["total_ms"] += dur_ms
            _walk(s.get("children", ()))

    _walk(tree)
    return {
        "trace_id": tree[0]["trace_id"],
        "processes": sorted(str(p) for p in pids),
        "phases": phases,
        "spans": tree,
    }


def _health_plane():
    from .core.health import get_health_plane

    return get_health_plane(create=True)


def _postmortems_payload() -> Dict[str, Any]:
    """Crash postmortems: the head's federated store (shipped by worker
    runtimes over telemetry) plus artifacts reaped in THIS process (the
    head's own pool/actor workers don't travel over telemetry)."""
    from .core import core_worker
    from .util import flight_recorder

    federated: List[Dict[str, Any]] = []
    rt = core_worker._global_runtime
    if rt is not None:
        try:
            federated = rt.control_plane.postmortems()
        except Exception:  # noqa: BLE001 — route must render partially
            pass
    return {
        "federated": federated,
        "local_paths": flight_recorder.list_postmortems(),
    }


def _objects_payload() -> Dict[str, Any]:
    """Federated object ledger: every live object across the cluster with
    size / location set / refcount / pin reason / age, plus the latest
    leak-sweep report (forced fresh so the API never serves a stale
    verdict about a leak the caller just created)."""
    from .core import core_worker, object_ledger

    rt = core_worker._global_runtime
    if rt is None:
        return {"generated_at": 0.0, "total_objects": 0, "total_bytes": 0,
                "objects": [], "nodes": {}, "leaks": [], "leak_counts": {}}
    object_ledger.sweep(rt)
    return object_ledger.collect_objects(rt)


def _flows_payload() -> Dict[str, Any]:
    """Per-edge transfer matrix: (src, dst, path) byte/transfer totals and
    window bandwidth, folded across the head and every node's federated
    metric snapshot."""
    from .core import core_worker, object_ledger

    return object_ledger.collect_flows(runtime=core_worker._global_runtime)


def _state_payload(what: str) -> Any:
    from .util import state

    if what == "nodes":
        return state.list_nodes()
    if what == "actors":
        return state.list_actors()
    if what == "jobs":
        return state.list_jobs()
    if what == "objects":
        return state.list_objects()
    if what == "summary":
        return state.summary()
    raise KeyError(what)


def _html_table(rows: List[Dict[str, Any]]) -> str:
    if not rows:
        return "<p><i>none</i></p>"
    cols = list(rows[0])
    head = "".join(f"<th>{html.escape(str(c))}</th>" for c in cols)
    body = "".join(
        "<tr>" + "".join(
            f"<td>{html.escape(str(r.get(c, '')))}</td>" for c in cols
        ) + "</tr>"
        for r in rows[:50]
    )
    return f"<table border=1 cellpadding=4><tr>{head}</tr>{body}</table>"


def _render_status_page() -> str:
    from .util import state

    s = state.summary()
    parts = [
        "<html><head><title>ray_tpu</title>",
        "<style>body{font-family:monospace;margin:2em}table{border-collapse:collapse}</style>",
        "</head><body><h1>ray_tpu session</h1>",
        f"<pre>{html.escape(json.dumps(s, indent=2, default=str))}</pre>",
        '<p><a href="/metrics">/metrics</a> (Prometheus)</p>',
    ]
    for what in ("nodes", "actors", "jobs"):
        try:
            rows = _state_payload(what)
        except Exception as e:  # noqa: BLE001 — page must render partially
            rows, parts = [], parts + [f"<p>{what}: error {html.escape(repr(e))}</p>"]
        parts.append(f"<h2>{what} ({len(rows)})</h2>")
        parts.append(_html_table(rows))
        parts.append(f'<p><a href="/api/v0/{what}">/api/v0/{what}</a></p>')
    parts.append("</body></html>")
    return "".join(parts)


_job_client_singleton = None


def _job_client():
    """Shared in-process job client for the REST routes (the dashboard
    runs in the head process, where the runtime lives)."""
    global _job_client_singleton
    if _job_client_singleton is None:
        from .job_submission import JobSubmissionClient

        _job_client_singleton = JobSubmissionClient()
    return _job_client_singleton


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> int:
    """Serve the dashboard; returns the bound port."""
    global _dash_server
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _json(self, code: int, payload) -> None:
            self._send(code, json.dumps(payload, default=str).encode(),
                       "application/json")

        def do_GET(self):
            try:
                if self.path in ("/", "/index.html"):
                    return self._send(
                        200, _render_status_page().encode(), "text/html"
                    )
                if self.path == "/metrics":
                    return self._send(
                        200, _render_metrics().encode(),
                        "text/plain; version=0.0.4",
                    )
                # trace lookup must outrank the generic /api/v0/<what>
                # state route
                if self.path.startswith("/api/v0/traces/"):
                    tid = self.path[len("/api/v0/traces/"):].strip("/")
                    return self._json(200, _trace_payload(tid))
                # health-plane surfaces (core/health.py) — like traces,
                # these must precede the generic state route
                if self.path.rstrip("/") == "/api/v0/health":
                    return self._json(200, _health_plane().payload())
                if self.path.rstrip("/") == "/api/v0/alerts":
                    plane = _health_plane()
                    return self._json(200, {"active": plane.active(),
                                            "history": plane.history()})
                if self.path.rstrip("/") == "/api/v0/postmortems":
                    return self._json(200, _postmortems_payload())
                # object plane (core/object_ledger.py) — the full ledger
                # body outranks the compact state route's "objects" rows
                if self.path.rstrip("/") == "/api/v0/objects":
                    return self._json(200, _objects_payload())
                if self.path.rstrip("/") == "/api/v0/flows":
                    return self._json(200, _flows_payload())
                # profiling plane: /api/v0/profile/<node>/<pid>?kind=...
                # (node "head"/"-" = the head's own driver node, pid 0 =
                # the node's agent process) — must precede the state route
                if (self.path.startswith("/api/v0/profile/")
                        or self.path.split("?")[0].rstrip("/")
                        == "/api/v0/profile"):
                    from urllib.parse import parse_qs, urlparse

                    parsed = urlparse(self.path)
                    rest = parsed.path[len("/api/v0/profile"):].strip("/")
                    return self._json(
                        200, _profile_payload(rest, parse_qs(parsed.query)))
                # job REST surface (reference: dashboard job module,
                # `dashboard/modules/job/job_head.py` HTTP routes)
                if self.path.startswith("/api/jobs/"):
                    rest = self.path[len("/api/jobs/"):].strip("/")
                    client = _job_client()
                    try:
                        if rest.endswith("/logs"):
                            job_id = rest[: -len("/logs")]
                            return self._json(
                                200, {"logs": client.get_job_logs(job_id)})
                        return self._json(
                            200, {"submission_id": rest,
                                  "status": client.get_job_status(rest)})
                    except ValueError as e:  # unknown job id -> 404, not 500
                        return self._json(404, {"error": str(e)})
                if self.path.startswith("/api/v0/"):
                    what = self.path[len("/api/v0/"):].strip("/")
                    payload = _state_payload(what)
                    return self._json(200, payload)
                return self._send(404, b'{"error": "not found"}',
                                  "application/json")
            except KeyError:
                return self._send(404, b'{"error": "unknown resource"}',
                                  "application/json")
            except Exception as e:  # noqa: BLE001 — serialized to client
                return self._json(500, {"error": repr(e)})

        def do_POST(self):
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                client = _job_client()
                if self.path in ("/api/jobs", "/api/jobs/"):
                    job_id = client.submit_job(
                        entrypoint=body["entrypoint"],
                        runtime_env=body.get("runtime_env"),
                        submission_id=body.get("submission_id"),
                        metadata=body.get("metadata"),
                    )
                    return self._json(200, {"submission_id": job_id})
                if self.path.startswith("/api/jobs/") and self.path.endswith("/stop"):
                    job_id = self.path[len("/api/jobs/"):-len("/stop")].strip("/")
                    return self._json(200, {"stopped": client.stop_job(job_id)})
                return self._send(404, b'{"error": "not found"}',
                                  "application/json")
            except Exception as e:  # noqa: BLE001
                return self._json(500, {"error": repr(e)})

    _dash_server = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=_dash_server.serve_forever, daemon=True,
                         name="dashboard")
    t.start()
    bound = _dash_server.server_address[1]
    logger.info("dashboard on http://%s:%d/", host, bound)
    return bound


def stop_dashboard() -> None:
    global _job_client_singleton
    _job_client_singleton = None  # never serve a dead runtime's handles
    global _dash_server
    if _dash_server is not None:
        _dash_server.shutdown()
        _dash_server.server_close()
        _dash_server = None
