"""Virtual multi-node cluster for tests.

Equivalent of the reference's in-process fake cluster (upstream ray
`python/ray/cluster_utils.py :: Cluster` used by `ray_start_cluster`
fixtures): many node agents in one OS process sharing a control plane, so
scheduling spread, node failure, object transfer and actor restart are
testable on one machine. TPU version: nodes can advertise topology-labelled
TPU resources and slice coordinates.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from .core import core_worker as _cw
from .core.core_worker import Runtime
from .core.ids import NodeID, SliceID
from .core.node_agent import NodeAgent


class Cluster:
    def __init__(self, initialize_head: bool = True, head_resources: Optional[Dict[str, float]] = None):
        self.runtime = Runtime()
        if initialize_head:
            self.head = self.runtime.add_node(
                resources=head_resources or {"CPU": 8.0}, is_head=True
            )
        _cw.set_runtime(self.runtime)

    def add_node(
        self,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        slice_id: Optional[SliceID] = None,
        topology_coords: Optional[Tuple[int, ...]] = None,
    ) -> NodeAgent:
        return self.runtime.add_node(
            resources=resources,
            labels=labels,
            slice_id=slice_id,
            topology_coords=topology_coords,
        )

    def add_slice(
        self,
        num_hosts: Optional[int] = None,
        chips_per_host: int = 4,
        generation: str = "v5e",
        topology_shape: Optional[Tuple[int, ...]] = None,
        extra_resources: Optional[Dict[str, float]] = None,
    ) -> SliceID:
        """Register a fake TPU slice: nodes sharing one SliceID, plus (when
        host ownership matches the generation's layout) an ICI topology
        registration so TopologyRequest placement groups can pack sub-boxes
        onto it.

        Give either ``num_hosts`` (chip grid shape derived near-cubic) or an
        explicit ``topology_shape`` (num_hosts derived from it).
        """
        from .sched.topology import (
            GENERATIONS,
            SliceInfo,
            SliceTopology,
            _default_shape,
        )

        gen = GENERATIONS[generation]
        if topology_shape is not None:
            shape = tuple(topology_shape)
            chips = 1
            for d in shape:
                chips *= d
            num_hosts = max(1, chips // chips_per_host)
        else:
            if num_hosts is None:
                raise ValueError("give num_hosts or topology_shape")
            shape = _default_shape(num_hosts * chips_per_host, gen.dims)

        slice_id = SliceID.generate()
        topo = SliceTopology(generation, shape)
        # Topology registration requires the generation's host layout AND a
        # uniform chip->host partition (ragged partitions from odd-dim shapes
        # would pin bundles bigger than any node advertises, leaving
        # topology requests queued forever).
        partition = topo.host_partition()
        register_topology = (
            chips_per_host == gen.chips_per_host
            and len(partition) == num_hosts
            and all(len(v) == chips_per_host for v in partition.values())
        )
        info = SliceInfo(slice_id=slice_id, topology=topo) if register_topology else None

        for h in range(num_hosts):
            resources = {"CPU": 8.0, "TPU": float(chips_per_host)}
            resources.update(extra_resources or {})
            agent = self.add_node(
                resources=resources,
                labels={"slice": slice_id.hex(), "host_index": str(h)},
                slice_id=slice_id,
                topology_coords=(h,),
            )
            if info is not None:
                info.hosts[h] = agent.node_id
        if info is not None:
            self.runtime.register_slice(info)
        return slice_id

    def remove_node(self, agent: NodeAgent) -> None:
        self.runtime.remove_node(agent.node_id)

    def shutdown(self) -> None:
        self.runtime.shutdown()
        if _cw.runtime_initialized() and _cw.get_runtime() is self.runtime:
            _cw.set_runtime(None)
