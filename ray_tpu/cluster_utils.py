"""Virtual multi-node cluster for tests.

Equivalent of the reference's in-process fake cluster (upstream ray
`python/ray/cluster_utils.py :: Cluster` used by `ray_start_cluster`
fixtures): many node agents in one OS process sharing a control plane, so
scheduling spread, node failure, object transfer and actor restart are
testable on one machine. TPU version: nodes can advertise topology-labelled
TPU resources and slice coordinates.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from .core import core_worker as _cw
from .core.core_worker import Runtime
from .core.ids import NodeID, SliceID
from .core.node_agent import NodeAgent


class Cluster:
    def __init__(self, initialize_head: bool = True, head_resources: Optional[Dict[str, float]] = None):
        self.runtime = Runtime()
        if initialize_head:
            self.head = self.runtime.add_node(
                resources=head_resources or {"CPU": 8.0}, is_head=True
            )
        _cw.set_runtime(self.runtime)

    def add_node(
        self,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        slice_id: Optional[SliceID] = None,
        topology_coords: Optional[Tuple[int, ...]] = None,
    ) -> NodeAgent:
        return self.runtime.add_node(
            resources=resources,
            labels=labels,
            slice_id=slice_id,
            topology_coords=topology_coords,
        )

    def add_slice(
        self,
        num_hosts: int,
        chips_per_host: int = 4,
        extra_resources: Optional[Dict[str, float]] = None,
    ) -> SliceID:
        """Register a fake TPU slice: num_hosts nodes sharing one SliceID."""
        slice_id = SliceID.generate()
        for h in range(num_hosts):
            resources = {"CPU": 8.0, "TPU": float(chips_per_host)}
            resources.update(extra_resources or {})
            self.add_node(
                resources=resources,
                labels={"slice": slice_id.hex(), "host_index": str(h)},
                slice_id=slice_id,
                topology_coords=(h,),
            )
        return slice_id

    def remove_node(self, agent: NodeAgent) -> None:
        self.runtime.remove_node(agent.node_id)

    def shutdown(self) -> None:
        self.runtime.shutdown()
        if _cw.runtime_initialized() and _cw.get_runtime() is self.runtime:
            _cw.set_runtime(None)
