"""Public task/actor API.

Equivalent of the reference's user-facing core API (upstream ray
`python/ray/_private/worker.py :: init/get/put/wait/remote`,
`python/ray/remote_function.py :: RemoteFunction`,
`python/ray/actor.py :: ActorClass/ActorHandle/ActorMethod`).
"""

from __future__ import annotations

import atexit
import functools
import inspect
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from .core import core_worker as _cw
from .core.config import config
from .core.control_plane import ActorState
from .core.core_worker import (
    GetTimeoutError,
    ObjectRef,
    ObjectRefGenerator,
    RayActorError,
    RayTaskError,
    Runtime,
)
from .core.ids import ActorID, NodeID, ObjectID, TaskID
from .core.logging import get_logger
from .core.task_spec import (
    TaskKind,
    TaskOptions,
    TaskSpec,
    TopologyRequest,
)

logger = get_logger("api")

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "broadcast",
    "kill",
    "get_actor",
    "cluster_resources",
    "available_resources",
    "ObjectRef",
    "ObjectRefGenerator",
    "RayTaskError",
    "RayActorError",
    "GetTimeoutError",
]


def init(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    system_config: Optional[Dict[str, Any]] = None,
    ignore_reinit_error: bool = True,
    resume_from: Optional[str] = None,
    address: Optional[str] = None,
    _existing_runtime: Optional[Runtime] = None,
) -> Runtime:
    """Start (or attach to) the runtime with one local node.

    On a real TPU host this discovers local devices and advertises them as
    TPU resources with topology labels (see ray_tpu.sched.topology).

    address: join an existing cluster head (its control-plane RPC address,
    ``host:port``) as a WORKER host: this process's NodeAgent registers with
    the head and executes tasks/actors the head's scheduler pushes to it
    (see ``ray_tpu.core.cross_host``). Returns the WorkerRuntime handle; the
    task-submission API stays with the head driver (single-controller).

    resume_from: path to a control-plane snapshot (see
    ``system_config={"control_plane_snapshot_path": ...}``); restores the
    KV/job tables and re-creates named actors from their pickled specs
    (`ray_tpu.core.persistence` documents the restore policy).
    """
    global _worker_runtime
    if address is not None:
        if _cw.runtime_initialized():
            raise RuntimeError("this process already hosts a head runtime; "
                               "init(address=...) joins as a worker")
        if _worker_runtime is not None and _worker_runtime.is_running:
            if ignore_reinit_error:
                return _worker_runtime
            raise RuntimeError("ray_tpu.init() called twice")
        config.apply_overrides(system_config)
        from .core.cross_host import join_cluster

        _worker_runtime = join_cluster(
            address, num_cpus=num_cpus, num_tpus=num_tpus, resources=resources
        )
        atexit.register(shutdown)
        return _worker_runtime
    if _cw.runtime_initialized():
        if ignore_reinit_error:
            return _cw.get_runtime()
        raise RuntimeError("ray_tpu.init() called twice")
    config.apply_overrides(system_config)
    if _existing_runtime is not None:
        _cw.set_runtime(_existing_runtime)
        return _existing_runtime
    rt = Runtime()
    rt.add_node(resources=default_node_resources(num_cpus, num_tpus, resources),
                is_head=True)
    _cw.set_runtime(rt)
    atexit.register(shutdown)
    if resume_from:
        from .core import persistence

        try:
            persistence.restore_into(rt, persistence.load_snapshot(resume_from))
        except Exception:
            shutdown()  # no half-initialized global runtime on failed restore
            raise
    if config.control_plane_snapshot_path:
        from .core.persistence import SnapshotWriter

        rt._snapshot_writer = SnapshotWriter(
            rt, config.control_plane_snapshot_path
        )
    if int(config.control_plane_shards) > 0:
        from .core.shard import enable_federation

        # shard the gossip planes (KV / pubsub) BEFORE serving the head:
        # attaching clients must only ever see the federated routing
        enable_federation(rt)
    if config.control_plane_rpc_port >= 0:
        from .core.cross_host import HeadService, enable_cross_host
        from .core.rpc import serve_control_plane

        # serve the full head surface (control plane + directory ops) and
        # accept worker-host joins (cross-host execution plane)
        rt._cp_server = serve_control_plane(
            HeadService(rt),
            host=config.control_plane_rpc_host,
            port=config.control_plane_rpc_port,
        )
        enable_cross_host(rt)
        # pool-worker children inherit the back-channel address (nested
        # submission from pool tasks; api._pool_worker_client)
        host, _, port = rt._cp_server.address.rpartition(":")
        if host in ("0.0.0.0", "::", ""):
            host = "127.0.0.1"
        os.environ["RAY_TPU_HEAD_ADDRESS"] = f"{host}:{port}"
    return rt


def default_node_resources(
    num_cpus: Optional[float],
    num_tpus: Optional[float],
    resources: Optional[Dict[str, float]],
) -> Dict[str, float]:
    """One resource-defaulting rule for every node this process hosts
    (head via init(), worker via init(address=...)): explicit resources
    win, CPU falls back to the host count, TPU to local chip detection."""
    node_resources = dict(resources or {})
    node_resources.setdefault(
        "CPU", num_cpus if num_cpus is not None else float(os.cpu_count() or 8))
    if num_tpus is None:
        num_tpus = _detect_local_tpu_chips()
    if num_tpus:
        node_resources.setdefault("TPU", float(num_tpus))
    return node_resources


def _detect_local_tpu_chips() -> float:
    """Count locally attached TPU chips without initializing a backend we
    don't need (reference analogue: `_private/accelerators/tpu.py ::
    TPUAcceleratorManager.get_current_node_num_accelerators`)."""
    try:
        import jax

        return float(len([d for d in jax.devices() if d.platform not in ("cpu",)]))
    except Exception:
        return 0.0


def shutdown() -> None:
    global _worker_runtime
    if _worker_runtime is not None:
        _worker_runtime.shutdown()
        _worker_runtime = None
        config.reset()
    if _cw.runtime_initialized():
        rt = _cw.get_runtime()
        if getattr(rt, "_cp_server", None) is not None:
            addr = os.environ.get("RAY_TPU_HEAD_ADDRESS", "")
            if addr.rpartition(":")[2] == rt._cp_server.address.rpartition(":")[2]:
                os.environ.pop("RAY_TPU_HEAD_ADDRESS", None)
        rt.shutdown()
        _cw.set_runtime(None)
        # init()-scoped system_config must not leak into the next runtime
        config.reset()


def is_initialized() -> bool:
    return _cw.runtime_initialized()


_worker_runtime = None  # WorkerRuntime when this process joined via address=


def _auto_init() -> Runtime:
    if not _cw.runtime_initialized():
        if _worker_runtime is not None:
            if _worker_runtime.is_running:
                # joined-host process: the API proxies to the head's
                # ownership tables (single-controller; core.worker_api)
                return _worker_runtime.api_client()
            # falling through to init() here would silently spin up a
            # phantom one-node head in a worker process, masking the
            # cluster death — fail loudly instead
            raise RuntimeError(
                "this process joined a cluster as a WORKER host and its "
                "runtime has shut down (head died or stop was requested); "
                "the API is unavailable. Re-join with init(address=...) "
                "once a head is reachable."
            )
        if os.environ.get("RAY_TPU_IN_POOL_WORKER"):
            client = _pool_worker_client()
            if client is not None:
                return client
            raise RuntimeError(
                "the ray_tpu API is not available inside worker processes "
                "(pool tasks / isolated actors) unless the cluster serves "
                "a control-plane RPC endpoint (the head back-channel). "
                "Start the head with system_config="
                "{'control_plane_rpc_port': 0} to enable nested submission, "
                "or return plain values; for an actor that must drive the "
                "runtime (spawn tasks/actors), create it with "
                "@ray_tpu.remote(in_process=True)."
            )
        init()
    return _cw.get_runtime()


_pool_client = None  # WorkerAPIClient inside a pool-worker subprocess
_pool_client_lock = __import__("threading").Lock()


def _pool_worker_client():
    """Lazy ownership back-channel for pool workers: the head address is
    inherited through the environment (set by the head's init() / a
    WorkerRuntime join); no address or unreachable head -> None and the
    caller raises the explanatory error."""
    global _pool_client
    addr = os.environ.get("RAY_TPU_HEAD_ADDRESS")
    if not addr:
        return None
    with _pool_client_lock:
        if (
            _pool_client is not None
            and _pool_client.is_alive
            and _pool_client.head_address == addr
        ):
            return _pool_client
        from .core.wire import WireError
        from .core.worker_api import WorkerAPIClient

        if _pool_client is not None:
            # dead connection (head restarted on the same port) or a new
            # head address: close the old client, or its socket + reader +
            # free threads leak once per runtime cycle
            _pool_client.close()
            _pool_client = None
        try:
            _pool_client = WorkerAPIClient(addr)
        except (OSError, WireError, RuntimeError) as e:
            # covers refused connects AND a reachable-but-dying head whose
            # server answers proxy_job_id with an error (RuntimeError)
            logger.warning("head back-channel %s unavailable: %s", addr, e)
            return None
        return _pool_client


# ---------------------------------------------------------------------------
# @remote
# ---------------------------------------------------------------------------


def _make_options(kwargs: Dict[str, Any]) -> TaskOptions:
    topo = kwargs.pop("topology", None)
    if topo is not None and not isinstance(topo, TopologyRequest):
        topo = TopologyRequest(tuple(topo))
    nr = kwargs.pop("num_returns", 1)
    if nr != "streaming" and not isinstance(nr, int):
        raise TypeError(f"num_returns must be an int or 'streaming', got {nr!r}")
    opts = TaskOptions(
        num_returns=nr,
        num_cpus=kwargs.pop("num_cpus", 1.0),
        num_tpus=kwargs.pop("num_tpus", 0.0),
        topology=topo,
        resources=kwargs.pop("resources", {}) or {},
        max_retries=kwargs.pop("max_retries", None),
        retry_exceptions=kwargs.pop("retry_exceptions", False),
        max_restarts=kwargs.pop("max_restarts", config.actor_max_restarts),
        max_task_retries=kwargs.pop("max_task_retries", 0),
        name=kwargs.pop("name", ""),
        scheduling_strategy=kwargs.pop("scheduling_strategy", None) or TaskOptions().scheduling_strategy,
        runtime_env=kwargs.pop("runtime_env", None),
        max_concurrency=kwargs.pop("max_concurrency", 1),
        in_process=kwargs.pop("in_process", None),
    )
    if kwargs:
        raise TypeError(f"unknown remote options: {sorted(kwargs)}")
    return opts


class RemoteFunction:
    def __init__(self, func, options: TaskOptions):
        self._func = func
        self._options = options
        functools.update_wrapper(self, func)

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        rt = _auto_init()
        task_id = TaskID.of()
        streaming = self._options.num_returns == "streaming"
        n = 0 if streaming else max(1, self._options.num_returns)
        from .util import tracing

        spec = TaskSpec(
            task_id=task_id,
            job_id=rt.job_id,
            kind=TaskKind.NORMAL,
            func=self._func,
            args=args,
            kwargs=kwargs,
            options=self._options,
            return_ids=[ObjectID.for_task_return(task_id, i) for i in range(n)],
            dependencies=_cw._collect_deps(args, kwargs),
            trace_ctx=tracing.current_context(),
        )
        if streaming:
            # generator task: refs stream back while it runs
            return rt.submit_streaming_task(spec)
        refs = rt.submit_task(spec)
        if self._options.num_returns == 1:
            return refs[0]
        return refs

    def options(self, **kwargs) -> "RemoteFunction":
        merged = _merge_options(self._options, kwargs)
        return RemoteFunction(self._func, merged)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._func.__name__} cannot be called directly; "
            f"use .remote()"
        )


def _merge_options(base: TaskOptions, kwargs: Dict[str, Any]) -> TaskOptions:
    import dataclasses

    fields = {f.name for f in dataclasses.fields(TaskOptions)}
    current = dataclasses.asdict(base)
    # asdict deep-copies; keep strategy/topology objects as-is
    current["scheduling_strategy"] = base.scheduling_strategy
    current["topology"] = base.topology
    for k, v in kwargs.items():
        if k == "topology" and v is not None and not isinstance(v, TopologyRequest):
            v = TopologyRequest(tuple(v))
        if k not in fields:
            raise TypeError(f"unknown option: {k}")
        current[k] = v
    return TaskOptions(**current)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def remote(self, *args, **kwargs):
        rt = _auto_init()
        opts = TaskOptions(
            num_cpus=0.0,
            num_returns=self._num_returns,
            max_task_retries=self._handle._max_task_retries,
            name=f"{self._handle._class_name}.{self._name}",
        )
        refs = rt.submit_actor_task(self._handle._actor_id, self._name, args, kwargs, opts)
        return refs[0] if self._num_returns == 1 else refs

    def options(self, num_returns: int = 1, **kwargs):
        if kwargs:
            raise TypeError(f"unsupported actor-method options: {sorted(kwargs)}")
        if not isinstance(num_returns, int):
            raise TypeError(
                "actor methods do not support streaming returns yet; "
                f"num_returns must be an int, got {num_returns!r}"
            )
        return ActorMethod(self._handle, self._name, num_returns)

    def bind(self, *args):
        """Bind into a compiled graph (see ray_tpu.dag)."""
        from .dag import MethodNode

        return MethodNode(self._handle, self._name, args)


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str, max_task_retries: int = 0):
        self._actor_id = actor_id
        self._class_name = class_name
        self._max_task_retries = max_task_retries

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:8]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name, self._max_task_retries))


class ActorClass:
    def __init__(self, cls, options: TaskOptions):
        self._cls = cls
        self._options = options

    def remote(self, *args, **kwargs) -> ActorHandle:
        rt = _auto_init()
        info = rt.create_actor(self._cls, args, kwargs, self._options)
        return ActorHandle(
            info.actor_id, self._cls.__name__, self._options.max_task_retries
        )

    def options(self, **kwargs) -> "ActorClass":
        return ActorClass(self._cls, _merge_options(self._options, kwargs))


def remote(*args, **kwargs):
    """``@remote`` decorator for functions and classes, with options."""
    if len(args) == 1 and not kwargs and (inspect.isfunction(args[0]) or inspect.isclass(args[0])):
        target = args[0]
        opts = TaskOptions()
        if inspect.isclass(target):
            opts.num_cpus = 1.0
            return ActorClass(target, opts)
        return RemoteFunction(target, opts)

    if args:
        raise TypeError("@remote accepts only keyword options")
    opts = _make_options(dict(kwargs))

    def decorator(target):
        if inspect.isclass(target):
            return ActorClass(target, opts)
        return RemoteFunction(target, opts)

    return decorator


# ---------------------------------------------------------------------------
# get / put / wait / kill
# ---------------------------------------------------------------------------


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *, timeout: Optional[float] = None):
    rt = _auto_init()
    if isinstance(refs, ObjectRef):
        return rt.get([refs], timeout=timeout)[0]
    batch = list(refs)
    for item in batch:
        if not isinstance(item, ObjectRef):
            # fail before any resolution starts: the batched path fans
            # refs over worker threads, where a mid-batch AttributeError
            # would surface as an opaque pool failure
            raise TypeError(
                f"get() expects ObjectRef(s), got {type(item).__name__}: "
                f"{item!r}")
    return rt.get(batch, timeout=timeout)


def put(value: Any) -> ObjectRef:
    rt = _auto_init()
    return rt.put(value)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    rt = _auto_init()
    return rt.wait(refs, num_returns=num_returns, timeout=timeout)


def broadcast(ref: ObjectRef, *, nodes: Optional[Sequence[Any]] = None,
              timeout: float = 120.0) -> dict:
    """Push one object to every node (or a `nodes` subset) ahead of
    demand, through the collective relay tree: pullers in each wave
    stream from each other's committed prefixes instead of all hammering
    the origin. Use before fan-out consumption — weight deployment,
    checkpoint restore, large shared inputs. Returns a summary dict with
    "warmed" (node id hexes now holding a replica) and "failed"
    ((node_hex, reason) pairs — per-node failures never raise)."""
    rt = _auto_init()
    return rt.broadcast(ref, nodes=nodes, timeout=timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    rt = _auto_init()
    rt.kill_actor(actor._actor_id, no_restart=no_restart)


def get_actor(name: str) -> ActorHandle:
    rt = _auto_init()
    info = rt.control_plane.get_named_actor(name)
    if info is None or info.state is ActorState.DEAD:
        raise ValueError(f"no live actor named {name!r}")
    return ActorHandle(info.actor_id, info.name or "Actor")


def _free(refs: Sequence[ObjectRef]) -> None:
    """Eagerly release objects AND their lineage records (reference:
    `ray._private.internal_api.free`). For intermediates that cascade-free
    only when a distant consumer drops its ref — all-to-all shuffle rounds
    — waiting for the cascade means peak residency ~= everything; callers
    that KNOW an object is consumed free it explicitly. Unreconstructable
    afterwards; never call on refs a user may still resolve."""
    rt = _auto_init()
    for ref in refs:
        try:
            rt.free_object(ref.object_id)
        except Exception:  # noqa: BLE001 — freeing is best-effort
            pass


def cluster_resources() -> Dict[str, float]:
    rt = _auto_init()
    totals: Dict[str, float] = {}
    for node in rt.control_plane.alive_nodes():
        for k, v in node.resources_total.items():
            totals[k] = totals.get(k, 0.0) + v
    return totals


def available_resources() -> Dict[str, float]:
    rt = _auto_init()
    totals: Dict[str, float] = {}
    for node in rt.control_plane.alive_nodes():
        for k, v in node.resources_available.items():
            totals[k] = totals.get(k, 0.0) + v
    return totals
