"""Autoscaler: resource-demand-driven slice provisioning.

Reference: `python/ray/autoscaler/_private/autoscaler.py ::
StandardAutoscaler` + `resource_demand_scheduler.py` + `node_provider.py`,
rebuilt v2-shaped (SURVEY.md §7.5: build only the instance-manager style
surface). TPU delta: the provisioning unit is a SLICE (host group with ICI
topology), not a single VM — matching the slice-is-the-failure-domain
design (§7.1.3).

NodeProvider is the pluggable boundary (reference's AWS/GCP/KubeRay
providers); FakeNodeProvider backs tests exactly like the reference's
fake_multi_node provider.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .core.logging import get_logger

logger = get_logger("autoscaler")


@dataclasses.dataclass
class NodeType:
    """A provisionable shape, e.g. one v5p-16 slice = 4 hosts x 4 chips."""

    name: str
    resources: Dict[str, float]  # per-node resources
    num_hosts: int = 1  # hosts per provisioned unit (slice granularity)
    max_workers: int = 10  # max provisioned units
    topology: Optional[str] = None  # e.g. "2x2x4"


class NodeProvider:
    """Pluggable cloud boundary."""

    def create_nodes(self, node_type: NodeType, count: int) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> Dict[str, str]:
        """-> {provider_node_id: node_type_name}"""
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Adds virtual nodes to the local Runtime (the reference's
    RAY_FAKE_CLUSTER / FakeMultiNodeProvider pattern)."""

    def __init__(self, runtime=None):
        from . import api

        self.runtime = runtime or api._auto_init()
        self._nodes: Dict[str, Any] = {}
        self._types: Dict[str, str] = {}
        self._counter = 0

    def create_nodes(self, node_type: NodeType, count: int) -> List[str]:
        out = []
        for _ in range(count):
            for _h in range(node_type.num_hosts):
                self._counter += 1
                pid = f"fake-{node_type.name}-{self._counter}"
                info = self.runtime.add_node(resources=dict(node_type.resources))
                self._nodes[pid] = info.node_id
                self._types[pid] = node_type.name
                out.append(pid)
        return out

    def terminate_node(self, node_id: str) -> None:
        nid = self._nodes.pop(node_id, None)
        self._types.pop(node_id, None)
        if nid is not None:
            self.runtime.remove_node(nid)

    def non_terminated_nodes(self) -> Dict[str, str]:
        return dict(self._types)


class Autoscaler:
    """Reconciles pending resource demand against provisioned capacity.

    Demand source: the scheduler's infeasible/pending queue (the reference
    reads the same from GCS resource load).
    """

    def __init__(
        self,
        node_types: List[NodeType],
        provider: NodeProvider,
        runtime=None,
        idle_timeout_s: float = 60.0,
        update_interval_s: float = 1.0,
    ):
        from . import api

        self.runtime = runtime or api._auto_init()
        self.runtime.autoscaling_enabled = True
        self.node_types = {t.name: t for t in node_types}
        self.provider = provider
        self.idle_timeout_s = idle_timeout_s
        self.update_interval_s = update_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._idle_since: Dict[str, float] = {}

    # -- demand → decisions --------------------------------------------------

    def pending_demand(self) -> List[Dict[str, float]]:
        return self.runtime.pending_resource_demand()

    def _fits(self, demand: Dict[str, float], resources: Dict[str, float]) -> bool:
        return all(resources.get(k, 0.0) >= v for k, v in demand.items())

    def _cluster_can_fit(self, demand: Dict[str, float]) -> bool:
        for node in self.runtime.control_plane.alive_nodes():
            if self._fits(demand, node.resources_available):
                return True
        return False

    def update(self) -> Dict[str, int]:
        """One reconcile pass. Returns {node_type: launched_count}."""
        launched: Dict[str, int] = {}
        demands = [d for d in self.pending_demand() if not self._cluster_can_fit(d)]
        by_type = self.provider.non_terminated_nodes()
        for demand in demands:
            for t in self.node_types.values():
                existing = sum(1 for v in by_type.values() if v == t.name)
                if existing >= t.max_workers:
                    continue
                if self._fits(demand, t.resources):
                    self.provider.create_nodes(t, 1)
                    launched[t.name] = launched.get(t.name, 0) + 1
                    by_type[f"_pending{len(by_type)}"] = t.name
                    break
        self._scale_down()
        return launched

    def _scale_down(self) -> None:
        """Terminate provider nodes idle (all resources free) past timeout."""
        now = time.monotonic()
        nodes_by_provider = self.provider.non_terminated_nodes()
        alive = {n.node_id: n for n in self.runtime.control_plane.alive_nodes()}
        for pid in list(nodes_by_provider):
            nid = getattr(self.provider, "_nodes", {}).get(pid)
            node = alive.get(nid) if nid is not None else None
            idle = node is not None and node.resources_available == node.resources_total
            if idle and not self.pending_demand():
                since = self._idle_since.setdefault(pid, now)
                if now - since > self.idle_timeout_s:
                    logger.info("terminating idle node %s", pid)
                    self.provider.terminate_node(pid)
                    self._idle_since.pop(pid, None)
            else:
                self._idle_since.pop(pid, None)

    # -- loop ----------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.update()
            except Exception:
                logger.warning("autoscaler update failed", exc_info=True)
            self._stop.wait(self.update_interval_s)

    def stop(self) -> None:
        self._stop.set()
