"""Autoscaler: resource-demand-driven slice provisioning.

Reference: `python/ray/autoscaler/_private/autoscaler.py ::
StandardAutoscaler` + `resource_demand_scheduler.py` + `node_provider.py`,
rebuilt v2-shaped (SURVEY.md §7.5: build only the instance-manager style
surface). TPU delta: the provisioning unit is a SLICE (host group with ICI
topology), not a single VM — matching the slice-is-the-failure-domain
design (§7.1.3).

NodeProvider is the pluggable boundary (reference's AWS/GCP/KubeRay
providers); FakeNodeProvider backs tests exactly like the reference's
fake_multi_node provider.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .core.config import config
from .core.logging import get_logger

logger = get_logger("autoscaler")


@dataclasses.dataclass
class NodeType:
    """A provisionable shape, e.g. one v5p-16 slice = 4 hosts x 4 chips."""

    name: str
    resources: Dict[str, float]  # per-node resources
    num_hosts: int = 1  # hosts per provisioned unit (slice granularity)
    max_workers: int = 10  # max provisioned units
    topology: Optional[str] = None  # e.g. "2x2x4"


class NodeProvider:
    """Pluggable cloud boundary."""

    def create_nodes(self, node_type: NodeType, count: int) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> Dict[str, str]:
        """-> {provider_node_id: node_type_name}"""
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Adds virtual nodes to the local Runtime (the reference's
    RAY_FAKE_CLUSTER / FakeMultiNodeProvider pattern)."""

    def __init__(self, runtime=None):
        from . import api

        self.runtime = runtime or api._auto_init()
        self._nodes: Dict[str, Any] = {}
        self._types: Dict[str, str] = {}
        self._counter = 0

    def create_nodes(self, node_type: NodeType, count: int) -> List[str]:
        out = []
        for _ in range(count):
            for _h in range(node_type.num_hosts):
                self._counter += 1
                pid = f"fake-{node_type.name}-{self._counter}"
                info = self.runtime.add_node(resources=dict(node_type.resources))
                self._nodes[pid] = info.node_id
                self._types[pid] = node_type.name
                out.append(pid)
        return out

    def terminate_node(self, node_id: str) -> None:
        nid = self._nodes.pop(node_id, None)
        self._types.pop(node_id, None)
        if nid is not None:
            self.runtime.remove_node(nid)

    def non_terminated_nodes(self) -> Dict[str, str]:
        return dict(self._types)


class SubprocessNodeProvider(NodeProvider):
    """Provisions REAL worker runtimes: each node is an OS process that
    joins the head over the cross-host execution plane (core/cross_host.py,
    `init(address=...)`) and executes dispatched tasks/actors.

    This is the executable shape of the reference's provider matrix
    (`autoscaler/_private/node_provider.py` implementations): swap the
    subprocess spawn for a cloud API call and the rest of the loop is
    unchanged. Demand-driven scale-up launches a joiner; idle scale-down
    stops it through the head's dispatch channel (worker exits cleanly).
    """

    def __init__(self, runtime=None, extra_env: Optional[Dict[str, str]] = None):
        from . import api

        self.runtime = runtime or api._auto_init()
        cp_server = getattr(self.runtime, "_cp_server", None)
        if cp_server is None:
            raise RuntimeError(
                "SubprocessNodeProvider needs a joinable head: init with "
                "system_config={'control_plane_rpc_port': 0}"
            )
        self.head_address = cp_server.address
        self.extra_env = dict(extra_env or {})
        self._procs: Dict[str, Any] = {}  # provider id -> Popen
        self._types: Dict[str, str] = {}
        self._nodes: Dict[str, Any] = {}  # provider id -> NodeID (lazy)
        self._counter = 0

    def create_nodes(self, node_type: NodeType, count: int) -> List[str]:
        import os
        import subprocess
        import sys
        import textwrap

        out = []
        for _ in range(count):
            for _h in range(node_type.num_hosts):
                self._counter += 1
                pid = f"sub-{node_type.name}-{self._counter}"
                code = textwrap.dedent(f"""
                    from ray_tpu.core.cross_host import join_cluster
                    w = join_cluster(
                        {self.head_address!r},
                        num_cpus={node_type.resources.get("CPU", 1.0)},
                        num_tpus={node_type.resources.get("TPU", 0.0)},
                        resources={ {k: v for k, v in node_type.resources.items()
                                     if k not in ("CPU", "TPU")} !r},
                        labels={{"provider_node_id": {pid!r}}},
                    )
                    w.wait()
                """)
                env = dict(os.environ)
                env.setdefault("JAX_PLATFORMS", "cpu")
                env.pop("PALLAS_AXON_POOL_IPS", None)
                env.update(self.extra_env)
                proc = subprocess.Popen([sys.executable, "-c", code], env=env)
                self._procs[pid] = proc
                self._types[pid] = node_type.name
                out.append(pid)
                logger.info("provisioned worker %s (pid %d) joining %s",
                            pid, proc.pid, self.head_address)
        return out

    def _resolve_node_id(self, pid: str):
        nid = self._nodes.get(pid)
        if nid is not None:
            return nid
        for node in self.runtime.control_plane.alive_nodes():
            if node.labels.get("provider_node_id") == pid:
                self._nodes[pid] = node.node_id
                return node.node_id
        return None

    def terminate_node(self, node_id: str) -> None:
        nid = self._nodes.get(node_id) or self._resolve_node_id(node_id)
        proc = self._procs.pop(node_id, None)
        self._types.pop(node_id, None)
        self._nodes.pop(node_id, None)
        graceful = nid is not None and nid in self.runtime.agents
        if graceful:
            # deliberate scale-down: notify so the worker exits instead of
            # treating the lost head connection as a restart and rejoining
            self.runtime.remove_node(nid, notify=True)
        if proc is not None:
            try:
                # short grace only when the worker was actually told to
                # exit; a not-yet-joined worker has nothing to hear
                proc.wait(timeout=5 if graceful else 0.1)
            except Exception:  # noqa: BLE001 — escalate
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except Exception:  # noqa: BLE001 — last resort, and reap
                    proc.kill()
                    proc.wait(timeout=5)

    def non_terminated_nodes(self) -> Dict[str, str]:
        # reap silently-died joiners so the scaler re-launches capacity
        for pid, proc in list(self._procs.items()):
            if proc.poll() is not None:
                logger.warning("provisioned worker %s exited rc=%s",
                               pid, proc.returncode)
                self._procs.pop(pid, None)
                self._types.pop(pid, None)
                self._nodes.pop(pid, None)
        # refresh the NodeID mapping (used by idle scale-down) from ONE
        # alive-nodes snapshot rather than one scan per unresolved pid
        unresolved = [p for p in self._types if p not in self._nodes]
        if unresolved:
            by_label = {
                n.labels.get("provider_node_id"): n.node_id
                for n in self.runtime.control_plane.alive_nodes()
            }
            for pid in unresolved:
                nid = by_label.get(pid)
                if nid is not None:
                    self._nodes[pid] = nid
        return dict(self._types)


class TPUVMNodeProvider(NodeProvider):
    """Provisions TPU-VM slices through the GCP TPU API (reference:
    `autoscaler/_private/gcp/node_provider.py` + its TPU-pod support;
    v2 instance-manager shape per SURVEY §7.5).

    The cloud boundary is an injectable `api_client` with the gcloud
    surface this provider drives:

        create_tpu_vm(name, accelerator_type, zone, startup_script) -> op
        delete_tpu_vm(name, zone) -> op
        list_tpu_vms(zone) -> [{"name", "state", "accelerator_type"}]

    A real deployment passes a thin wrapper over
    `google.cloud.tpu_v2.TpuClient` (or `gcloud compute tpus tpu-vm`);
    tests pass a mock that records the calls — and can "boot" the VM by
    executing the startup script locally, which is exactly what a fresh
    TPU-VM does: `ray-tpu start --address <head>` joins the cross-host
    plane and the rest of the autoscaler loop is provider-agnostic.

    NodeType.topology (e.g. "2x2x4") selects the accelerator_type; one
    create call provisions the whole slice (the TPU API's granularity is
    the slice, matching slice-is-the-failure-domain, SURVEY §7.1.3)."""

    STATE_PENDING = ("CREATING", "STARTING", "PROVISIONING")
    STATE_READY = ("READY", "ACTIVE")

    def __init__(self, head_address: str, api_client, zone: str,
                 name_prefix: str = "ray-tpu"):
        self.head_address = head_address
        self.api = api_client
        self.zone = zone
        self.name_prefix = name_prefix
        self._types: Dict[str, str] = {}  # vm name -> node_type.name
        self._counter = 0

    # -- the exact strings a real TPU-VM boots with -------------------------
    def _accelerator_type(self, node_type: NodeType) -> str:
        if node_type.topology:
            chips = 1
            for d in node_type.topology.split("x"):
                chips *= int(d)
            gen = node_type.resources.get("tpu_generation", "v5p")
            gen = gen if isinstance(gen, str) else "v5p"
            return f"{gen}-{chips}"
        return f"v5litepod-{int(node_type.resources.get('TPU', 1))}"

    def _startup_script(self, node_type: NodeType, vm_name: str) -> str:
        extra = {k: v for k, v in node_type.resources.items()
                 if k not in ("CPU", "TPU", "tpu_generation")}
        return (
            "#!/bin/bash\n"
            "# every host of the slice joins the head's cross-host plane\n"
            f"ray-tpu start --address {self.head_address} "
            f"--num-cpus {node_type.resources.get('CPU', 1)} "
            f"--resources '{extra!r}' "
            f"--labels provider_node_id={vm_name}\n"
        )

    # -- NodeProvider surface ----------------------------------------------
    def create_nodes(self, node_type: NodeType, count: int) -> List[str]:
        out = []
        for _ in range(count):
            self._counter += 1
            name = f"{self.name_prefix}-{node_type.name}-{self._counter}"
            self.api.create_tpu_vm(
                name=name,
                accelerator_type=self._accelerator_type(node_type),
                zone=self.zone,
                startup_script=self._startup_script(node_type, name),
            )
            self._types[name] = node_type.name
            out.append(name)
            logger.info("requested TPU-VM %s (%s) in %s", name,
                        self._accelerator_type(node_type), self.zone)
        return out

    def terminate_node(self, node_id: str) -> None:
        self._types.pop(node_id, None)
        self.api.delete_tpu_vm(name=node_id, zone=self.zone)

    def non_terminated_nodes(self) -> Dict[str, str]:
        live = {}
        for vm in self.api.list_tpu_vms(zone=self.zone):
            state = str(vm.get("state", "")).upper()
            if state in self.STATE_PENDING or state in self.STATE_READY:
                name = vm["name"]
                if name in self._types:
                    live[name] = self._types[name]
        # forget VMs the cloud no longer reports (preempted/deleted out
        # of band) so the scaler re-launches the capacity
        for name in list(self._types):
            if name not in live:
                self._types.pop(name, None)
        return live


class Autoscaler:
    """Reconciles pending resource demand against provisioned capacity.

    Demand sources: the scheduler's infeasible/pending queue (the
    reference reads the same from GCS resource load), plus — when a
    health plane is attached — the demand hints carried by firing alert
    rules (core/health.py `Rule.demand`): e.g. a sustained
    `serve_disagg_queue_depth{role=decode}` breach can ask for another
    decode-capable node before the pending queue ever backs up.
    """

    def __init__(
        self,
        node_types: List[NodeType],
        provider: NodeProvider,
        runtime=None,
        idle_timeout_s: float = 60.0,
        update_interval_s: float = 1.0,
        health_plane=None,
    ):
        from . import api

        self.runtime = runtime or api._auto_init()
        self.health_plane = health_plane
        self.runtime.autoscaling_enabled = True
        self.node_types = {t.name: t for t in node_types}
        self.provider = provider
        self.idle_timeout_s = idle_timeout_s
        self.update_interval_s = update_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._idle_since: Dict[str, float] = {}
        # capacity launched but not yet joined: absorbs repeat demand so a
        # slow-joining node (SubprocessNodeProvider: seconds) isn't
        # re-launched every tick. Entries expire after launch_grace_s —
        # a joiner that never arrives is eventually retried.
        self.launch_grace_s = 30.0
        self._launching: List[tuple] = []  # (monotonic_ts, remaining_cap)
        # hysteresis: launches only happen outside the cooldown window
        # that the previous scale-up wave opened, and one pass may take
        # at most autoscale_step_max launch actions — so a burst of
        # alerts produces ONE bounded wave, not one node per alert
        self._last_wave_ts = float("-inf")

    # -- demand → decisions --------------------------------------------------

    def pending_demand(self) -> List[Dict[str, float]]:
        demands = list(self.runtime.pending_resource_demand())
        if self.health_plane is not None:
            try:
                demands.extend(self.health_plane.pending_demand())
            except Exception:  # noqa: BLE001 — health hints are advisory
                logger.warning("health-plane demand read failed",
                               exc_info=True)
        return demands

    def _fits(self, demand: Dict[str, float], resources: Dict[str, float]) -> bool:
        return all(resources.get(k, 0.0) >= v for k, v in demand.items())

    def _cluster_can_fit(self, demand: Dict[str, float]) -> bool:
        for node in self.runtime.control_plane.alive_nodes():
            if self._fits(demand, node.resources_available):
                return True
        return False

    def update(self) -> Dict[str, int]:
        """One reconcile pass. Returns {node_type: launched_count}."""
        launched: Dict[str, int] = {}
        demands = [d for d in self.pending_demand() if not self._cluster_can_fit(d)]
        by_type = self.provider.non_terminated_nodes()
        # In-flight launch capacity absorbs repeat demand (bin-packing-
        # lite, the reference's resource_demand_scheduler shape): a
        # 2-member gang provisions ONE fitting node, and a node still
        # JOINING (async providers) isn't re-launched every tick. A fresh
        # copy of each unexpired cap is spent per pass — the same pending
        # demand re-absorbs into it next tick instead of draining it.
        now = time.monotonic()
        alive_ids = {n.node_id for n in self.runtime.control_plane.alive_nodes()}
        # retire a launch entry as soon as SOME node that wasn't alive at
        # launch time joins (one join clears one entry, oldest first);
        # grace expiry covers joiners that die before registering
        assigned: set = set()
        kept = []
        for ts, cap, known in sorted(self._launching, key=lambda e: e[0]):
            new = alive_ids - known - assigned
            if new:
                assigned.add(next(iter(new)))
                continue
            if now - ts < self.launch_grace_s:
                kept.append((ts, cap, known))
        self._launching = kept
        pending_caps: List[Dict[str, float]] = [
            dict(cap) for _ts, cap, _known in self._launching
        ]
        cooldown_s = float(config.get("autoscale_cooldown_s"))
        step_max = max(1, int(config.get("autoscale_step_max")))
        in_cooldown = now - self._last_wave_ts < cooldown_s
        steps = deferred = 0
        for demand in demands:
            absorbed = False
            for cap in pending_caps:
                if self._fits(demand, cap):
                    for k, v in demand.items():
                        cap[k] = cap.get(k, 0.0) - v
                    absorbed = True
                    break
            if absorbed:
                continue
            if in_cooldown or steps >= step_max:
                deferred += 1
                continue
            for t in self.node_types.values():
                existing = sum(1 for v in by_type.values() if v == t.name)
                if existing >= t.max_workers:
                    continue
                if self._fits(demand, t.resources):
                    self.provider.create_nodes(t, 1)
                    launched[t.name] = launched.get(t.name, 0) + 1
                    steps += 1
                    by_type[f"_pending{len(by_type)}"] = t.name
                    cap = dict(t.resources)
                    for k, v in demand.items():
                        cap[k] = cap.get(k, 0.0) - v
                    pending_caps.append(cap)
                    self._launching.append((now, dict(t.resources), set(alive_ids)))
                    break
        if steps:
            self._last_wave_ts = now
        if deferred:
            logger.info(
                "deferred %d unabsorbed demand(s): %s", deferred,
                "inside autoscale_cooldown_s window" if in_cooldown
                else "autoscale_step_max reached this pass")
        self._scale_down()
        return launched

    def _scale_down(self) -> None:
        """Terminate provider nodes idle (all resources free) past timeout."""
        now = time.monotonic()
        nodes_by_provider = self.provider.non_terminated_nodes()
        alive = {n.node_id: n for n in self.runtime.control_plane.alive_nodes()}
        for pid in list(nodes_by_provider):
            nid = getattr(self.provider, "_nodes", {}).get(pid)
            node = alive.get(nid) if nid is not None else None
            idle = node is not None and node.resources_available == node.resources_total
            if idle and not self.pending_demand():
                since = self._idle_since.setdefault(pid, now)
                if now - since > self.idle_timeout_s:
                    logger.info("terminating idle node %s", pid)
                    self.provider.terminate_node(pid)
                    self._idle_since.pop(pid, None)
            else:
                self._idle_since.pop(pid, None)

    # -- loop ----------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.update()
            except Exception:
                logger.warning("autoscaler update failed", exc_info=True)
            self._stop.wait(self.update_interval_s)

    def stop(self) -> None:
        self._stop.set()
