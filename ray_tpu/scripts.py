"""Operator CLI: ``ray-tpu start|status|list|submit|logs|serve|memory|
timeline|bench|microbenchmark``.

Reference analogue: `python/ray/scripts/scripts.py`. Three ways to reach
a runtime:

- ``--address host:port`` attaches to a LIVE session's control-plane RPC
  (``ray-tpu start`` serves it; status/list/logs --follow work remotely).
- ``--snapshot path`` reads a persisted control-plane snapshot from a
  possibly-dead runtime.
- neither: commands run against a fresh in-process runtime (``submit``
  supervises the entrypoint as a job; ``serve run`` deploys and blocks;
  ``start`` boots the long-lived session: snapshots, metrics, RPC, log
  publishing).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict, List


def _print_rows(rows: List[Dict[str, Any]], columns: List[str]) -> None:
    if not rows:
        print("(none)")
        return
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows)) for c in columns}
    print("  ".join(c.upper().ljust(widths[c]) for c in columns))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in columns))


def _sum_resources(nodes) -> Dict[str, float]:
    acc: Dict[str, float] = {}
    for n in nodes:
        for k, v in n.resources_total.items():
            acc[k] = acc.get(k, 0.0) + v
    return acc


def _remote_cp(address: str):
    from ray_tpu.core.rpc import RemoteControlPlane

    return RemoteControlPlane(address)


def cmd_status(args) -> int:
    if args.address:
        cp = _remote_cp(args.address)
        nodes = cp.alive_nodes()
        actors = cp.list_actors()
        jobs = cp.list_jobs()
        print(json.dumps({
            "address": args.address,
            "nodes_alive": len(nodes),
            "actors": len(actors),
            "jobs": len(jobs),
            "cluster_resources": _sum_resources(nodes),
        }, indent=2, default=str))
        cp.close()
        return 0
    if args.snapshot:
        from ray_tpu.core import persistence

        snap = persistence.load_snapshot(args.snapshot)
        age = time.time() - snap.get("time", 0)
        print(f"snapshot: {args.snapshot} (written {age:.0f}s ago)")
        print(f"  kv entries:    {len(snap.get('kv', {}))}")
        print(f"  jobs:          {len(snap.get('jobs', {}))}")
        print(f"  named actors:  {sorted(snap.get('named_actors', {}))}")
        print(f"  nodes:         {len(snap.get('nodes', []))}")
        print(f"  objects:       {len(snap.get('objects', []))}")
        return 0
    import ray_tpu
    from ray_tpu.util import state

    ray_tpu.init()
    s = state.summary()
    print(json.dumps(s, indent=2, default=str))
    return 0


def cmd_list(args) -> int:
    if args.address:
        cp = _remote_cp(args.address)
        if args.what == "nodes":
            rows = [{"node_id": n.node_id.hex()[:16], "state": n.state.value,
                     "resources": n.resources_total} for n in cp.all_nodes()]
            _print_rows(rows, ["node_id", "state", "resources"])
        elif args.what == "actors":
            rows = [{"actor_id": a.actor_id.hex()[:16], "name": a.name,
                     "class": a.class_name, "state": a.state.value}
                    for a in cp.list_actors()]
            _print_rows(rows, ["actor_id", "name", "class", "state"])
        elif args.what == "jobs":
            rows = [{"job_id": j.hex()[:16], **{k: v for k, v in m.items()
                     if isinstance(v, (str, int, float))}}
                    for j, m in cp.list_jobs().items()]
            _print_rows(rows, ["job_id", "state"])
        else:
            print("objects are node-local; not served over the control plane")
        cp.close()
        return 0
    if args.snapshot:
        from ray_tpu.core import persistence

        snap = persistence.load_snapshot(args.snapshot)
        if args.what == "jobs":
            rows = [{"job_id": j, **m} for j, m in snap.get("jobs", {}).items()]
            _print_rows(rows, ["job_id", "state", "death_cause"])
        elif args.what == "actors":
            rows = [
                {"name": n, "class": e.get("class_name", "")}
                for n, e in snap.get("named_actors", {}).items()
            ]
            _print_rows(rows, ["name", "class"])
        elif args.what == "nodes":
            _print_rows(snap.get("nodes", []), ["node_id", "state", "resources"])
        else:
            print("\n".join(snap.get("objects", [])) or "(none)")
        return 0
    import ray_tpu
    from ray_tpu.util import state

    ray_tpu.init()
    fn = {
        "nodes": state.list_nodes,
        "actors": state.list_actors,
        "jobs": state.list_jobs,
        "objects": state.list_objects,
    }[args.what]
    rows = fn(limit=args.limit)
    cols = list(rows[0].keys()) if rows else []
    _print_rows(rows, cols)
    return 0


def cmd_submit(args) -> int:
    import ray_tpu
    from ray_tpu.job_submission import JobSubmissionClient

    import shlex

    ray_tpu.init()
    client = JobSubmissionClient()
    entrypoint = shlex.join(args.entrypoint)  # preserve argv quoting
    job_id = client.submit_job(entrypoint=entrypoint)
    print(f"job {job_id} submitted: {entrypoint}", file=sys.stderr)
    status = client.wait_until_finish(job_id, timeout_s=args.timeout)
    logs = client.get_job_logs(job_id)
    if logs:
        sys.stdout.write(logs)
    print(f"job {job_id}: {status}", file=sys.stderr)
    return 0 if status == "SUCCEEDED" else 1


def cmd_start(args) -> int:
    import ray_tpu
    from ray_tpu.util import state

    if getattr(args, "address", None):
        # worker mode: join the head and serve dispatched tasks until the
        # head stops us (or dies)
        system_config = (
            {"node_host": args.node_host} if args.node_host else None
        )
        worker = ray_tpu.init(
            address=args.address, num_cpus=args.num_cpus,
            num_tpus=args.num_tpus, system_config=system_config,
        )
        print(f"joined {args.address} as node {worker.node_id.hex()[:8]} "
              f"({worker.info.resources_total})")
        try:
            worker.wait()
        except KeyboardInterrupt:
            print("shutting down worker")
            worker.shutdown()
        return 0

    system_config: Dict[str, Any] = {"control_plane_rpc_port": args.rpc_port}
    if args.snapshot:
        system_config["control_plane_snapshot_path"] = args.snapshot
    rt = ray_tpu.init(
        system_config=system_config,
        resume_from=args.resume_from,
    )
    port = state.start_metrics_server(port=args.metrics_port)
    print(f"ray-tpu session up: metrics http://127.0.0.1:{port}/metrics")
    from ray_tpu.core.log_monitor import LogMonitor

    # publish session logs to the control-plane pubsub so remote shells can
    # `ray-tpu logs --follow --address …`; silent locally (sink drops)
    LogMonitor(sink=lambda record: None,
               pubsub=rt.control_plane.pubsub).start()
    cp_server = getattr(rt, "_cp_server", None)
    if cp_server is not None:
        print(f"  control-plane RPC: {cp_server.address} "
              f"(attach: ray-tpu status --address {cp_server.address})")
    res = rt.control_plane.alive_nodes()
    for n in res:
        print(f"  node {n.node_id.hex()[:8]}: {n.resources_total}")
    if args.serve_app:
        module, _, attr = args.serve_app.partition(":")
        import importlib

        from ray_tpu import serve

        app = getattr(importlib.import_module(module), attr or "app")
        serve.run(app)
        print(f"  serve app '{args.serve_app}' at port {serve.http_port()}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("shutting down")
    return 0


def cmd_logs(args) -> int:
    """Session log access: list files, tail one, or follow the live stream
    of an attached session (reference: `ray logs` + the log monitor's
    driver echo)."""
    from ray_tpu.core.log_monitor import (
        LOG_CHANNEL,
        list_log_files,
        tail_log_file,
    )

    if args.follow:
        import threading

        if not args.address:
            print("logs --follow needs --address (a live session's RPC)",
                  file=sys.stderr)
            return 2
        client = _remote_cp(args.address)
        done = threading.Event()

        def on_record(record):
            pid = f" pid={record['pid']}" if record.get("pid") else ""
            print(f"({record['file']}{pid}) {record['line']}", flush=True)

        client.subscribe(LOG_CHANNEL, on_record)
        print(f"following logs from {args.address} (ctrl-c to stop)",
              file=sys.stderr)
        try:
            while not done.wait(1.0):
                pass
        except KeyboardInterrupt:
            pass
        finally:
            client.close()
        return 0

    if args.file:
        try:
            for line in tail_log_file(args.file, n=args.lines,
                                      directory=args.log_dir):
                print(line)
        except OSError as e:
            print(f"cannot read {args.file}: {e}", file=sys.stderr)
            return 1
        return 0

    files = list_log_files(args.log_dir)
    if not files:
        print("no session logs found (is a session running on this host?)")
        return 0
    _print_rows(files, ["file", "bytes", "mtime"])
    return 0


def cmd_memory(args) -> int:
    """Object-plane introspection (reference: `ray memory`), federated
    over the cluster ledger: every live object with size / location set /
    refcount / pin reason / age, top-N by size. `--group-by reason|node`
    aggregates instead; `--leaks` runs the leak sweep and prints what it
    flagged. `--snapshot` still lists object ids from a persisted
    control-plane snapshot of a dead runtime."""
    if args.snapshot:
        from ray_tpu.core import persistence

        snap = persistence.load_snapshot(args.snapshot)
        oids = snap.get("objects", [])
        print("\n".join(oids) or "(none)")
        print(f"\ntotal: {len(oids)} objects (snapshot)")
        return 0
    import ray_tpu
    from ray_tpu.core import object_ledger

    rt = ray_tpu.init()
    report = object_ledger.sweep(rt, force=True)
    body = object_ledger.collect_objects(rt, limit=max(args.limit, 10_000))
    rows = body["objects"]

    if args.leaks:
        leak_rows = [{
            "kind": l.get("kind", ""),
            "object_id": l.get("object_id", "")[:16],
            "node_id": l.get("node_id", ""),
            "size_bytes": l.get("size_bytes", 0),
            "age_s": l.get("age_s", 0.0),
            "detail": l.get("detail", ""),
        } for l in report.get("leaks", [])]
        _print_rows(leak_rows, ["kind", "object_id", "node_id",
                                "size_bytes", "age_s", "detail"])
        counts = report.get("counts", {})
        print(f"\nleaks: {sum(counts.values())} "
              + " ".join(f"{k}={v}" for k, v in sorted(counts.items())))
        return 0

    if args.group_by:
        key = {"reason": "pin_reason", "node": "node_id"}[args.group_by]
        groups: Dict[str, Dict[str, Any]] = {}
        for r in rows:
            g = groups.setdefault(str(r.get(key, "") or "(none)"),
                                  {args.group_by: str(r.get(key, "") or "(none)"),
                                   "objects": 0, "bytes": 0})
            g["objects"] += 1
            g["bytes"] += int(r.get("size_bytes", 0) or 0)
        grows = sorted(groups.values(), key=lambda g: g["bytes"], reverse=True)
        _print_rows(grows, [args.group_by, "objects", "bytes"])
    else:
        view = [{
            "object_id": r.get("object_id", "")[:16],
            "size_bytes": r.get("size_bytes", 0),
            "node_id": r.get("node_id", ""),
            "store": r.get("store", ""),
            "locations": ",".join(r.get("locations", [])) or "-",
            "refcount": r.get("refcount", 0),
            "pin_reason": r.get("pin_reason", "") or "-",
            "age_s": round(float(r.get("age_s", 0.0)), 1),
            "creator_task": r.get("creator_task", "") or "-",
        } for r in rows[:args.limit]]
        _print_rows(view, ["object_id", "size_bytes", "node_id", "store",
                           "locations", "refcount", "pin_reason", "age_s",
                           "creator_task"])
    counts = report.get("counts", {})
    print(f"\ntotal: {body['total_objects']} objects, "
          f"{body['total_bytes']} bytes across "
          f"{len(body['nodes'])} node store(s); "
          f"leaks flagged: {sum(counts.values())} (--leaks for detail)")
    return 0


def cmd_serve_run(args) -> int:
    """Run serve apps in the foreground from a YAML/JSON config or an
    import path (reference: `serve run` / `serve deploy` config shape)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.schema import ServeConfigSchema, apply

    ray_tpu.init()
    target = args.config_or_import_path
    if target.endswith((".yaml", ".yml", ".json")):
        config = ServeConfigSchema.load(target)
        if args.http_port:
            config.http_port = args.http_port
        status = apply(config)
    else:
        import importlib

        module, _, attr = target.partition(":")
        app = getattr(importlib.import_module(module), attr or "app")
        serve.run(app, http_port=args.http_port)
        status = serve.status()
    if getattr(args, "grpc_port", None) is not None:
        port = serve.start_grpc(port=args.grpc_port)
        print(f"gRPC ingress on 127.0.0.1:{port}", file=sys.stderr)
    print(json.dumps(status, indent=2, default=str))
    print(f"serving on http://127.0.0.1:{serve.http_port()} (ctrl-c to stop)",
          file=sys.stderr)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        serve.shutdown()
    return 0


def cmd_microbenchmark(args) -> int:
    from ray_tpu.microbenchmark import run_all

    run_all()
    return 0


def cmd_timeline(args) -> int:
    if args.events_dir:
        # merge per-session dumps (written on runtime shutdown when
        # system_config event_log_dir is set) into one Perfetto trace
        import glob
        import os

        events: List[Dict[str, Any]] = []
        files = sorted(glob.glob(os.path.join(args.events_dir, "timeline_*.json")))
        for f in files:
            try:
                events.extend(json.load(open(f)).get("traceEvents", []))
            except Exception as e:
                print(f"skipping {f}: {e}", file=sys.stderr)
        with open(args.out, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        print(f"merged {len(events)} events from {len(files)} session(s) "
              f"into {args.out} (open in Perfetto)")
        return 0
    import ray_tpu

    n = ray_tpu.timeline(args.out)
    if n == 0:
        print(
            "no events in this process. Task events live in the runtime "
            "process; set system_config={'event_log_dir': DIR} there (dumped "
            "on shutdown) and run: ray-tpu timeline --events-dir DIR",
            file=sys.stderr,
        )
        return 1
    print(f"wrote {n} events to {args.out} (open in Perfetto)")
    return 0


def cmd_bench(args) -> int:
    import os

    os.environ["RAY_TPU_BENCH_SUITE"] = args.suite
    sys.path.insert(0, os.getcwd())
    import bench

    bench.main()
    return 0


def cmd_health(args) -> int:
    import ray_tpu

    ray_tpu.status(address=args.address or "")
    return 0


def cmd_profile(args) -> int:
    """Stack-dump / CPU-profile any process in the cluster (profiling
    plane, util/profiler.py). `--address` reads a running head's
    dashboard over HTTP; without it the in-process runtime is used."""
    node = args.node or ""
    if node in ("head", "local", "-"):
        node = ""
    pid = int(args.pid or 0)
    duration = args.duration
    if args.address:
        from urllib.request import urlopen

        url = args.address if "://" in args.address else f"http://{args.address}"
        path = f"{url.rstrip('/')}/api/v0/profile/{node or 'head'}"
        if pid:
            path += f"/{pid}"
        q = [f"kind={args.kind}"]
        if duration is not None:
            q.append(f"duration={duration}")
        if args.hz is not None:
            q.append(f"hz={args.hz}")
        path += "?" + "&".join(q)
        with urlopen(path, timeout=(duration or 5.0) + 30.0) as r:
            out = json.loads(r.read().decode())
    else:
        import time as _time

        from . import api
        from .core import core_worker
        from .core.cross_host import HeadService

        api._auto_init()
        svc = HeadService(core_worker.get_runtime())
        if args.kind == "jax":
            out = svc.profile_start(node=node, pid=pid,
                                    duration_s=duration or 5.0, kind="jax")
        elif args.kind == "cpu":
            svc.profile_start(node=node, pid=pid, duration_s=duration or 2.0,
                              hz=args.hz, kind="cpu")
            _time.sleep(min(duration or 2.0, 60.0))
            out = svc.profile_fetch(node=node, pid=pid, kind="cpu")
        else:
            out = svc.profile_fetch(node=node, pid=pid, kind=args.kind)
    if isinstance(out.get("text"), str):
        print(out["text"])
    elif isinstance(out.get("collapsed"), dict):
        for stack, count in sorted(out["collapsed"].items(),
                                   key=lambda kv: (-kv[1], kv[0])):
            print(f"{stack} {count}")
    elif isinstance(out.get("collapsed"), str):
        print(out["collapsed"])
    else:
        print(json.dumps(out, indent=2, default=str))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray-tpu", description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("status", help="runtime or snapshot summary")
    ps.add_argument("--snapshot", help="read a control-plane snapshot file")
    ps.add_argument("--address", help="attach to a live runtime's control-plane "
                    "RPC (system_config control_plane_rpc_port)")
    ps.set_defaults(fn=cmd_status)

    pl = sub.add_parser("list", help="list nodes/actors/jobs/objects")
    pl.add_argument("what", choices=["nodes", "actors", "jobs", "objects"])
    pl.add_argument("--snapshot", help="read a control-plane snapshot file")
    pl.add_argument("--address", help="attach to a live runtime's control-plane RPC")
    pl.add_argument("--limit", type=int, default=100)
    pl.set_defaults(fn=cmd_list)

    pj = sub.add_parser("submit", help="run an entrypoint as a supervised job")
    pj.add_argument("--timeout", type=float, default=3600.0)
    pj.add_argument("entrypoint", nargs=argparse.REMAINDER,
                    help="command to run, e.g.: -- python train.py")
    pj.set_defaults(fn=cmd_submit)

    pst = sub.add_parser("start", help="long-lived session (metrics + snapshots)")
    pst.add_argument("--snapshot", help="control-plane snapshot path to write")
    pst.add_argument("--resume-from", help="snapshot to restore at boot")
    pst.add_argument("--metrics-port", type=int, default=0)
    pst.add_argument("--rpc-port", type=int, default=0,
                     help="control-plane RPC port (0 = ephemeral)")
    pst.add_argument("--serve-app", help="module:attr of a serve Application")
    pst.add_argument("--address", help="join an existing head as a WORKER "
                     "host (head's control-plane RPC host:port)")
    pst.add_argument("--num-cpus", type=float, default=None,
                     help="CPU resource to advertise (worker join)")
    pst.add_argument("--num-tpus", type=float, default=None,
                     help="TPU resource to advertise (worker join)")
    pst.add_argument("--node-host", default=None,
                     help="this host's cluster-reachable address (worker "
                     "join serves dispatch/transfer on it; default "
                     "RAY_TPU_NODE_HOST or 127.0.0.1)")
    pst.set_defaults(fn=cmd_start)

    ph = sub.add_parser("health", help="health plane: alerts, SLO digests, "
                        "node liveness (renders /api/v0/health)")
    ph.add_argument("--address", default="",
                    help="dashboard host:port of a running head (default: "
                    "in-process health plane)")
    ph.set_defaults(fn=cmd_health)

    ppf = sub.add_parser("profile", help="profiling plane: stack-dump or "
                         "CPU-profile any worker (util/profiler.py)")
    ppf.add_argument("node", nargs="?", default="",
                     help="node id hex prefix ('' / 'head' = the head node)")
    ppf.add_argument("pid", nargs="?", type=int, default=0,
                     help="target pid (0 = the node's agent process; "
                     "--kind pids lists what a node can profile)")
    ppf.add_argument("--kind", choices=["stack", "cpu", "jax", "pids"],
                     default="stack")
    ppf.add_argument("--duration", type=float, default=None,
                     help="sampling window seconds (cpu/jax kinds)")
    ppf.add_argument("--hz", type=float, default=None,
                     help="cpu sampling rate (default config profiler_sample_hz)")
    ppf.add_argument("--address", default="",
                     help="dashboard host:port of a running head (default: "
                     "in-process runtime)")
    ppf.set_defaults(fn=cmd_profile)

    pmem = sub.add_parser("memory", help="object ledger: sizes, locations, "
                          "refcounts, pin reasons, leaks")
    pmem.add_argument("--limit", type=int, default=100,
                      help="top-N objects by size")
    pmem.add_argument("--group-by", choices=["reason", "node"], default=None,
                      help="aggregate objects/bytes by pin reason or node")
    pmem.add_argument("--leaks", action="store_true",
                      help="run the leak sweep and print flagged objects")
    pmem.add_argument("--snapshot", help="read a control-plane snapshot file")
    pmem.set_defaults(fn=cmd_memory)

    plog = sub.add_parser("logs", help="list/tail/follow session logs")
    plog.add_argument("file", nargs="?", help="log file name to tail")
    plog.add_argument("-n", "--lines", type=int, default=100)
    plog.add_argument("--log-dir", help="session log dir "
                      "(default: /tmp/ray_tpu/session_latest/logs)")
    plog.add_argument("--follow", action="store_true",
                      help="stream live lines over RPC (needs --address)")
    plog.add_argument("--address", help="live session control-plane RPC address")
    plog.set_defaults(fn=cmd_logs)

    pt = sub.add_parser("timeline", help="export the task timeline (chrome trace)")
    pt.add_argument("out", nargs="?", default="timeline.json")
    pt.add_argument("--events-dir",
                    help="merge session dumps written via event_log_dir")
    pt.set_defaults(fn=cmd_timeline)

    pb = sub.add_parser("bench", help="run the driver benchmarks")
    pb.add_argument("--suite", default="train,serve,data")
    pb.set_defaults(fn=cmd_bench)

    pm = sub.add_parser("microbenchmark",
                        help="core task/actor/object-plane throughput canaries")
    pm.set_defaults(fn=cmd_microbenchmark)

    psv = sub.add_parser("serve", help="serve apps from a config or import path")
    psv_sub = psv.add_subparsers(dest="serve_cmd", required=True)
    psr = psv_sub.add_parser("run", help="deploy + serve in the foreground")
    psr.add_argument("config_or_import_path",
                     help="a serve YAML/JSON config, or module:attr")
    psr.add_argument("--http-port", type=int, default=0)
    psr.add_argument("--grpc-port", type=int, default=None,
                     help="also serve the gRPC ingress (0 = ephemeral)")
    psr.set_defaults(fn=cmd_serve_run)

    args = p.parse_args(argv)
    if hasattr(args, "entrypoint"):
        # strip a leading "--" separator
        if args.entrypoint and args.entrypoint[0] == "--":
            args.entrypoint = args.entrypoint[1:]
        if not args.entrypoint:
            p.error("submit: entrypoint required (e.g.: ray-tpu submit -- python train.py)")
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
