"""Device mesh construction and the named-axis convention.

TPU-native replacement for the reference's collective-group management
(upstream ray `python/ray/util/collective/collective.py ::
init_collective_group` + NCCL groups): on TPU there is no runtime collective
library to wrap — the *compiler* is the comm backend. What remains is mesh
and axis bookkeeping: pick a mesh shape that maps logical parallelism axes
onto the physical ICI torus, and hand everything else to pjit/XLA.

Canonical axis order (outer → inner, DCN-most → ICI-most):
    dcn_dp  data parallel ACROSS slices: the one gradient all-reduce per
            step is the only traffic that crosses DCN (multislice recipe)
    dcn_pp  pipeline stages across slices: activations cross DCN once per
            microbatch boundary — the other DCN-tolerant axis
    pp   pipeline stages (within a slice)
    dp   pure data parallel (replicated params)
    fsdp data parallel with sharded params/opt-state (ZeRO-3 equivalent)
    ep   expert parallel (MoE)
    sp   sequence/context parallel (ring attention)
    tp   tensor parallel (innermost: highest-bandwidth ICI)

Multi-slice: ``build_hybrid_mesh`` places the dcn_* axes over slice
boundaries (jax mesh_utils' hybrid mesh on real hardware, slice-major
reshape on virtual devices), so every non-dcn axis's collectives stay on
ICI inside a slice by construction.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("dcn_dp", "dcn_pp", "dcn_sp", "pp", "dp", "fsdp", "ep", "sp", "tp")
DCN_AXES = ("dcn_dp", "dcn_pp", "dcn_sp")


@dataclass(frozen=True)
class MeshSpec:
    """Named axis sizes; -1 on at most one axis means 'absorb the rest'."""

    axes: Tuple[Tuple[str, int], ...]

    @classmethod
    def create(cls, **sizes: int) -> "MeshSpec":
        unknown = set(sizes) - set(AXIS_ORDER)
        if unknown:
            raise ValueError(f"unknown mesh axes {sorted(unknown)}; use {AXIS_ORDER}")
        ordered = tuple((a, sizes[a]) for a in AXIS_ORDER if a in sizes)
        if sum(1 for _, s in ordered if s == -1) > 1:
            raise ValueError("at most one axis may be -1")
        return cls(ordered)

    def resolve(self, num_devices: int) -> "MeshSpec":
        sizes = dict(self.axes)
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if num_devices % max(fixed, 1):
            raise ValueError(
                f"{num_devices} devices not divisible by fixed axes product {fixed}"
            )
        resolved = []
        for a, s in self.axes:
            if s == -1:
                s = num_devices // fixed
            resolved.append((a, s))
        total = math.prod(s for _, s in resolved)
        if total != num_devices:
            raise ValueError(
                f"mesh spec {resolved} covers {total} devices, have {num_devices}"
            )
        return MeshSpec(tuple(resolved))

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(a for a, _ in self.axes)

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(s for _, s in self.axes)

    def size(self, axis: str, default: int = 1) -> int:
        return dict(self.axes).get(axis, default)


def build_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    **axis_sizes: int,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` over the given (default: all) devices.

    Device ordering: jax's device list already follows the TPU torus traversal
    order on real hardware, so reshaping it row-major puts the innermost mesh
    axis (tp) on torus-adjacent chips — the layout that makes tp all-reduces
    ride single-hop ICI (scaling-book recipe). For richer control,
    ``jax.experimental.mesh_utils.create_device_mesh`` is used when available.
    """
    if spec is None:
        spec = MeshSpec.create(**axis_sizes) if axis_sizes else MeshSpec.create(dp=-1)
    if devices is None:
        devices = jax.devices()
    spec = spec.resolve(len(devices))
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(spec.shape, devices=list(devices))
    except Exception:
        dev_array = np.array(list(devices)).reshape(spec.shape)
    return Mesh(dev_array, spec.names)


class MeshRegistry:
    """Process-wide named meshes (the collective-'group' registry analogue)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._meshes: Dict[str, Mesh] = {}

    def register(self, name: str, mesh: Mesh) -> None:
        with self._lock:
            self._meshes[name] = mesh

    def peek(self, name: str = "default") -> Optional[Mesh]:
        """Like get(), but never auto-builds: None when nothing registered."""
        with self._lock:
            return self._meshes.get(name)

    def get(self, name: str = "default") -> Mesh:
        with self._lock:
            mesh = self._meshes.get(name)
        if mesh is None:
            if name != "default":
                raise KeyError(f"no mesh registered under {name!r}")
            mesh = build_mesh()
            self.register("default", mesh)
        return mesh

    def clear(self) -> None:
        with self._lock:
            self._meshes.clear()


registry = MeshRegistry()


def build_hybrid_mesh(
    num_slices: int,
    devices: Optional[Sequence[jax.Device]] = None,
    **axis_sizes: int,
) -> Mesh:
    """Multi-slice mesh: dcn_* axes over slice boundaries, everything else
    within a slice (collectives on ICI by construction).

    On real multi-slice TPU hardware (devices carry slice_index), uses
    mesh_utils.create_hybrid_device_mesh with same-length shape vectors:
    mesh axis i gets its ICI extent from mesh_shape[i] and its DCN extent
    from dcn_mesh_shape[i], so the dcn_* axes (and only they) vary across
    slices. On virtual/single-slice device sets, slices are consecutive
    equal blocks of the device list — same axis semantics, testable on a
    CPU mesh.
    """
    spec = MeshSpec.create(**axis_sizes)
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if len(devices) % num_slices:
        raise ValueError(f"{len(devices)} devices not divisible into {num_slices} slices")
    per_slice = len(devices) // num_slices
    dcn = {a: s for a, s in spec.axes if a in DCN_AXES}
    dcn_total = math.prod(dcn.values()) if dcn else 1
    if dcn_total != num_slices:
        raise ValueError(
            f"dcn axes {dcn} cover {dcn_total} slices, have {num_slices}"
        )
    ici_spec = MeshSpec(
        tuple((a, s) for a, s in spec.axes if a not in DCN_AXES)
        or (("dp", -1),)
    ).resolve(per_slice)
    dcn_names = tuple(a for a in DCN_AXES if a in dcn)
    names = dcn_names + ici_spec.names
    final_shape = tuple(dcn[a] for a in dcn_names) + ici_spec.shape

    real_multislice = all(
        getattr(d, "slice_index", None) is not None for d in devices
    ) and len({getattr(d, "slice_index", 0) for d in devices}) == num_slices
    if real_multislice:
        from jax.experimental import mesh_utils

        # same-length vectors (the create_hybrid_device_mesh contract):
        # dcn axes get ICI extent 1; ici axes get DCN extent 1
        mesh_shape = (1,) * len(dcn_names) + ici_spec.shape
        dcn_mesh_shape = tuple(dcn[a] for a in dcn_names) + (1,) * len(ici_spec.shape)
        dev_array = mesh_utils.create_hybrid_device_mesh(
            mesh_shape, dcn_mesh_shape, devices=devices
        )
    else:
        # virtual devices: slice-major consecutive blocks
        dev_array = np.array(devices).reshape(final_shape)
    return Mesh(dev_array.reshape(final_shape), names)


def get_mesh(name: str = "default") -> Mesh:
    return registry.get(name)


def set_mesh(mesh: Mesh, name: str = "default") -> None:
    registry.register(name, mesh)
