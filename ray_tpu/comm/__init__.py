"""Communication: device meshes (XLA collectives over ICI) + host collectives."""

from .bootstrap import init_distributed  # noqa: F401
from .host_collectives import CollectiveGroup, KVCollectiveGroup  # noqa: F401
from .mesh import (  # noqa: F401
    AXIS_ORDER,
    MeshSpec,
    build_mesh,
    get_mesh,
    registry,
    set_mesh,
)
