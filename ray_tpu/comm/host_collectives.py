"""Host-side (control-plane) collectives: barrier, broadcast, allgather.

Equivalent of the reference's GLOO/CPU side of ray.util.collective (upstream
ray `python/ray/util/collective/collective_group/gloo_collective_group.py`):
device tensors use XLA collectives compiled into programs; *host* coordination
(gang barriers, config broadcast, rendezvous of per-host metadata) uses these
actor-backed primitives over the task runtime instead of a gloo ring.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from .. import api as _api
from ..core.config import config
from ..core.logging import get_logger

logger = get_logger("host_collectives")


class _RendezvousState:
    """Actor state for one named collective group."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.barrier_gen = 0
        self.barrier_count = 0
        self.slots: Dict[int, Dict[int, Any]] = {}  # round -> rank -> payload
        self.round = 0

    def arrive(self) -> int:
        self.barrier_count += 1
        if self.barrier_count == self.world_size:
            self.barrier_count = 0
            self.barrier_gen += 1
        return self.barrier_gen

    def generation(self) -> int:
        return self.barrier_gen

    def put(self, round_id: int, rank: int, payload: Any) -> None:
        self.slots.setdefault(round_id, {})[rank] = payload

    def gathered(self, round_id: int) -> Optional[List[Any]]:
        slot = self.slots.get(round_id, {})
        if len(slot) == self.world_size:
            return [slot[r] for r in sorted(slot)]
        return None


class CollectiveGroup:
    """Client handle: each participant constructs one with its rank."""

    def __init__(self, name: str, world_size: int, rank: int):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self._actor = self._get_or_create(name, world_size)
        self._round = 0

    @staticmethod
    def _get_or_create(name: str, world_size: int):
        actor_name = f"_collective_{name}"
        try:
            return _api.get_actor(actor_name)
        except ValueError:
            try:
                return _api.remote(_RendezvousState).options(
                    name=actor_name, num_cpus=0
                ).remote(world_size)
            except ValueError:
                return _api.get_actor(actor_name)  # lost the creation race

    def barrier(self, timeout_s: Optional[float] = None) -> None:
        if timeout_s is None:
            timeout_s = config.gang_barrier_timeout_ms / 1000.0
        target = _api.get(self._actor.generation.remote()) + 1
        _api.get(self._actor.arrive.remote())
        deadline = time.monotonic() + timeout_s
        while _api.get(self._actor.generation.remote()) < target:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"barrier timeout in group {self.name!r} (rank {self.rank})"
                )
            time.sleep(0.002)

    def allgather(self, payload: Any, timeout_s: float = 60.0) -> List[Any]:
        round_id = self._round
        self._round += 1
        _api.get(self._actor.put.remote(round_id, self.rank, payload))
        deadline = time.monotonic() + timeout_s
        while True:
            out = _api.get(self._actor.gathered.remote(round_id))
            if out is not None:
                return out
            if time.monotonic() > deadline:
                raise TimeoutError(f"allgather timeout in group {self.name!r}")
            time.sleep(0.002)

    def broadcast(self, payload: Any = None, root: int = 0, timeout_s: float = 60.0) -> Any:
        gathered = self.allgather(payload if self.rank == root else None, timeout_s)
        return gathered[root]


class KVCollectiveGroup:
    """Host collectives over the control-plane KV — works across OS
    processes and hosts (participants may hold a local ControlPlane or a
    RemoteControlPlane attached over RPC; the KV is the single authority).

    Reference analogue: gloo's store-based rendezvous
    (`gloo_collective_group.py` bootstraps via a shared KV store the same
    way). Each round writes `__collective/{group}/{round}/{rank}` and
    polls for world_size entries; rank 0 garbage-collects the previous
    round once the current one completes.

    Group names must be UNIQUE PER INCARNATION (same contract as gloo
    store prefixes): the FINAL round's keys survive until `close()` /
    `destroy()`, so a fresh group reusing a live name would read the old
    incarnation's payloads. Rank 0 should `close()` when done (or use the
    group as a context manager); `KVCollectiveGroup.destroy(cp, name)`
    scrubs a name unconditionally."""

    PREFIX = "__collective/"

    def __init__(self, control_plane, name: str, world_size: int, rank: int,
                 poll_s: float = 0.005):
        self.cp = control_plane
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.poll_s = poll_s
        self._round = 0

    def _key(self, round_id: int, rank: int) -> str:
        return f"{self.PREFIX}{self.name}/{round_id}/{rank}"

    def _prefix(self, round_id: int) -> str:
        return f"{self.PREFIX}{self.name}/{round_id}/"

    def allgather(self, payload: Any, timeout_s: float = 60.0) -> List[Any]:
        round_id = self._round
        self._round += 1
        self.cp.kv_put(self._key(round_id, self.rank), payload)
        deadline = time.monotonic() + timeout_s
        while True:
            keys = self.cp.kv_keys(self._prefix(round_id))
            if len(keys) >= self.world_size:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"allgather timeout in KV group {self.name!r} "
                    f"(rank {self.rank}, have {len(keys)}/{self.world_size})"
                )
            time.sleep(self.poll_s)
        out = [self.cp.kv_get(self._key(round_id, r))
               for r in range(self.world_size)]
        if self.rank == 0 and round_id > 0:
            # lazy GC: the previous round is complete by induction
            for r in range(self.world_size):
                self.cp.kv_del(self._key(round_id - 1, r))
        return out

    def barrier(self, timeout_s: float = 60.0) -> None:
        self.allgather(None, timeout_s)

    def broadcast(self, payload: Any = None, root: int = 0,
                  timeout_s: float = 60.0) -> Any:
        gathered = self.allgather(
            payload if self.rank == root else None, timeout_s
        )
        return gathered[root]

    def close(self) -> None:
        """Rank 0: delete the final round's keys (every earlier round was
        GC'd inductively). Other ranks: no-op — only call after all ranks
        have consumed the last round."""
        if self.rank == 0 and self._round > 0:
            for r in range(self.world_size):
                self.cp.kv_del(self._key(self._round - 1, r))

    def __enter__(self) -> "KVCollectiveGroup":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @staticmethod
    def destroy(control_plane, name: str) -> int:
        """Scrub every key a group name ever wrote (crash cleanup /
        making a name reusable). Returns the number of keys deleted."""
        n = 0
        for key in control_plane.kv_keys(f"{KVCollectiveGroup.PREFIX}{name}/"):
            if control_plane.kv_del(key):
                n += 1
        return n
