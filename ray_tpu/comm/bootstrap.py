"""Multi-host gang bootstrap.

Equivalent of the reference's process-group setup inside Train workers
(upstream ray `python/ray/train/torch/config.py ::
_setup_torch_process_group` and `ray/util/collective`'s group init): every
host of a gang must call ``jax.distributed.initialize`` with the same
coordinator before building a global mesh. The worker-group leader (host 0)
publishes its address through the control-plane KV; followers poll it.
"""

from __future__ import annotations

import os
import socket
import time
from typing import Optional

from ..core import core_worker as _cw
from ..core.logging import get_logger

logger = get_logger("bootstrap")

_COORD_KEY = "comm/coordinator/{gang}"


def _control_plane():
    """The cluster KV, from whichever runtime this process can reach: the
    head driver's, a joined worker host's remote client
    (cross_host.WorkerRuntime), or — in a dedicated actor/pool worker
    process — the head back-channel (api._pool_worker_client). Train
    workers run either in the device-owning runtime process (real TPU) or
    in per-member actor processes (ScalingConfig.workers_in_process=False),
    so the rendezvous must work from all three."""
    if _cw.runtime_initialized():
        return _cw.get_runtime().control_plane
    from .. import api

    if api._worker_runtime is not None:
        return api._worker_runtime.control_plane
    client = (
        api._pool_worker_client()
        if os.environ.get("RAY_TPU_IN_POOL_WORKER")
        else None
    )
    if client is not None:
        return client.control_plane
    raise RuntimeError(
        "no runtime in this process: gang rendezvous needs the cluster KV "
        "(head driver, a joined worker host, or a worker process with the "
        "head back-channel)"
    )


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def publish_coordinator(gang_name: str, address: Optional[str] = None) -> str:
    """Host 0 of a gang: publish the coordinator address into cluster KV."""
    cp = _control_plane()
    if address is None:
        address = f"{socket.gethostbyname(socket.gethostname())}:{free_port()}"
    cp.kv_put(_COORD_KEY.format(gang=gang_name), address.encode())
    return address


def lookup_coordinator(gang_name: str, timeout_s: float = 60.0) -> str:
    cp = _control_plane()
    deadline = time.monotonic() + timeout_s
    key = _COORD_KEY.format(gang=gang_name)
    while time.monotonic() < deadline:
        raw = cp.kv_get(key)
        if raw:
            return raw.decode()
        time.sleep(0.05)
    raise TimeoutError(f"coordinator for gang {gang_name!r} never published")


def init_distributed(
    gang_name: str,
    num_processes: int,
    process_id: int,
    coordinator_address: Optional[str] = None,
) -> None:
    """Bring this process into the gang's jax.distributed world.

    Single-process gangs (and the virtual CPU mesh used in tests) skip the
    coordination service entirely — jax already sees all devices.
    """
    if num_processes <= 1:
        logger.info("gang %s: single process, skipping jax.distributed", gang_name)
        return
    import jax

    # CPU-simulated pods (JAX_PLATFORMS=cpu, one forced host device per
    # process): jax's default cpu collectives impl is "none", which fails
    # any cross-process computation at compile time. Gloo ships in jaxlib;
    # opt in before the backend is created. Real TPU paths are untouched.
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if "cpu" in platforms.split(","):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # jax version without the flag: keep the old behavior

    if coordinator_address is None:
        if process_id == 0:
            coordinator_address = publish_coordinator(gang_name)
        else:
            coordinator_address = lookup_coordinator(gang_name)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "gang %s: process %d/%d joined via %s",
        gang_name, process_id, num_processes, coordinator_address,
    )
