"""Parallelism strategies: logical-axis sharding (DP/FSDP/TP), ring attention
(SP), expert parallelism (EP), pipeline parallelism (PP)."""

from .moe import aux_load_balance_loss, moe_layer_local, top_k_gating  # noqa: F401
from .ring import ring_attention, ring_attention_local  # noqa: F401
from .sharding import (  # noqa: F401
    DEFAULT_RULES,
    constrain,
    shard_tree,
    sharding_for,
    spec_for,
    tree_shardings,
)
from .zero import (  # noqa: F401
    flatten_tree,
    group_mean,
    leaf_sq_norms,
    partition_leaves,
    unflatten_like,
)
